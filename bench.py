"""Benchmark: all five BASELINE.md configs through the real engine.

Prints ONE JSON line. Top-level keys keep the round-1..3 north-star
contract — {"metric", "value", "unit", "vs_baseline"} for RS
encode+decode GiB/s/chip (8+4, 1MiB blocks) — plus:

  "configs":  the five BASELINE.md target configs, each measured through
              the real code path (S3 server / erasure engine / kernels):
     1. ec4+2_put_p50_ms          single 1MiB PutObject p50 via the HTTP
                                  S3 server (SigV4-signed requests)
     2. ec8+4_encode_verify_GiBs  encode + HighwayHash bitrot verify
                                  roundtrip, device codec vs host codec
     3. ec12+4_multipart_GiBs     multipart upload through the engine
                                  (batched shard encode; scaled from
                                  BASELINE's 10GiB to bound wall time,
                                  noted in "scale")
     4. ec8+4_get_2lost_GiBs      GetObject with 2 shards lost through
                                  the engine (mask-grouped TPU
                                  reconstruct); asserts the device path
                                  actually ran via batching.STATS
     5. ec16+4_heal_GiBs          full-disk heal through the engine
                                  (batched reconstruct); STATS-asserted
  "stats":    batching.STATS snapshot (device-vs-host honesty counters)
  "errors":   per-config error strings (configs that failed still leave
              the others reported; the script never exits nonzero)

Baselines are the host codec (C++ nibble-shuffle RS in native/rs.cc and
C++ HighwayHash; numpy fallback without a compiler) on this machine — a
stand-in for the Go reference's AVX2 reedsolomon (harness parity:
cmd/erasure-encode_test.go:209, erasure-decode_test.go:344,
cmd/benchmark-utils_test.go).

Timing note: the TPU is reached through a relay with ~80ms fixed RPC
latency, so kernel-level numbers use steady-state marginal cost
(pipelined N1/N2 dispatches); engine-level numbers are wall-clock
end-to-end, which is what an operator sees.
"""

from __future__ import annotations

import json
import os
import shutil
import statistics
import sys
import tempfile
import time


def _progress(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.monotonic()


def _retrying(fn, what: str, attempts: int = 4, base_sleep: float = 2.0):
    """Run fn with exponential backoff. Returns (value, None) or
    (None, error-string) — bench configs degrade, they never abort."""
    last = None
    for i in range(attempts):
        try:
            return fn(), None
        except Exception as exc:  # noqa: BLE001 - report, don't die
            last = f"{what}: {type(exc).__name__}: {exc}"
            if i < attempts - 1:
                time.sleep(base_sleep * (2 ** i))
    return None, last


def _pipelined_seconds_per_iter(launch, sync, n1: int = 4, n2: int = 20,
                                ) -> float:
    def run(n: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = launch()
        sync(out)
        return time.perf_counter() - t0

    run(2)  # warm
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(n2) for _ in range(2))
    return max(t2 - t1, 1e-9) / (n2 - n1)


# --- north star: kernel encode+decode marginal throughput --------------------


def bench_kernel_north_star(np, jnp, rs_tpu, device: bool = True,
                            ) -> tuple[float, float]:
    """(tpu_gibs, cpu_gibs) for the 8+4/1MiB encode+decode roundtrip —
    same measurement as rounds 1-3 for cross-round comparability."""
    k, m = 8, 4
    S = (1024 * 1024) // k
    batch = 64 if device else 8  # XLA-CPU fallback: bound wall time

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (batch, k, S)).astype(np.uint8)

    big_enc = jnp.asarray(rs_tpu.parity_bitplane(k, m))
    missing = (0, 5)
    available = tuple(i for i in range(k + m) if i not in missing)
    big_dec_np, used = rs_tpu.decode_bitplane(k, m, available, missing)
    big_dec = jnp.asarray(big_dec_np)

    data_dev = jnp.asarray(data)
    shards = rs_tpu.encode_blocks(big_enc, data_dev)
    survivors = jnp.take(shards, jnp.asarray(used, dtype=jnp.int32), axis=-2)

    def launch():
        s = rs_tpu.encode_blocks(big_enc, data_dev)
        r = rs_tpu.gf_apply(big_dec, survivors)
        return s, r

    def sync(out):
        s, r = out
        np.asarray(s[0, k, 0])
        np.asarray(r[0, 0, 0])

    if device:
        t_iter = _pipelined_seconds_per_iter(launch, sync)
    else:
        t_iter = _pipelined_seconds_per_iter(launch, sync, n1=1, n2=3)
    tpu_gibs = (batch * k * S) / t_iter / (1 << 30)

    # CPU baseline: the PRODUCTION host path — C++ nibble-shuffle kernel
    # (native/rs.cc) when built, numpy table-gather otherwise — the
    # honest stand-in for the reference's AVX2 reedsolomon.
    from minio_tpu.ops import batching as _batching
    from minio_tpu.ops.rs_matrix import decode_matrix, parity_matrix
    pm = parity_matrix(k, m)
    dec_full, _ = decode_matrix(k, m, list(available))
    dec_miss = dec_full[list(missing), :]
    cpu_batch = max(1, batch // 16)
    cpu_data = data[:cpu_batch]
    cpu_survivors = np.asarray(survivors[:cpu_batch])

    def cpu_roundtrip():
        for b in range(cpu_batch):
            _batching.host_apply(pm, cpu_data[b])
            _batching.host_apply(dec_miss, cpu_survivors[b])

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_roundtrip()
        times.append(time.perf_counter() - t0)
    cpu_gibs = (cpu_batch * k * S) / min(times) / (1 << 30)
    return tpu_gibs, cpu_gibs


# --- config 1: 4+2 single PutObject p50 through the S3 server ----------------


def bench_put_p50(np, workdir: str) -> dict:
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage

    access, secret = "benchadmin", "benchadmin-secret"
    root = os.path.join(workdir, "cfg1")
    disks = [XLStorage(os.path.join(root, f"disk{i}")) for i in range(6)]
    layer = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
    srv = S3Server(layer, access, secret)
    port = srv.start()
    try:
        client = S3Client("127.0.0.1", port, access, secret)
        client.make_bucket("bench")
        rng = np.random.default_rng(1)
        body = rng.integers(0, 256, 1024 * 1024).astype(np.uint8).tobytes()
        # warm (compile/caches/first-touch disk dirs)
        for i in range(3):
            client.put_object("bench", f"warm-{i}", body)
        lat = []
        for i in range(30):
            t0 = time.perf_counter()
            r = client.put_object("bench", f"obj-{i}", body)
            lat.append(time.perf_counter() - t0)
            if r.status != 200:
                raise RuntimeError(f"PutObject failed: {r.status}")
        p50_ms = statistics.median(lat) * 1e3
        return {"metric": "ec4+2_put_p50", "value": round(p50_ms, 3),
                "unit": "ms", "objects": 30, "object_bytes": len(body)}
    finally:
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)


# --- config 2: 8+4 encode + HighwayHash bitrot verify roundtrip --------------


def bench_encode_verify(np, device: bool) -> dict:
    from minio_tpu.erasure import bitrot
    from minio_tpu.erasure.codec import Erasure

    k, m = 8, 4
    S = (1024 * 1024) // k          # 1MiB stripe -> 128KiB shards
    batch = 32                       # 32 MiB of data per dispatch
    shard_chunk = S                  # one bitrot sub-block per shard
    rng = np.random.default_rng(2)
    blocks = rng.integers(0, 256, (batch, k, S)).astype(np.uint8)

    def roundtrip(backend: str) -> float:
        codec = Erasure(k, m, block_size=1024 * 1024, backend=backend)
        t0 = time.perf_counter()
        encoded = codec.encode_blocks_batch(blocks)
        # Bitrot-hash every shard of every block; one batched (device-
        # eligible) dispatch for the whole set (erasure/bitrot.py).
        streams = [encoded[b, s].tobytes() for b in range(batch)
                   for s in range(k + m)]
        if backend == "cpu":
            # Pin the hash to the host for the baseline measurement.
            for st in streams:
                if not bitrot.digest_chunks(bitrot.DEFAULT_ALGORITHM, st,
                                            shard_chunk):
                    raise RuntimeError("empty bitrot digest")
        else:
            hs = bitrot.digest_chunks_many(bitrot.DEFAULT_ALGORITHM,
                                           streams, shard_chunk)
            if len(hs) != len(streams):
                raise RuntimeError("bitrot digest count mismatch")
        return time.perf_counter() - t0

    from minio_tpu.ops import batching
    backend = "tpu" if device else "cpu"
    roundtrip(backend)  # warm
    before = batching.HH_STATS.snapshot()
    t_dev = min(roundtrip(backend) for _ in range(3))
    hh_tpu = (batching.HH_STATS.snapshot()["tpu_dispatches"]
              - before["tpu_dispatches"])
    t_cpu = min(roundtrip("cpu") for _ in range(2))
    gibs = (batch * k * S) / t_dev / (1 << 30)
    cpu_gibs = (batch * k * S) / t_cpu / (1 << 30)
    return {"metric": "ec8+4_encode_verify", "value": round(gibs, 3),
            "unit": "GiB/s", "vs_baseline": round(gibs / cpu_gibs, 2),
            "device": device, "hh_tpu_dispatches": hh_tpu}


# --- config 3: 12+4 multipart upload through the engine ----------------------


def bench_multipart(np, workdir: str) -> dict:
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.storage.xl import XLStorage

    root = os.path.join(workdir, "cfg3")
    disks = [XLStorage(os.path.join(root, f"disk{i}")) for i in range(16)]
    eng = ErasureObjects(disks, 12, 4, block_size=1024 * 1024)
    eng.make_bucket("bench")
    part_bytes = 32 * 1024 * 1024
    n_parts = 8                      # 256 MiB total (scaled from 10GiB)
    rng = np.random.default_rng(3)
    part = rng.integers(0, 256, part_bytes).astype(np.uint8).tobytes()
    try:
        # warm: single-part upload compiles the encode shapes
        eng.put_object("bench", "warm", part)
        up = eng.multipart.new_multipart_upload("bench", "big")
        t0 = time.perf_counter()
        etags = []
        for p in range(1, n_parts + 1):
            info = eng.multipart.put_object_part("bench", "big", up, p, part)
            etags.append((p, info["etag"]))
        eng.multipart.complete_multipart_upload("bench", "big", up, etags)
        dt = time.perf_counter() - t0
        total = n_parts * part_bytes
        return {"metric": "ec12+4_multipart_encode",
                "value": round(total / dt / (1 << 30), 3), "unit": "GiB/s",
                "total_bytes": total,
                "scale": "256MiB stand-in for BASELINE's 10GiB (wall-time bound)"}
    finally:
        shutil.rmtree(root, ignore_errors=True)


# --- config 4: 8+4 GetObject with 2 shards lost ------------------------------


def bench_get_with_loss(np, workdir: str, device: bool = False) -> dict:
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.ops import batching
    from minio_tpu.storage.xl import XLStorage

    root = os.path.join(workdir, "cfg4")
    roots = [os.path.join(root, f"disk{i}") for i in range(12)]
    disks = [XLStorage(r) for r in roots]
    eng = ErasureObjects(disks, 8, 4, block_size=1024 * 1024)
    eng.make_bucket("bench")
    size = 64 * 1024 * 1024
    rng = np.random.default_rng(4)
    body = rng.integers(0, 256, size).astype(np.uint8).tobytes()
    try:
        eng.put_object("bench", "obj", body)
        # Lose 2 shards: wipe the object's data on two disks.
        for r in roots[:2]:
            shutil.rmtree(os.path.join(r, "bench", "obj"),
                          ignore_errors=True)
        eng.get_object("bench", "obj")  # warm (compile reconstruct shapes)
        before = batching.STATS.snapshot()
        t0 = time.perf_counter()
        got, _info = eng.get_object("bench", "obj")
        dt = time.perf_counter() - t0
        after = batching.STATS.snapshot()
        if got != body:
            raise RuntimeError("reconstructed object bytes differ")
        tpu_delta = after["tpu_dispatches"] - before["tpu_dispatches"]
        if device and tpu_delta == 0:
            raise RuntimeError(
                "device present but GET reconstruct never dispatched to "
                "it (honesty check)")
        return {"metric": "ec8+4_get_2lost",
                "value": round(size / dt / (1 << 30), 3), "unit": "GiB/s",
                "object_bytes": size,
                "tpu_dispatches": after["tpu_dispatches"]
                - before["tpu_dispatches"]}
    finally:
        shutil.rmtree(root, ignore_errors=True)


# --- config 5: 16+4 full-disk heal -------------------------------------------


def bench_heal(np, workdir: str, device: bool = False) -> dict:
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.ops import batching
    from minio_tpu.storage.xl import XLStorage

    root = os.path.join(workdir, "cfg5")
    roots = [os.path.join(root, f"disk{i}") for i in range(20)]
    disks = [XLStorage(r) for r in roots]
    eng = ErasureObjects(disks, 16, 4, block_size=1024 * 1024)
    eng.make_bucket("bench")
    n_objects, obj_bytes = 24, 8 * 1024 * 1024  # 192 MiB (scaled from
    rng = np.random.default_rng(5)              # 1000x64MiB; wall-time bound)
    try:
        for i in range(n_objects):
            body = rng.integers(0, 256, obj_bytes).astype(np.uint8)
            eng.put_object("bench", f"obj-{i}", body.tobytes())
        # Wipe one disk wholesale (full-disk loss), keep format metadata
        # dirs intact enough for rejoin by recreating the root.
        shutil.rmtree(roots[0])
        os.makedirs(roots[0], exist_ok=True)
        before = batching.STATS.snapshot()
        t0 = time.perf_counter()
        results = eng.healer.heal_disk(0)
        dt = time.perf_counter() - t0
        after = batching.STATS.snapshot()
        healed = sum(1 for r in results if r.healed_disks)
        if healed == 0:
            raise RuntimeError("heal_disk healed nothing")
        tpu_delta = after["tpu_dispatches"] - before["tpu_dispatches"]
        if device and tpu_delta == 0:
            raise RuntimeError(
                "device present but heal reconstruct never dispatched to "
                "it (honesty check)")
        total = n_objects * obj_bytes
        return {"metric": "ec16+4_heal",
                "value": round(total / dt / (1 << 30), 3), "unit": "GiB/s",
                "objects_healed": healed, "total_bytes": total,
                "scale": "24x8MiB stand-in for BASELINE's 1000x64MiB",
                "tpu_dispatches": after["tpu_dispatches"]
                - before["tpu_dispatches"]}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main() -> None:
    import numpy as np

    errors: dict[str, str] = {}

    # Persistent compilation cache: the relay makes each distinct jit
    # shape cost tens of seconds to compile; cache across runs.
    import jax
    try:
        cache_dir = os.environ.get(
            "MINIO_TPU_JIT_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "minio_tpu_jit"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    # Device bring-up. The relay can hang indefinitely (not just fail),
    # so probe it in a SUBPROCESS with a hard timeout — an in-process
    # jax.devices() that never returns would kill the whole bench (it
    # did, twice, in round 4). A definitive "no device" answer is not
    # retried; only hangs/crashes get a second attempt.
    import subprocess
    probe = ("import jax; import jax.numpy as jnp; "
             "assert any(d.platform != 'cpu' for d in jax.devices()), "
             "'no accelerator'; "
             "jnp.zeros((8,128), jnp.bfloat16).block_until_ready()")
    err = None
    device = False
    for attempt in range(2):
        _progress(f"probing device (attempt {attempt + 1})")
        try:
            r = subprocess.run([sys.executable, "-c", probe],
                               capture_output=True, timeout=150,
                               text=True)
            if r.returncode == 0:
                device = True
                err = None
                break
            err = f"device-probe: rc={r.returncode}: {r.stderr[-300:]}"
            if "no accelerator" in (r.stderr or ""):
                break  # deterministic: don't retry
        except subprocess.TimeoutExpired:
            err = "device-probe: hung >150s (relay unreachable)"
        time.sleep(5 * (attempt + 1))
    if device:
        import jax.numpy as jnp
    else:
        # Pin to CPU so in-process jax can never hang on the relay.
        jax.config.update("jax_platforms", "cpu")
        jnp = None
    if err:
        errors["device"] = err
    _progress(f"device init done (ok={device})")

    out: dict = {"metric": "rs_encode+decode_8+4_1MiB_GiB_per_s_per_chip",
                 "value": 0.0, "unit": "GiB/s", "vs_baseline": 0.0,
                 "baseline": "host codec (C++ nibble-shuffle native/rs.cc "
                             "when built; stand-in for the reference's "
                             "AVX2 reedsolomon)"}

    # North star (kernel marginal throughput, comparable to r01-r03).
    _progress("north star kernel bench")
    try:
        from minio_tpu.ops import rs_tpu
        if device:
            tpu_gibs, cpu_gibs = bench_kernel_north_star(np, jnp, rs_tpu)
            out["value"] = round(tpu_gibs, 3)
            out["vs_baseline"] = round(tpu_gibs / cpu_gibs, 2)
            # Which device implementation actually ran (honesty field):
            # the Pallas packed-GF kernel, or the XLA bit-plane fallback.
            # _pallas_enabled folds in the mesh and env-override gates.
            out["kernel"] = ("pallas" if rs_tpu._pallas_enabled()
                             else "xla")
        else:
            # Host-only fallback: report CPU numbers, flagged as degraded.
            import jax.numpy as jnp_cpu
            tpu_gibs, cpu_gibs = bench_kernel_north_star(
                np, jnp_cpu, rs_tpu, device=False)
            out["value"] = round(tpu_gibs, 3)
            out["vs_baseline"] = round(tpu_gibs / max(cpu_gibs, 1e-9), 2)
            errors.setdefault("north_star",
                              "no device; values are host XLA-CPU")
    except Exception as exc:  # noqa: BLE001
        errors["north_star"] = f"{type(exc).__name__}: {exc}"

    workdir = tempfile.mkdtemp(prefix="minio-tpu-bench-")
    configs: list[dict] = []
    for name, fn in (("put_p50", lambda: bench_put_p50(np, workdir)),
                     ("encode_verify",
                      lambda: bench_encode_verify(np, device)),
                     ("multipart", lambda: bench_multipart(np, workdir)),
                     ("get_2lost",
                      lambda: bench_get_with_loss(np, workdir, device)),
                     ("heal", lambda: bench_heal(np, workdir, device))):
        _progress(f"config {name}")
        res, err = _retrying(fn, name, attempts=2, base_sleep=1.0)
        if res is not None:
            configs.append(res)
        else:
            errors[name] = err or "unknown"
    shutil.rmtree(workdir, ignore_errors=True)

    from minio_tpu.ops import batching
    out["configs"] = configs
    out["stats"] = batching.STATS.snapshot()
    if errors:
        out["errors"] = errors
    print(json.dumps(out))


if __name__ == "__main__":
    main()
