"""Benchmark: RS encode+decode GiB/s/chip (8+4, 1MiB blocks) on TPU vs CPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N}

value       = sustained TPU throughput of the north-star config (EC 8+4,
              1MiB stripe blocks): bytes of source data erasure-encoded AND
              reconstructed (2-missing-shard decode) per second.
baseline    = same ops with the vectorized CPU (numpy table-gather) codec on
              this host — stand-in for the Go reference's AVX2 reedsolomon
              (harness parity: cmd/erasure-encode_test.go:209,
              erasure-decode_test.go:344).

Timing note: this TPU is reached through a relay with ~80ms fixed RPC
latency, so we measure steady-state marginal cost: pipeline N1 and N2
dispatches with one final readback sync each and use (t2-t1)/(N2-N1) —
exactly the regime the object-store data plane runs in (batched coalesced
blocks, SURVEY §7).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _pipelined_seconds_per_iter(launch, sync, n1: int = 4, n2: int = 20,
                                ) -> float:
    def run(n: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = launch()
        sync(out)
        return time.perf_counter() - t0

    run(2)  # warm
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(n2) for _ in range(2))
    return max(t2 - t1, 1e-9) / (n2 - n1)


def main() -> None:
    import jax.numpy as jnp

    from minio_tpu.ops import rs_tpu

    k, m = 8, 4
    block = 1024 * 1024           # 1 MiB stripe blocks (north-star config)
    S = block // k                # 128 KiB shards
    batch = 64                    # 64 MiB of data per dispatch

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (batch, k, S)).astype(np.uint8)

    # --- TPU path ---
    big_enc = jnp.asarray(rs_tpu.parity_bitplane(k, m))
    missing = (0, 5)
    available = tuple(i for i in range(k + m) if i not in missing)
    big_dec_np, used = rs_tpu.decode_bitplane(k, m, available, missing)
    big_dec = jnp.asarray(big_dec_np)

    data_dev = jnp.asarray(data)
    shards = rs_tpu.encode_blocks(big_enc, data_dev)
    survivors = jnp.take(shards, jnp.asarray(used, dtype=jnp.int32), axis=-2)

    def launch():
        s = rs_tpu.encode_blocks(big_enc, data_dev)
        r = rs_tpu.gf_apply(big_dec, survivors)
        return s, r

    def sync(out):
        s, r = out
        np.asarray(s[0, k, 0])  # device->host readback forces completion
        np.asarray(r[0, 0, 0])

    t_iter = _pipelined_seconds_per_iter(launch, sync)
    tpu_gibs = (batch * k * S) / t_iter / (1 << 30)

    # --- CPU baseline (numpy table-gather codec, same semantics) ---
    from minio_tpu.ops.gf256 import gf_mat_vec_apply
    from minio_tpu.ops.rs_matrix import decode_matrix, parity_matrix
    pm = parity_matrix(k, m)
    dec_full, _ = decode_matrix(k, m, list(available))
    dec_miss = dec_full[list(missing), :]
    cpu_batch = max(1, batch // 16)  # keep CPU wall time sane
    cpu_data = data[:cpu_batch]
    cpu_survivors = np.asarray(survivors[:cpu_batch])

    def cpu_roundtrip():
        for b in range(cpu_batch):
            gf_mat_vec_apply(pm, cpu_data[b])
            gf_mat_vec_apply(dec_miss, cpu_survivors[b])

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_roundtrip()
        times.append(time.perf_counter() - t0)
    cpu_gibs = (cpu_batch * k * S) / min(times) / (1 << 30)

    print(json.dumps({
        "metric": "rs_encode+decode_8+4_1MiB_GiB_per_s_per_chip",
        "value": round(tpu_gibs, 3),
        "unit": "GiB/s",
        "vs_baseline": round(tpu_gibs / cpu_gibs, 2),
    }))


if __name__ == "__main__":
    main()
