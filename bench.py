"""Benchmark: all five BASELINE.md configs through the real engine.

Prints ONE JSON line. Top-level keys keep the round-1..3 north-star
contract — {"metric", "value", "unit", "vs_baseline"} for RS
encode+decode GiB/s/chip (8+4, 1MiB blocks) — plus:

  "configs":  the five BASELINE.md target configs, each measured through
              the real code path (S3 server / erasure engine / kernels):
     1. ec4+2_put_p50_ms          single 1MiB PutObject p50 via the HTTP
                                  S3 server (SigV4-signed requests)
     2. ec8+4_encode_verify_GiBs  encode + HighwayHash bitrot verify
                                  roundtrip, device codec vs host codec
     3. ec12+4_multipart_GiBs     multipart upload through the engine
                                  (batched shard encode; scaled from
                                  BASELINE's 10GiB to bound wall time,
                                  noted in "scale")
     4. ec8+4_get_2lost_GiBs      GetObject with 2 shards lost through
                                  the engine (mask-grouped TPU
                                  reconstruct); asserts the device path
                                  actually ran via batching.STATS
     5. ec16+4_heal_GiBs          full-disk heal through the engine
                                  (batched reconstruct); STATS-asserted
     6. qos_brownout              loadgen at ~4x the write cap: shed
                                  rate + admitted p50/p99, and fg PUT
                                  p50 with/without a concurrent heal
                                  sweep (priority-lane interference)
     7. hot_get                   Zipfian GETs, hot-object cache on vs
                                  off (paired off/on/off): GET QPS
                                  speedup, hit ratio, coalesced fills,
                                  p99, cache-off consult overhead
     8. noisy_neighbor            one Zipf-hot tenant amid uniform
                                  background through the multi-tenant
                                  loadgen: admin /top ranks the hot
                                  bucket, the noisy_neighbor watchdog
                                  rule fires naming it and resolves,
                                  paired usage-on/off PUT p50 <= 2%
  "stats":    batching.STATS snapshot (device-vs-host honesty counters)
  "errors":   per-config error strings (configs that failed still leave
              the others reported; the script never exits nonzero)

Baselines are the host codec (C++ nibble-shuffle RS in native/rs.cc and
C++ HighwayHash; numpy fallback without a compiler) on this machine — a
stand-in for the Go reference's AVX2 reedsolomon (harness parity:
cmd/erasure-encode_test.go:209, erasure-decode_test.go:344,
cmd/benchmark-utils_test.go).

Device acquisition (round-5 rework): the main process is pinned to CPU
and can never hang on the TPU relay. A background hunt thread probes the
relay for the whole run (subprocess probes with hard timeouts) and runs
tools/device_bench.py the moment a device answers; its result becomes
the headline value ("value_source": "device-live"). When the relay is
down for the entire run, the bench falls back to the best device-backed
result the round-long watcher (tools/device_watch.py) ever persisted
("device-persisted"), and failing that reports the engine's REAL host
fallback — the native C++ codec, not jit-on-CPU ("host-native"). Every
config carries "device_asserted" so a green bench can never quietly
mean host-only.

Timing note: the TPU is reached through a relay with ~80ms fixed RPC
latency, so kernel-level numbers use steady-state marginal cost
(pipelined N1/N2 dispatches); engine-level numbers are wall-clock
end-to-end, which is what an operator sees.
"""

from __future__ import annotations

import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time


def _progress(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.monotonic()


def _retrying(fn, what: str, attempts: int = 4, base_sleep: float = 2.0):
    """Run fn with exponential backoff. Returns (value, None) or
    (None, error-string) — bench configs degrade, they never abort."""
    last = None
    for i in range(attempts):
        try:
            return fn(), None
        except Exception as exc:  # noqa: BLE001 - report, don't die
            last = f"{what}: {type(exc).__name__}: {exc}"
            if i < attempts - 1:
                time.sleep(base_sleep * (2 ** i))
    return None, last


def _backend_mix(before: dict, after: dict) -> dict:
    """Fractions of kernel-dispatched BYTES per kernprof backend over
    a [before, after) mix_snapshot window (dispatch-count fractions
    when no bytes moved). This is the stamp that keeps a host-mode
    bench from masquerading as a device number."""
    deltas = {}
    for b, cur in after.items():
        prev = before.get(b, {})
        deltas[b] = {k: cur.get(k, 0) - prev.get(k, 0)
                     for k in ("bytes", "dispatches")}
    basis = "bytes" if any(d["bytes"] for d in deltas.values()) \
        else "dispatches"
    total = sum(d[basis] for d in deltas.values())
    if total <= 0:
        return {}
    return {b: round(d[basis] / total, 4)
            for b, d in sorted(deltas.items()) if d[basis]}


def _pipelined_seconds_per_iter(launch, sync, n1: int = 4, n2: int = 20,
                                ) -> float:
    def run(n: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = launch()
        sync(out)
        return time.perf_counter() - t0

    run(2)  # warm
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(n2) for _ in range(2))
    return max(t2 - t1, 1e-9) / (n2 - n1)


# --- north star: kernel encode+decode marginal throughput --------------------


def bench_kernel_north_star(np, jnp, rs_tpu, device: bool = True,
                            ) -> tuple[float, float]:
    """(tpu_gibs, cpu_gibs) for the 8+4/1MiB encode+decode roundtrip —
    same measurement as rounds 1-3 for cross-round comparability."""
    k, m = 8, 4
    S = (1024 * 1024) // k
    batch = 64 if device else 8  # XLA-CPU fallback: bound wall time

    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (batch, k, S)).astype(np.uint8)

    big_enc = jnp.asarray(rs_tpu.parity_bitplane(k, m))
    missing = (0, 5)
    available = tuple(i for i in range(k + m) if i not in missing)
    big_dec_np, used = rs_tpu.decode_bitplane(k, m, available, missing)
    big_dec = jnp.asarray(big_dec_np)

    data_dev = jnp.asarray(data)
    shards = rs_tpu.encode_blocks(big_enc, data_dev)
    survivors = jnp.take(shards, jnp.asarray(used, dtype=jnp.int32), axis=-2)

    def launch():
        s = rs_tpu.encode_blocks(big_enc, data_dev)
        r = rs_tpu.gf_apply(big_dec, survivors)
        return s, r

    def sync(out):
        s, r = out
        np.asarray(s[0, k, 0])
        np.asarray(r[0, 0, 0])

    if device:
        t_iter = _pipelined_seconds_per_iter(launch, sync)
    else:
        t_iter = _pipelined_seconds_per_iter(launch, sync, n1=1, n2=3)
    tpu_gibs = (batch * k * S) / t_iter / (1 << 30)

    # CPU baseline: the PRODUCTION host path — C++ nibble-shuffle kernel
    # (native/rs.cc) when built, numpy table-gather otherwise — the
    # honest stand-in for the reference's AVX2 reedsolomon.
    from minio_tpu.ops import batching as _batching
    from minio_tpu.ops.rs_matrix import decode_matrix, parity_matrix
    pm = parity_matrix(k, m)
    dec_full, _ = decode_matrix(k, m, list(available))
    dec_miss = dec_full[list(missing), :]
    cpu_batch = max(1, batch // 16)
    cpu_data = data[:cpu_batch]
    cpu_survivors = np.asarray(survivors[:cpu_batch])

    def cpu_roundtrip():
        for b in range(cpu_batch):
            _batching.host_apply(pm, cpu_data[b])
            _batching.host_apply(dec_miss, cpu_survivors[b])

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        cpu_roundtrip()
        times.append(time.perf_counter() - t0)
    cpu_gibs = (cpu_batch * k * S) / min(times) / (1 << 30)
    return tpu_gibs, cpu_gibs


def bench_host_native_north_star(np) -> float:
    """The engine's REAL degraded-mode number: the 8+4/1MiB roundtrip
    through the same folded host applies the serving path uses when no
    device is reachable (batching.host_encode / _host_reconstruct over
    the C++ nibble-shuffle kernel). Round-4 verdict weak #2: reporting
    jit-on-CPU here (0.016 GiB/s) was misleading — the engine never
    falls back to XLA-CPU, it falls back to native/rs.cc."""
    from minio_tpu.ops import batching
    from minio_tpu.ops.rs_matrix import decode_matrix

    k, m = 8, 4
    S = (1024 * 1024) // k
    batch = 16
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (batch, k, S)).astype(np.uint8)

    missing = (0, 5)
    available = [i for i in range(k + m) if i not in missing]
    dec_full, used = decode_matrix(k, m, available)
    dec_miss = np.ascontiguousarray(dec_full[list(missing), :])

    encoded = batching.host_encode(data, k, m)
    survivors = np.ascontiguousarray(encoded[:, used, :])

    def roundtrip():
        enc = batching.host_encode(data, k, m)
        rec = batching._host_reconstruct(survivors, dec_miss)
        return enc, rec

    roundtrip()  # warm (native lib build, first-touch)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        roundtrip()
        times.append(time.perf_counter() - t0)
    return (batch * k * S) / min(times) / (1 << 30)


# --- config 1: 4+2 single PutObject p50 through the S3 server ----------------


def bench_put_p50(np, workdir: str) -> dict:
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage
    from minio_tpu.utils.phasetimer import PUT

    access, secret = "benchadmin", "benchadmin-secret"
    # tmpfs when available: this config tracks the serving path's CPU
    # cost; the VM's disk writeback throttling swings 2-12ms run to
    # run and would drown the signal (labeled in "workdir").
    base = workdir
    if os.path.isdir("/dev/shm"):
        base = tempfile.mkdtemp(prefix="minio-tpu-p50-", dir="/dev/shm")
    root = os.path.join(base, "cfg1")
    disks = [XLStorage(os.path.join(root, f"disk{i}")) for i in range(6)]
    layer = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
    srv = S3Server(layer, access, secret)
    port = srv.start()
    try:
        client = S3Client("127.0.0.1", port, access, secret)
        client.make_bucket("bench")
        rng = np.random.default_rng(1)
        body = rng.integers(0, 256, 1024 * 1024).astype(np.uint8).tobytes()
        # warm (compile/caches/first-touch disk dirs)
        for i in range(5):
            client.put_object("bench", f"warm-{i}", body)
        PUT.reset()

        # Acceptance: drivemon+slowlog recording overhead on this path
        # must measure <= 2%. This VM's throughput drifts +/-20% on
        # second timescales, so pool-median A/B aliases drift into the
        # comparison; instead each recording-ON PUT is PAIRED with the
        # immediately-following recording-OFF PUT (toggling is two
        # attribute writes) and the overhead is the median of the
        # per-pair deltas — drift moves both halves of a pair
        # together, the systematic recording cost survives.
        from minio_tpu.obs.drivemon import DRIVEMON
        from minio_tpu.obs.slowlog import SLOWLOG
        from minio_tpu.obs.watchdog import WATCHDOG
        lat_on: list = []
        lat_off: list = []
        try:
            for i in range(80):
                # Alternate which half leads: a fixed on-first order
                # would alias any position-within-pair effect (post-
                # pair stalls, allocator periodicity) into the delta.
                order = (True, False) if i % 2 == 0 else (False, True)
                for on in order:
                    # The watchdog toggles with the other recorders:
                    # its only request-path cost is the 5xx class
                    # counter, but the paired measurement should cover
                    # the whole PR-9 layer (sampler-tick evaluation
                    # steals CPU on a 2-core box).
                    DRIVEMON.enabled = SLOWLOG.enabled = on
                    WATCHDOG.enabled = on
                    t0 = time.perf_counter()
                    r = client.put_object(
                        "bench", f"obj-{i}-{int(on)}", body)
                    (lat_on if on else lat_off).append(
                        time.perf_counter() - t0)
                    if r.status != 200:
                        raise RuntimeError(
                            f"PutObject failed: {r.status}")
        finally:
            DRIVEMON.enabled = SLOWLOG.enabled = True
            WATCHDOG.enabled = True
        p50_ms = statistics.median(lat_on) * 1e3
        p50_off_ms = statistics.median(lat_off) * 1e3
        med_delta_ms = statistics.median(
            [(a - b) * 1e3 for a, b in zip(lat_on, lat_off)])
        overhead_pct = med_delta_ms / max(p50_off_ms, 1e-9) * 100.0
        return {"metric": "ec4+2_put_p50", "value": round(p50_ms, 3),
                "unit": "ms", "objects": len(lat_on),
                "object_bytes": len(body),
                "workdir": "tmpfs" if base != workdir else "disk",
                # Drive-health + slowlog recording cost on the hot
                # path (acceptance bar: <= 2%; sub-ms medians make
                # small negatives normal measurement noise).
                "put_p50_no_obs_ms": round(p50_off_ms, 3),
                "obs_overhead_pct": round(overhead_pct, 2),
                # Round-4 verdict weak #3: publish where the ms go.
                "phase_p50_ms": {k: v["p50_ms"] for k, v in
                                 sorted(PUT.snapshot().items())}}
    finally:
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)
        if base != workdir:
            shutil.rmtree(base, ignore_errors=True)


# --- config 2: 8+4 encode + HighwayHash bitrot verify roundtrip --------------


def bench_encode_verify(np, device: bool) -> dict:
    from minio_tpu.erasure import bitrot
    from minio_tpu.erasure.codec import Erasure

    k, m = 8, 4
    S = (1024 * 1024) // k          # 1MiB stripe -> 128KiB shards
    batch = 32                       # 32 MiB of data per dispatch
    rng = np.random.default_rng(2)
    blocks = rng.integers(0, 256, (batch, k, S)).astype(np.uint8)

    def roundtrip(backend: str) -> float:
        """The engine's real write pipeline for one batch: shard-major
        encode + streaming-bitrot framing (what _encode_batch runs),
        not a hand-rolled encode+digest loop."""
        codec = Erasure(k, m, block_size=1024 * 1024, backend=backend)
        t0 = time.perf_counter()
        sm = codec.encode_blocks_batch_shardmajor(blocks)
        frames = bitrot.encode_stream_arrays(list(sm))
        if len(frames) != k + m:
            raise RuntimeError("bitrot frame count mismatch")
        return time.perf_counter() - t0

    from minio_tpu.ops import batching
    backend = "tpu" if device else "cpu"
    roundtrip(backend)  # warm
    before = batching.HH_STATS.snapshot()
    t_dev = min(roundtrip(backend) for _ in range(3))
    hh_tpu = (batching.HH_STATS.snapshot()["tpu_dispatches"]
              - before["tpu_dispatches"])
    t_cpu = min(roundtrip("cpu") for _ in range(2))
    gibs = (batch * k * S) / t_dev / (1 << 30)
    cpu_gibs = (batch * k * S) / t_cpu / (1 << 30)
    return {"metric": "ec8+4_encode_verify", "value": round(gibs, 3),
            "unit": "GiB/s", "vs_baseline": round(gibs / cpu_gibs, 2),
            "device": device, "hh_tpu_dispatches": hh_tpu}


# --- config: codec autotuner — paired tuned-vs-untuned dispatch --------------


def bench_codec_autotune(np) -> dict:
    """Measured-plan dispatch vs the legacy static device-first policy,
    PAIRED per batch-size bucket (alternating order, like put_p50's
    overhead pairs — this VM drifts +/-20% on second timescales, so
    only the within-pair delta is trustworthy).  Stamps the probe
    ladder's full crossover table and the converged plan; the
    acceptance bar is tuned >= untuned within noise on every bucket —
    on a no-device box both policies should converge on host-native
    (BENCH_r04/r05's lesson), so the deltas measure planner overhead,
    not lane wins."""
    from minio_tpu.erasure.codec import Erasure
    from minio_tpu.ops.autotune import AUTOTUNE

    AUTOTUNE.reset()
    ladder = AUTOTUNE.probe_ladder()

    k, m = 8, 4
    # (bucket, B, data bytes) — S = bytes / (B*k); one case per plan
    # bucket the serving path actually exercises.
    cases = (("<64K", 1, 32 * 1024),
             ("64K-1M", 8, 512 * 1024),
             ("1-4M", 8, 2 * 1024 * 1024),
             ("4-16M", 8, 8 * 1024 * 1024))
    codec = Erasure(k, m, block_size=1024 * 1024)
    rng = np.random.default_rng(7)
    buckets: dict[str, dict] = {}
    worst_speedup = None
    best_tuned = 0.0
    for bucket, B, nbytes in cases:
        S = nbytes // (B * k)
        blocks = rng.integers(0, 256, (B, k, S)).astype(np.uint8)

        def encode_once(blocks=blocks) -> float:
            t0 = time.perf_counter()
            codec.encode_blocks_batch(blocks)
            return time.perf_counter() - t0

        encode_once()  # warm (native lib, jit shapes, caches)
        tuned: list[float] = []
        untuned: list[float] = []
        try:
            for i in range(6):
                order = (True, False) if i % 2 == 0 else (False, True)
                for on in order:
                    AUTOTUNE.enabled = on
                    (tuned if on else untuned).append(encode_once())
        finally:
            AUTOTUNE.enabled = True
        t_t = statistics.median(tuned)
        t_u = statistics.median(untuned)
        speedup = round(t_u / max(t_t, 1e-9), 3)
        lane = AUTOTUNE.decide("rs_encode", nbytes)
        gibs = nbytes / t_t / (1 << 30)
        best_tuned = max(best_tuned, gibs)
        buckets[bucket] = {
            "chosen_lane": lane,
            "tuned_GiBs": round(gibs, 3),
            "untuned_GiBs": round(nbytes / t_u / (1 << 30), 3),
            "tuned_over_untuned": speedup,
        }
        if worst_speedup is None or speedup < worst_speedup:
            worst_speedup = speedup
    return {"metric": "codec_autotune_encode",
            "value": round(best_tuned, 3), "unit": "GiB/s",
            # Paired acceptance signal: min tuned/untuned across
            # buckets (>= ~1.0 within noise = the planner never made
            # dispatch slower).
            "worst_tuned_over_untuned": worst_speedup,
            "buckets": buckets,
            "crossover_GiBs": ladder,
            "plan": AUTOTUNE.plan_compact()}


def bench_north_star_scaling(np) -> dict:
    """n_devices-aware north star: sweep serving meshes of 1..N
    devices (batching.set_mesh_devices) and report the encode scaling
    curve.  Empty on a single-device box — the sweep only means
    something when jax exposes a mesh (the MULTICHIP harness reports
    8), and this process pins jax to CPU so a relay-less run is 1."""
    import jax

    from minio_tpu.ops import batching, rs_tpu
    n_dev = len(jax.devices())
    if n_dev < 2:
        return {}
    k, m = 8, 4
    S = (1 << 20) // k
    steps = sorted({n for n in (1, 2, 4, 8, n_dev) if n <= n_dev})
    curve: dict[str, float] = {}
    rng = np.random.default_rng(0)
    try:
        for n in steps:
            batching.set_mesh_devices(n)
            batch = 8 * max(1, n)  # B divides every mesh in the sweep
            data = rng.integers(0, 256, (batch, k, S)).astype(np.uint8)
            rs_tpu.encode_batch(data, k, m)  # warm/compile
            t = min(
                _timed_call(lambda: rs_tpu.encode_batch(data, k, m))
                for _ in range(3))
            curve[str(n)] = round(
                batch * k * S / t / (1 << 30), 3)
    finally:
        batching.set_mesh_devices(None)
    return {"devices": n_dev, "scaling_GiBs": curve}


def _timed_call(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


# --- config 3: 12+4 multipart upload through the engine ----------------------


def bench_multipart(np, workdir: str) -> dict:
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.storage.xl import XLStorage

    root = os.path.join(workdir, "cfg3")
    disks = [XLStorage(os.path.join(root, f"disk{i}")) for i in range(16)]
    eng = ErasureObjects(disks, 12, 4, block_size=1024 * 1024)
    eng.make_bucket("bench")
    part_bytes = 32 * 1024 * 1024
    n_parts = 8                      # 256 MiB total (scaled from 10GiB)
    rng = np.random.default_rng(3)
    part = rng.integers(0, 256, part_bytes).astype(np.uint8).tobytes()
    try:
        # warm: single-part upload compiles the encode shapes
        eng.put_object("bench", "warm", part)
        up = eng.multipart.new_multipart_upload("bench", "big")
        t0 = time.perf_counter()
        etags = []
        for p in range(1, n_parts + 1):
            info = eng.multipart.put_object_part("bench", "big", up, p, part)
            etags.append((p, info["etag"]))
        eng.multipart.complete_multipart_upload("bench", "big", up, etags)
        dt = time.perf_counter() - t0
        total = n_parts * part_bytes
        return {"metric": "ec12+4_multipart_encode",
                "value": round(total / dt / (1 << 30), 3), "unit": "GiB/s",
                "total_bytes": total,
                "scale": "256MiB stand-in for BASELINE's 10GiB (wall-time bound)"}
    finally:
        shutil.rmtree(root, ignore_errors=True)


# --- config 4: 8+4 GetObject with 2 shards lost ------------------------------


def bench_get_with_loss(np, workdir: str, device: bool = False) -> dict:
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.ops import batching
    from minio_tpu.storage.xl import XLStorage

    root = os.path.join(workdir, "cfg4")
    roots = [os.path.join(root, f"disk{i}") for i in range(12)]
    disks = [XLStorage(r) for r in roots]
    eng = ErasureObjects(disks, 8, 4, block_size=1024 * 1024)
    eng.make_bucket("bench")
    size = 64 * 1024 * 1024
    rng = np.random.default_rng(4)
    body = rng.integers(0, 256, size).astype(np.uint8).tobytes()
    try:
        eng.put_object("bench", "obj", body)
        # Lose 2 shards: wipe the object's data on two disks.
        for r in roots[:2]:
            shutil.rmtree(os.path.join(r, "bench", "obj"),
                          ignore_errors=True)
        eng.get_object("bench", "obj")  # warm (compile reconstruct shapes)
        before = batching.STATS.snapshot()
        t0 = time.perf_counter()
        got, _info = eng.get_object("bench", "obj")
        dt = time.perf_counter() - t0
        after = batching.STATS.snapshot()
        if got != body:
            raise RuntimeError("reconstructed object bytes differ")
        tpu_delta = after["tpu_dispatches"] - before["tpu_dispatches"]
        if device and tpu_delta == 0:
            raise RuntimeError(
                "device present but GET reconstruct never dispatched to "
                "it (honesty check)")
        return {"metric": "ec8+4_get_2lost",
                "value": round(size / dt / (1 << 30), 3), "unit": "GiB/s",
                "object_bytes": size,
                "tpu_dispatches": after["tpu_dispatches"]
                - before["tpu_dispatches"]}
    finally:
        shutil.rmtree(root, ignore_errors=True)


# --- config 5: 16+4 full-disk heal -------------------------------------------


def bench_heal(np, workdir: str, device: bool = False) -> dict:
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.ops import batching
    from minio_tpu.storage.xl import XLStorage

    root = os.path.join(workdir, "cfg5")
    roots = [os.path.join(root, f"disk{i}") for i in range(20)]
    disks = [XLStorage(r) for r in roots]
    eng = ErasureObjects(disks, 16, 4, block_size=1024 * 1024)
    eng.make_bucket("bench")
    # 2x96MiB (was 24x8MiB): same 192MiB total, but objects larger than
    # one HEAL_BATCH_BYTES group so the heal pipeline (reconstruct
    # overlapping write-back) actually engages — the shape the BASELINE
    # 1000x64MiB workload has.
    n_objects, obj_bytes = 2, 96 * 1024 * 1024  # 192 MiB (scaled from
    rng = np.random.default_rng(5)              # 1000x64MiB; wall-time bound)
    try:
        for i in range(n_objects):
            body = rng.integers(0, 256, obj_bytes).astype(np.uint8)
            eng.put_object("bench", f"obj-{i}", body.tobytes())
        # Wipe one disk wholesale (full-disk loss), keep format metadata
        # dirs intact enough for rejoin by recreating the root.
        shutil.rmtree(roots[0])
        os.makedirs(roots[0], exist_ok=True)
        before = batching.STATS.snapshot()
        t0 = time.perf_counter()
        results = eng.healer.heal_disk(0)
        dt = time.perf_counter() - t0
        after = batching.STATS.snapshot()
        healed = sum(1 for r in results if r.healed_disks)
        if healed == 0:
            raise RuntimeError("heal_disk healed nothing")
        tpu_delta = after["tpu_dispatches"] - before["tpu_dispatches"]
        if device and tpu_delta == 0:
            raise RuntimeError(
                "device present but heal reconstruct never dispatched to "
                "it (honesty check)")
        total = n_objects * obj_bytes
        return {"metric": "ec16+4_heal",
                "value": round(total / dt / (1 << 30), 3), "unit": "GiB/s",
                "objects_healed": healed, "total_bytes": total,
                "scale": "2x96MiB stand-in for BASELINE's 1000x64MiB",
                "tpu_dispatches": after["tpu_dispatches"]
                - before["tpu_dispatches"]}
    finally:
        shutil.rmtree(root, ignore_errors=True)


# --- config 6: degraded tail — hedged reads vs one slow drive ----------------


def bench_degraded_tail(np, workdir: str) -> dict:
    """Paired hedging-on/off GET p99 with ONE injected-slow drive (a
    data-shard holder at 10x-ish the healthy read), using PR 4's
    paired-delta method: each hedging-ON GET is paired with the
    immediately-following hedging-OFF GET (alternating pair order so
    position-within-pair effects don't alias), so VM drift moves both
    halves together and the hedge's tail win survives. Also reports
    the hedge fire rate and the wasted-read fraction (completed
    hedges the primary beat anyway)."""
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.faultinject import FAULTS
    from minio_tpu.obs.metrics2 import METRICS2
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage

    def hedges(result: str) -> int:
        return METRICS2.get("minio_tpu_v2_hedged_reads_total",
                            {"result": result}) or 0

    access, secret = "benchadmin", "benchadmin-secret"
    root = os.path.join(workdir, "cfg-degraded")
    disks = [XLStorage(os.path.join(root, f"disk{i}"))
             for i in range(6)]
    layer = ErasureObjects(disks, 4, 2, block_size=256 * 1024)
    srv = S3Server(layer, access, secret)
    port = srv.start()
    try:
        client = S3Client("127.0.0.1", port, access, secret)
        client.make_bucket("bench")
        rng = np.random.default_rng(7)
        body = rng.integers(0, 256, 1024 * 1024).astype(
            np.uint8).tobytes()
        r = client.put_object("bench", "obj", body)
        if r.status != 200:
            raise RuntimeError(f"PutObject failed: {r.status}")
        # Calibrate the hedge budget on healthy reads.
        for _ in range(10):
            if client.get_object("bench", "obj").status != 200:
                raise RuntimeError("warm GET failed")
        healthy_ms = []
        for _ in range(10):
            t0 = time.perf_counter()
            client.get_object("bench", "obj")
            healthy_ms.append((time.perf_counter() - t0) * 1e3)
        # Slow ONE data-shard holder's shard reads to ~10x the
        # healthy GET (shard reads are a fraction of that, so the
        # multiple vs the read itself is far larger).
        import json as _json
        slow = None
        for d in disks:
            meta = os.path.join(d.root, "bench", "obj", "xl.meta")
            doc = _json.loads(open(meta).read())
            if doc["versions"][0]["erasure"]["index"] == 1:
                slow = d.root
                break
        inj_ms = max(50.0, 10.0 * statistics.median(healthy_ms))
        FAULTS.load_plan({"seed": 1, "rules": [
            {"kind": "latency", "target": slow, "op": "read_file",
             "latency_ms": inj_ms}]})
        fired0, won0, wasted0 = (hedges("fired"), hedges("won"),
                                 hedges("wasted"))
        lat_on: list = []
        lat_off: list = []
        try:
            for i in range(40):
                order = (True, False) if i % 2 == 0 else (False, True)
                for on in order:
                    layer.hedge_enabled = on
                    t0 = time.perf_counter()
                    g = client.get_object("bench", "obj")
                    (lat_on if on else lat_off).append(
                        (time.perf_counter() - t0) * 1e3)
                    if g.status != 200:
                        raise RuntimeError(f"GET failed: {g.status}")
        finally:
            layer.hedge_enabled = True
            FAULTS.clear()

        def p99(xs):
            return sorted(xs)[max(0, int(len(xs) * 0.99) - 1)]

        fired = hedges("fired") - fired0
        completed = (hedges("won") - won0) + (hedges("wasted")
                                              - wasted0)
        return {
            "metric": "degraded_get_p99_hedged_ms",
            "value": round(p99(lat_on), 3), "unit": "ms",
            "object_bytes": len(body),
            "injected_latency_ms": round(inj_ms, 1),
            "healthy_get_p50_ms": round(
                statistics.median(healthy_ms), 3),
            "get_p99_hedge_off_ms": round(p99(lat_off), 3),
            "get_p50_hedge_on_ms": round(
                statistics.median(lat_on), 3),
            "get_p50_hedge_off_ms": round(
                statistics.median(lat_off), 3),
            # How often the budget tripped, and how much of the fired
            # I/O the primary beat anyway (the hedging tax).
            "hedge_fire_rate": round(fired / max(1, len(lat_on)), 3),
            "hedge_wasted_fraction": round(
                (hedges("wasted") - wasted0) / max(1, completed), 3),
            "hedge_budget_ms": round(
                layer.hedge_budget.budget() * 1e3, 3),
        }
    finally:
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)


# --- config 7: QoS brownout — overload shedding + heal interference ----------


def bench_qos_brownout(np, workdir: str) -> dict:
    """Two degradation numbers the QoS subsystem owns:

    1. brownout: loadgen drives 1MiB PUTs at ~4x the configured write
       cap; the server must SHED the excess with 503 SlowDown +
       Retry-After (bounded admitted p50/p99) instead of queueing
       unboundedly.
    2. heal interference: foreground 1MiB PUT p50 with a continuous
       heal sweep running vs heal-off baseline — the priority lanes
       (qos/scheduler.py) keep repair work out of the serving path.
    """
    import statistics as stats

    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage
    from tools.loadgen import run_load

    access, secret = "benchadmin", "benchadmin-secret"
    root = os.path.join(workdir, "cfg6")
    disks = [XLStorage(os.path.join(root, f"disk{i}")) for i in range(6)]
    layer = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
    srv = S3Server(layer, access, secret)
    port = srv.start()
    write_cap = 4
    try:
        client = S3Client("127.0.0.1", port, access, secret)
        client.make_bucket("bench")
        client.make_bucket("healbkt")
        rng = np.random.default_rng(6)
        body = rng.integers(0, 256, 1024 * 1024).astype(
            np.uint8).tobytes()
        for i in range(4):  # warm compile/caches
            client.put_object("bench", f"warm-{i}", body)

        # -- brownout: loadgen at ~4x the write cap ---------------------
        # Sheds are DELIBERATE backpressure: they must not pollute the
        # slow-request log or its blame histogram. Asserted via the
        # slowlog's exemption counter — every 503 the loadgen saw must
        # have been EXEMPTED (shed/deadline), not captured. (Raw 503
        # entry counts can't distinguish a leaked shed from a quorum
        # 503, which the slowlog deliberately captures.)
        from minio_tpu.obs.metrics2 import METRICS2 as _M2
        from minio_tpu.obs.slowlog import SLOWLOG
        from minio_tpu.obs.watchdog import WATCHDOG
        slowlog_before = SLOWLOG.total
        exempted_before = SLOWLOG.exempted
        # Standing regression test for the watchdog itself: with fast
        # sampling and short burn windows, the shed-rate built-in MUST
        # fire during the brownout and resolve after it — the bench
        # asserts the whole pending->firing->resolved loop against
        # real overload, not synthetic samples.
        shed_fired_before = _M2.get(
            "minio_tpu_v2_alert_transitions_total",
            {"rule": "shed_burn", "state": "firing"}) or 0
        srv.config.set_kv("obs timeline_sample=250ms")
        srv.config.set_kv("alerts fast_window=3s slow_window=30s "
                          "pending_ticks=2 resolve_ticks=2")
        srv.config.set_kv(f"api requests_max_write={write_cap} "
                          "requests_deadline=250ms")
        brown = run_load("127.0.0.1", port, access, secret, "bench",
                         concurrency=4 * write_cap, duration=4.0,
                         put_fraction=1.0, object_bytes=len(body))
        # The last shed-heavy samples are still inside the fast window:
        # give the sampler a moment to evaluate them before the caps
        # lift (the alert may already have fired mid-load).
        shed_deadline = time.time() + 10
        while (time.time() < shed_deadline
               and (_M2.get("minio_tpu_v2_alert_transitions_total",
                            {"rule": "shed_burn", "state": "firing"})
                    or 0) <= shed_fired_before):
            time.sleep(0.25)
        srv.config.set_kv("api requests_max_write=0 "
                          "requests_deadline=10s")
        shed_alert_fired = (_M2.get(
            "minio_tpu_v2_alert_transitions_total",
            {"rule": "shed_burn", "state": "firing"}) or 0) \
            - shed_fired_before
        if shed_alert_fired < 1:
            raise RuntimeError(
                "shed-rate watchdog built-in never fired during the "
                f"brownout (shed rate {brown['shed_rate']})")
        exempted = SLOWLOG.exempted - exempted_before
        if exempted < brown["shed_503"]:
            raise RuntimeError(
                f"only {exempted} of {brown['shed_503']} shed 503s "
                "were slowlog-exempt (sheds leaked into the blame "
                "histogram)")

        def put_lat(tag: str, n: int = 14) -> list[float]:
            lat = []
            for i in range(n):
                t0 = time.perf_counter()
                r = client.put_object("bench", f"{tag}-{i}", body)
                lat.append(time.perf_counter() - t0)
                if r.status != 200:
                    raise RuntimeError(f"PUT failed: {r.status}")
            return lat

        # -- heal interference ------------------------------------------
        # off -> on -> off: the two baselines bracket the measurement
        # so page-cache/VM drift doesn't masquerade as interference.
        for i in range(16):
            client.put_object("healbkt", f"obj-{i}", body)
        lat_off = put_lat("off1")
        stop = threading.Event()

        def heal_forever():
            import shutil as _sh
            while not stop.is_set():
                for i in range(16):  # re-damage so the sweep never idles
                    _sh.rmtree(os.path.join(root, "disk0", "healbkt",
                                            f"obj-{i}"),
                               ignore_errors=True)
                layer.healer.heal_disk(0)

        ht = threading.Thread(target=heal_forever, daemon=True)
        ht.start()
        time.sleep(0.3)  # let the sweep reach steady state
        lat_on = put_lat("on")
        stop.set()
        ht.join(timeout=60)
        lat_off += put_lat("off2")
        p50_off = stats.median(lat_off) * 1e3
        p50_on = stats.median(lat_on) * 1e3
        # The shed-rate alert must RESOLVE once the brownout is over:
        # the heal-interference PUTs above ran shed-free, so the fast
        # window has long cleared — poll out the resolve hysteresis.
        resolve_deadline = time.time() + 30
        while (time.time() < resolve_deadline
               and WATCHDOG.state_of("shed_burn") != "ok"):
            time.sleep(0.25)
        if WATCHDOG.state_of("shed_burn") != "ok":
            raise RuntimeError(
                "shed-rate alert never resolved after the brownout: "
                f"{WATCHDOG.snapshot()['alerts']}")
        from minio_tpu.obs.metrics2 import METRICS2
        return {
            "metric": "qos_brownout",
            "value": brown["shed_rate"], "unit": "shed_rate",
            "write_cap": write_cap,
            "overload_concurrency": 4 * write_cap,
            "requests": brown["requests"], "ok": brown["ok"],
            "shed_503": brown["shed_503"],
            "retry_after_headers": brown["retry_after_headers"],
            "admitted_p50_ms": brown["latency_ms"]["p50"],
            "admitted_p99_ms": brown["latency_ms"]["p99"],
            "put_p50_heal_off_ms": round(p50_off, 3),
            "put_p50_heal_on_ms": round(p50_on, 3),
            "heal_interference_ratio": round(p50_on / max(p50_off, 1e-9),
                                             3),
            "bg_deferrals": METRICS2.get(
                "minio_tpu_v2_qos_bg_deferrals_total"),
            "bg_promotions": METRICS2.get(
                "minio_tpu_v2_qos_bg_promotions_total"),
            # Asserted above: every shed was slowlog-exempt.
            "slowlog_exempted_sheds": exempted,
            "slowlog_entries_during": SLOWLOG.total - slowlog_before,
            # Asserted above: the shed-rate built-in fired during the
            # brownout and resolved after it.
            "shed_alert_fired": shed_alert_fired,
            "shed_alert_resolved": True,
        }
    finally:
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)


def bench_hot_get(np, workdir: str) -> dict:
    """Hot-object serving tier: Zipfian GETs with the cache on vs off,
    PAIRED off/on/off so VM drift brackets the measurement (PR 4's
    method). Reports GET QPS both ways, the speedup, hit ratio,
    coalesced-fill count, and p99 — stamped with the cache config the
    way every config is stamped with backend_mix. Also records the
    cache-OFF PUT+GET p50 as a cross-round tripwire: the consult hook
    when disabled is one attribute read, so this number regressing
    against earlier BENCH_r0N records means the default-off path grew
    real cost (the code-present vs code-absent A/B cannot be toggled
    at runtime — the round history IS the baseline)."""
    import statistics as stats

    from minio_tpu.cache.hotcache import HOTCACHE
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.obs.metrics2 import METRICS2
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage
    from tools.loadgen import run_load

    access, secret = "benchadmin", "benchadmin-secret"
    base = workdir
    if os.path.isdir("/dev/shm"):
        # tmpfs like put_p50: this config tracks the serving path's
        # CPU cost, not VM writeback noise.
        base = tempfile.mkdtemp(prefix="minio-tpu-hotget-",
                                dir="/dev/shm")
    root = os.path.join(base, "cfg7")
    # 4+2 like put_p50: wider sets convoy this 2-core box's quorum
    # pool into multi-second tails that drown the signal.
    disks = [XLStorage(os.path.join(root, f"disk{i}"))
             for i in range(6)]
    layer = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
    srv = S3Server(layer, access, secret)
    port = srv.start()
    # revalidate must outlast warm+segment: a mem hit that trips the
    # revalidation window pays a metadata fan-out, which is the miss
    # path's dominant cost — the window is the operator's staleness
    # bound, and the bench measures steady-state hits inside it.
    keys, obj_bytes, zipf_s, seg_s = 64, 256 * 1024, 1.2, 4.0
    cache_kv = ("cache enable=on mem_bytes=268435456 min_hits=1 "
                "max_object_bytes=8388608 revalidate=30s")
    try:
        client = S3Client("127.0.0.1", port, access, secret)
        client.make_bucket("bench")
        rng = np.random.default_rng(7)
        body = rng.integers(0, 256, obj_bytes).astype(np.uint8).tobytes()
        for r in range(keys):   # preload the Zipf key space + warm
            client.put_object("bench", f"hot/z{r}", body)

        def seg(tag: str) -> dict:
            return run_load("127.0.0.1", port, access, secret, "bench",
                            concurrency=4, duration=seg_s,
                            put_fraction=0.0, object_bytes=obj_bytes,
                            key_prefix="hot", key_space=keys,
                            zipf_s=zipf_s, seed=7)

        off1 = seg("off1")

        def m(name, labels=None):
            return METRICS2.get(name, labels)

        srv.config.set_kv(cache_kv)
        for r in range(keys):
            # Warm the tier: the measured window is STEADY-STATE hot
            # serving (cold-fill cost is the miss path, measured by
            # the off segments and amortized over an object's life).
            client.get_object("bench", f"hot/z{r}")
        hits0 = (m("minio_tpu_v2_cache_hits_total", {"tier": "mem"})
                 + m("minio_tpu_v2_cache_hits_total", {"tier": "disk"}))
        miss0 = m("minio_tpu_v2_cache_misses_total")
        coal0 = m("minio_tpu_v2_cache_coalesced_waits_total")
        on = seg("on")
        hits = (m("minio_tpu_v2_cache_hits_total", {"tier": "mem"})
                + m("minio_tpu_v2_cache_hits_total", {"tier": "disk"})
                - hits0)
        misses = m("minio_tpu_v2_cache_misses_total") - miss0
        coalesced = m("minio_tpu_v2_cache_coalesced_waits_total") - coal0
        srv.config.set_kv("cache enable=off")
        off2 = seg("off2")

        # Cache-OFF PUT+GET p50 tripwire (see docstring): the default
        # mode's absolute cost, judged against prior rounds' records.
        lat_pg: list[float] = []
        for i in range(30):
            t0 = time.perf_counter()
            client.put_object("bench", f"ov-{i}", body)
            client.get_object("bench", f"ov-{i}")
            lat_pg.append(time.perf_counter() - t0)

        qps_off = (off1["qps_achieved"] + off2["qps_achieved"]) / 2
        qps_on = on["qps_achieved"]
        lookups = hits + misses
        return {
            "metric": "hot_get",
            "value": round(qps_on / max(qps_off, 1e-9), 2),
            "unit": "x_get_qps",
            "get_qps_cache_on": qps_on,
            "get_qps_cache_off": round(qps_off, 2),
            "p99_ms_cache_on": on["latency_ms"]["p99"],
            "p99_ms_cache_off": round(
                (off1["latency_ms"]["p99"]
                 + off2["latency_ms"]["p99"]) / 2, 3),
            "hit_ratio": round(hits / lookups, 4) if lookups else 0.0,
            "cache_hits": hits, "cache_misses": misses,
            "coalesced_fills": coalesced,
            "key_distribution": on.get("key_distribution", {}),
            "cache_off_put_get_p50_ms": round(
                stats.median(lat_pg) * 1e3, 3),
            "errors_other": (off1["errors_other"] + on["errors_other"]
                             + off2["errors_other"]),
            # The stamp: which cache config produced these numbers
            # (like backend_mix stamps which backend ran the math).
            "cache": {"keys": keys, "object_bytes": obj_bytes,
                      "zipf_s": zipf_s, "segment_s": seg_s,
                      "kv": cache_kv,
                      "workdir": "tmpfs" if base != workdir else "disk"},
        }
    finally:
        HOTCACHE.reset()
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)
        if base != workdir:
            shutil.rmtree(base, ignore_errors=True)


def bench_noisy_neighbor(np, workdir: str) -> dict:
    """Tenant attribution plane end-to-end (obs/usage.py): one
    Zipf-hot tenant amid uniform background, driven through the
    multi-tenant loadgen against a capped write class so the hot
    tenant causes real sheds.  Asserts the whole loop the plane
    exists for:

    1. admin /top ranks the injected hot bucket first, with a
       worst-request trace-id exemplar that resolves in the slowlog;
    2. the watchdog's noisy_neighbor built-in fires with the tenant
       named in the cause, and resolves after the skew stops;
    3. a paired usage-on/off PUT p50 stays within the PR-4 noise bar
       (<= 2%) — attribution must be free on the hot path.
    """
    import statistics as stats

    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.obs.metrics2 import METRICS2
    from minio_tpu.obs.usage import USAGE
    from minio_tpu.obs.watchdog import WATCHDOG
    from minio_tpu.s3.admin_client import AdminClient
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage
    from tools.loadgen import run_load

    access, secret = "benchadmin", "benchadmin-secret"
    base = workdir
    if os.path.isdir("/dev/shm"):
        # tmpfs like put_p50/hot_get: the paired p50 tracks the
        # record() hook's CPU cost, not VM writeback noise.
        base = tempfile.mkdtemp(prefix="minio-tpu-noisy-",
                                dir="/dev/shm")
    root = os.path.join(base, "cfg-noisy")
    disks = [XLStorage(os.path.join(root, f"disk{i}"))
             for i in range(6)]
    layer = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
    srv = S3Server(layer, access, secret)
    port = srv.start()
    n_tenants, write_cap = 4, 2
    try:
        USAGE.reset()
        client = S3Client("127.0.0.1", port, access, secret)
        adm = AdminClient("127.0.0.1", port, access, secret)
        for i in range(n_tenants):
            client.make_bucket(f"nz-{i}")
        client.make_bucket("ovh")
        rng = np.random.default_rng(15)
        # 1MiB like qos_brownout: big enough that a 4x-cap overload
        # piles queue waits past the deadline and actually SHEDS.
        body = rng.integers(0, 256, 1024 * 1024).astype(
            np.uint8).tobytes()
        for i in range(4):  # warm compile/caches
            client.put_object("ovh", f"warm-{i}", body)

        # -- paired usage-on/off PUT p50 (off/on/off brackets drift) --
        def put_lat(tag: str, n: int = 24) -> list[float]:
            lat = []
            for i in range(n):
                t0 = time.perf_counter()
                r = client.put_object("ovh", f"{tag}-{i}", body)
                lat.append(time.perf_counter() - t0)
                if r.status != 200:
                    raise RuntimeError(f"PUT failed: {r.status}")
            return lat

        adm.set_config_kv("usage enable=off")
        lat_off = put_lat("off1")
        adm.set_config_kv("usage enable=on")
        lat_on = put_lat("on")
        adm.set_config_kv("usage enable=off")
        lat_off += put_lat("off2")
        adm.set_config_kv("usage enable=on")
        p50_off = stats.median(lat_off) * 1e3
        p50_on = stats.median(lat_on) * 1e3
        overhead_pct = (p50_on - p50_off) / max(p50_off, 1e-9) * 100
        if overhead_pct > 2.0:
            raise RuntimeError(
                f"usage-on PUT p50 overhead {overhead_pct:.2f}% "
                f"exceeds the 2% noise bar "
                f"(on {p50_on:.3f}ms vs off {p50_off:.3f}ms)")

        # -- skewed fleet: Zipf-hot tenant 0 vs uniform background ----
        USAGE.reset()
        adm.set_config_kv("obs timeline_sample=250ms slow_ms=100")
        adm.set_config_kv("usage fast_window=2s slow_window=10s "
                          "noisy_share=0.5 noisy_min_requests=20")
        adm.set_config_kv("alerts pending_ticks=2 resolve_ticks=2")
        # ~12x the cap: the bounded wait queue (QUEUE_FACTOR x cap)
        # overflows and the 100ms budget burns, so the overload SHEDS
        # instead of merely queueing on a fast box.
        adm.set_config_kv(f"api requests_max_write={write_cap} "
                          "requests_deadline=100ms")
        fired_before = METRICS2.get(
            "minio_tpu_v2_alert_transitions_total",
            {"rule": "noisy_neighbor", "state": "firing"}) or 0
        load = run_load("127.0.0.1", port, access, secret, "nz",
                        concurrency=12 * write_cap, duration=3.0,
                        put_fraction=1.0, object_bytes=len(body),
                        buckets=n_tenants, tenant_zipf_s=3.0, seed=15)
        # The skew is still inside the fast window: give the sampler
        # a moment to evaluate it before the caps lift.
        fire_deadline = time.time() + 10
        while (time.time() < fire_deadline
               and (METRICS2.get(
                   "minio_tpu_v2_alert_transitions_total",
                   {"rule": "noisy_neighbor", "state": "firing"})
                   or 0) <= fired_before):
            time.sleep(0.25)
        fired = (METRICS2.get(
            "minio_tpu_v2_alert_transitions_total",
            {"rule": "noisy_neighbor", "state": "firing"})
            or 0) - fired_before
        snap_alerts = {a["rule"]: a for a in
                       WATCHDOG.snapshot()["alerts"]}
        cause = snap_alerts.get("noisy_neighbor", {}).get("cause", "")
        if fired < 1 or "nz-0" not in cause:
            raise RuntimeError(
                "noisy_neighbor never fired naming the hot tenant "
                f"(fired={fired}, cause={cause!r}, "
                f"shed_rate={load['shed_rate']})")

        # -- admin /top names the hot bucket, exemplar -> slowlog -----
        top = adm.top()
        ranked = [b for b in top["buckets"]
                  if b["name"].startswith("nz-")]
        if not ranked or ranked[0]["name"] != "nz-0":
            raise RuntimeError(
                f"/top did not rank the hot tenant first: "
                f"{[b['name'] for b in top['buckets']]}")
        worst = ranked[0].get("worst", {})
        if not worst.get("traceId"):
            raise RuntimeError(f"/top carried no trace exemplar: "
                               f"{ranked[0]}")
        hot_keys = (top.get("keys") or {}).get("write", [])
        if not any(k["key"].startswith("nz-0/") for k in hot_keys):
            raise RuntimeError(
                f"write-key sketch missed the hot bucket: {hot_keys}")

        # -- resolve once the skew stops ------------------------------
        adm.set_config_kv("api requests_max_write=0 "
                          "requests_deadline=10s")
        resolve_deadline = time.time() + 30
        while (time.time() < resolve_deadline
               and WATCHDOG.state_of("noisy_neighbor") != "ok"):
            time.sleep(0.25)
        if WATCHDOG.state_of("noisy_neighbor") != "ok":
            raise RuntimeError(
                "noisy_neighbor never resolved after the skew "
                f"stopped: {WATCHDOG.snapshot()['alerts']}")

        hot = (load.get("tenants") or {}).get("nz-0", {})
        return {
            "metric": "noisy_neighbor",
            "value": round(hot.get("requests", 0)
                           / max(load["requests"], 1), 4),
            "unit": "hot_tenant_share",
            "tenants": n_tenants, "write_cap": write_cap,
            "requests": load["requests"],
            "shed_503": load["shed_503"],
            "per_tenant": load.get("tenants", {}),
            "alert_fired": fired, "alert_cause": cause,
            "alert_resolved": True,
            "top_bucket": ranked[0]["name"],
            "worst_trace_id": worst.get("traceId", ""),
            "worst_in_slowlog": "slowlog" in worst,
            "usage_folded": USAGE.folded_total,
            "put_p50_usage_on_ms": round(p50_on, 3),
            "put_p50_usage_off_ms": round(p50_off, 3),
            "usage_overhead_pct": round(overhead_pct, 2),
        }
    finally:
        USAGE.reset()
        from minio_tpu.config.kv import DEFAULT_KVS
        USAGE.configure(
            top_k=int(DEFAULT_KVS["usage"]["top_k"]),
            cardinality_cap=int(DEFAULT_KVS["usage"]
                                ["cardinality_cap"]))
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)
        if base != workdir:
            shutil.rmtree(base, ignore_errors=True)


def bench_loop_health(np, workdir: str) -> dict:
    """Event-loop health plane end-to-end (obs/loopmon.py), two
    promises:

    1. a paired loopmon-on/off keep-alive PUT p50 within the repo's
       2% noise bar — a 10Hz heartbeat + watcher must be free on the
       hot path;
    2. an injected 400ms ``loop_block`` fault plan against a
       front-door loop drives the ``loop_stall`` watchdog built-in to
       firing with the blamed frame (``_injected_loop_block``) named
       in the cause, and the alert resolves after the plan clears and
       the recent-stall window drains.
    """
    import statistics as stats

    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.obs.loopmon import LOOPMON
    from minio_tpu.obs.metrics2 import METRICS2
    from minio_tpu.obs.watchdog import WATCHDOG
    from minio_tpu.s3.admin_client import AdminClient
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage

    access, secret = "benchadmin", "benchadmin-secret"
    base = workdir
    if os.path.isdir("/dev/shm"):
        # tmpfs like put_p50: the paired p50 tracks the heartbeat's
        # CPU cost, not VM writeback noise.
        base = tempfile.mkdtemp(prefix="minio-tpu-loop-",
                                dir="/dev/shm")
    root = os.path.join(base, "cfg-loop")
    disks = [XLStorage(os.path.join(root, f"disk{i}"))
             for i in range(6)]
    layer = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
    srv = S3Server(layer, access, secret)
    port = srv.start()
    try:
        client = S3Client("127.0.0.1", port, access, secret)
        adm = AdminClient("127.0.0.1", port, access, secret)
        client.make_bucket("lhealth")
        rng = np.random.default_rng(19)
        body = rng.integers(0, 256, 1024 * 1024).astype(
            np.uint8).tobytes()
        for i in range(4):  # warm compile/caches
            client.put_object("lhealth", f"warm-{i}", body)

        # -- paired loopmon-on/off PUT p50 (off/on/off brackets drift)
        def put_lat(tag: str, n: int = 24) -> list[float]:
            lat = []
            for i in range(n):
                t0 = time.perf_counter()
                r = client.put_object("lhealth", f"{tag}-{i}", body)
                lat.append(time.perf_counter() - t0)
                if r.status != 200:
                    raise RuntimeError(f"PUT failed: {r.status}")
            return lat

        LOOPMON.set_enabled(False)
        lat_off = put_lat("off1")
        LOOPMON.set_enabled(True)
        lat_on = put_lat("on")
        LOOPMON.set_enabled(False)
        lat_off += put_lat("off2")
        LOOPMON.set_enabled(True)
        p50_off = stats.median(lat_off) * 1e3
        p50_on = stats.median(lat_on) * 1e3
        overhead_pct = (p50_on - p50_off) / max(p50_off, 1e-9) * 100
        if overhead_pct > 2.0:
            raise RuntimeError(
                f"loopmon-on PUT p50 overhead {overhead_pct:.2f}% "
                f"exceeds the 2% noise bar "
                f"(on {p50_on:.3f}ms vs off {p50_off:.3f}ms)")

        # -- injected 400ms loop_block -> loop_stall fires -> resolves
        adm.set_config_kv("obs timeline_sample=250ms "
                          "loop_stall_ms=200")
        adm.set_config_kv("alerts pending_ticks=2 resolve_ticks=2")
        fired_before = METRICS2.get(
            "minio_tpu_v2_alert_transitions_total",
            {"rule": "loop_stall", "state": "firing"}) or 0
        # ONE deterministic block on the first front-door loop: the
        # heartbeat schedules it as a real time.sleep on the loop.
        adm.fault_inject({"seed": 19, "rules": [
            {"kind": "loop_block", "target": "s3-0",
             "latency_ms": 400, "count": 1}]})
        fire_deadline = time.time() + 20
        while (time.time() < fire_deadline
               and (METRICS2.get(
                   "minio_tpu_v2_alert_transitions_total",
                   {"rule": "loop_stall", "state": "firing"})
                   or 0) <= fired_before):
            time.sleep(0.25)
        fired = (METRICS2.get(
            "minio_tpu_v2_alert_transitions_total",
            {"rule": "loop_stall", "state": "firing"})
            or 0) - fired_before
        snap_alerts = {a["rule"]: a for a in
                       WATCHDOG.snapshot()["alerts"]}
        cause = snap_alerts.get("loop_stall", {}).get("cause", "")
        if fired < 1 or "_injected_loop_block" not in cause:
            raise RuntimeError(
                "loop_stall never fired naming the injected frame "
                f"(fired={fired}, cause={cause!r}, "
                f"stalls={LOOPMON.snapshot()['stalls'][-3:]})")

        adm.fault_inject(clear=True)
        # The recent-stall window (10s) drains, then resolve_ticks.
        resolve_deadline = time.time() + 40
        while (time.time() < resolve_deadline
               and WATCHDOG.state_of("loop_stall") != "ok"):
            time.sleep(0.25)
        if WATCHDOG.state_of("loop_stall") != "ok":
            raise RuntimeError(
                "loop_stall never resolved after the plan cleared: "
                f"{WATCHDOG.snapshot()['alerts']}")

        prof = LOOPMON.profiler.report(top=5, minutes=2)
        return {
            "metric": "loop_health",
            "value": round(overhead_pct, 2),
            "unit": "loopmon_on_p50_overhead_pct",
            "put_p50_loopmon_on_ms": round(p50_on, 3),
            "put_p50_loopmon_off_ms": round(p50_off, 3),
            "alert_fired": fired, "alert_cause": cause,
            "alert_resolved": True,
            "loop_census": LOOPMON.lag_census(),
            "profiler_running": prof["running"],
            "profiler_samples": prof["samples"],
        }
    finally:
        from minio_tpu.faultinject import FAULTS
        FAULTS.clear()
        LOOPMON.set_enabled(True)
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)
        if base != workdir:
            shutil.rmtree(base, ignore_errors=True)


# --- config 9: crash recovery — kill -9 mid-PUT-loop, restart, recover -------


def bench_front_door(np, workdir: str) -> dict:
    """Event-loop front door at connection scale, three numbers:

    1. connection sweep — the asyncio loadgen (subprocess: client and
       server each get their own fd budget) holds 100 / 1k / 10k
       keep-alive sockets and drives a paced in-cap GET/PUT mix;
       p50/p99 vs connection count. Flat p99 = idle sockets are free.
    2. idle-connection RSS: server RSS delta while 10k established
       connections sit on keep-alive, per connection.
    3. paired low-concurrency put_p50 tripwire: async vs threaded
       front door on identical layers, alternating pairs (PR-4's
       method — this VM drifts on second timescales, pairing cancels
       it); the event loop must cost ~nothing at today's workloads.
    4. distributed fan-out: a 2-node cluster (half the erasure set
       behind peer RPC) drives paired async-vs-threaded RPC-fabric
       PUTs (same alternating-pair method, flipping MINIO_RPC_FABRIC
       per call), then parks 1k concurrent peer calls on the RPC loop
       and reads the in-flight census against the process thread
       count — the zero-thread-per-call claim, stamped.

    Tripwires raise (bench records the failure): p99 flatness
    (10k within 2x of 100-conn p99 plus a 15ms scheduling-jitter
    floor — two python processes on 2 cores), zero loadgen framing
    errors, zero admission-slot leaks, put_p50 delta within noise,
    census >= 900 of 1k in flight with <= 8 extra threads.
    """
    import statistics as stats
    import subprocess
    import sys

    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage

    access, secret = "benchadmin", "benchadmin-secret"
    root = os.path.join(workdir, "cfg_fd")

    def rss_kib() -> int:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
        return 0

    def boot(front: str, tag: str):
        disks = [XLStorage(os.path.join(root, f"{tag}{i}"))
                 for i in range(6)]
        layer = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
        prev = os.environ.get("MINIO_FRONT_DOOR")
        os.environ["MINIO_FRONT_DOOR"] = front
        try:
            srv = S3Server(layer, access, secret)
            port = srv.start()
        finally:
            if prev is None:
                os.environ.pop("MINIO_FRONT_DOOR", None)
            else:
                os.environ["MINIO_FRONT_DOOR"] = prev
        return srv, port

    srv, port = boot("async", "disk")
    srv_t = None
    try:
        client = S3Client("127.0.0.1", port, access, secret)
        client.make_bucket("bench")
        body16k = os.urandom(16 * 1024)
        for i in range(6):  # warm codec/caches
            client.put_object("bench", f"warm-{i}", body16k)
        # In-cap traffic: executing concurrency is capped, so request
        # latency must not depend on how many sockets are PARKED.
        srv.config.set_kv("api requests_max_read=8 requests_max_write=4"
                          " requests_deadline=10s")

        def drive(conns: int, duration: float, qps: float) -> dict:
            out = subprocess.run(
                [sys.executable, "-m", "tools.loadgen",
                 "--port", str(port), "--access-key", access,
                 "--secret-key", secret, "--bucket", "bench",
                 "--connections", str(conns),
                 "--duration", str(duration), "--qps", str(qps),
                 "--put-fraction", "0.1", "--size", str(len(body16k))],
                capture_output=True, text=True, timeout=600,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            if out.returncode != 0:
                raise RuntimeError(
                    f"loadgen at {conns} conns failed: "
                    f"{out.stderr[-500:]}")
            return json.loads(out.stdout)

        sweep: list[dict] = []
        rss_idle_per_conn = 0.0
        for conns in (100, 1000, 10000):
            rss_before = rss_kib()
            rep = drive(conns, 6.0, 150.0)
            if rep["errors_other"] or rep["connect_failures"]:
                raise RuntimeError(
                    f"loadgen framing/connect errors at {conns} "
                    f"conns: {rep['errors_other']} / "
                    f"{rep['connect_failures']}")
            sweep.append({
                "connections": conns,
                "established": rep["established"],
                "requests": rep["requests"], "ok": rep["ok"],
                "shed_503": rep["shed_503"],
                "reconnects": rep["reconnects"],
                "connect_p50_ms": rep["connect_ms"]["p50"],
                "connect_p99_ms": rep["connect_ms"]["p99"],
                "get_p50_ms": rep["get"]["total_ms"]["p50"],
                "get_p99_ms": rep["get"]["total_ms"]["p99"],
                "get_ttfb_p99_ms": rep["get"]["ttfb_ms"]["p99"],
                "put_p50_ms": rep["put"]["total_ms"]["p50"],
                "put_p99_ms": rep["put"]["total_ms"]["p99"],
                "rss_before_kib": rss_before,
            })
        p99_100 = sweep[0]["get_p99_ms"]
        p99_10k = sweep[-1]["get_p99_ms"]
        # Flatness: within 2x plus a fixed scheduling-jitter floor —
        # client (10k coroutines) and server share 2 cores here, and
        # the 100-conn baseline p99 itself swings 6-12ms run to run.
        if p99_10k > 2.0 * p99_100 + 15.0:
            raise RuntimeError(
                f"p99 not flat across the sweep: {p99_100:.1f}ms @100 "
                f"vs {p99_10k:.1f}ms @10k conns")
        if srv.qos.foreground_inflight() != 0:
            raise RuntimeError(
                f"admission slots leaked after sweep: "
                f"{srv.qos.foreground_inflight()}")

        # -- idle-connection RSS: hold 10k established, mostly idle --
        rss_before = rss_kib()
        hold = subprocess.Popen(
            [sys.executable, "-m", "tools.loadgen",
             "--port", str(port), "--access-key", access,
             "--secret-key", secret, "--bucket", "bench",
             "--connections", "10000", "--duration", "6",
             "--qps", "20", "--put-fraction", "0", "--size", "4096"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        try:
            # Sample at the held plateau: wait for the full fleet, but
            # a TIME_WAIT-throttled connect storm (the sweep's 10k
            # sockets just closed) may cap below 10k — any plateau of
            # thousands gives a valid per-connection number.
            deadline = time.time() + 120
            held = peak = 0
            rss_at_peak = rss_before
            while time.time() < deadline:
                held = srv._front_door.open_connections()
                if held >= peak:
                    peak = held
                    rss_at_peak = rss_kib()
                if held >= 9900:
                    break
                if held < peak * 0.8 and peak >= 2000:
                    break  # fleet already draining; peak was the hold
                time.sleep(0.25)
            if peak >= 2000:
                rss_idle_per_conn = (rss_at_peak - rss_before) \
                    * 1024.0 / peak
        finally:
            hold.wait(timeout=300)
        open_after = srv._front_door.open_connections()

        # -- paired async vs threaded put_p50 tripwire ---------------
        # KEEP-ALIVE clients (how every real S3 SDK talks): one
        # persistent connection per server, alternating pair order so
        # VM drift cancels. A second, per-request-CONNECT series is
        # recorded informationally (the async accept path pays a loop
        # hop per connection that the thread-spawn path does not).
        import http.client as _hc

        from minio_tpu.s3 import sigv4 as _sigv4

        srv.config.set_kv("api requests_max_read=0 requests_max_write=0"
                          " requests_deadline=10s")
        srv_t, port_t = boot("threaded", "tdisk")
        client_t = S3Client("127.0.0.1", port_t, access, secret)
        client_t.make_bucket("bench")
        body1m = os.urandom(1024 * 1024)

        def timed_put_ka(conn, sport, tag, i) -> float:
            path = f"/bench/{tag}-{i}"
            hdrs = _sigv4.sign_request(
                "PUT", path, "",
                {"host": f"127.0.0.1:{sport}",
                 "content-length": str(len(body1m))},
                body1m, access, secret, "us-east-1")
            t0 = time.perf_counter()
            conn.request("PUT", path, body=body1m, headers=hdrs)
            r = conn.getresponse()
            r.read()
            if r.status != 200:
                raise RuntimeError(f"PUT failed: {r.status}")
            return (time.perf_counter() - t0) * 1e3

        conn_a = _hc.HTTPConnection("127.0.0.1", port, timeout=60)
        conn_t = _hc.HTTPConnection("127.0.0.1", port_t, timeout=60)
        for i in range(3):  # warm both paths + connections
            timed_put_ka(conn_a, port, "wa", i)
            timed_put_ka(conn_t, port_t, "wt", i)
        deltas, lat_a, lat_t = [], [], []
        for i in range(14):
            if i % 2 == 0:  # alternate order inside each pair
                a = timed_put_ka(conn_a, port, "pa", i)
                t = timed_put_ka(conn_t, port_t, "pt", i)
            else:
                t = timed_put_ka(conn_t, port_t, "pt", i)
                a = timed_put_ka(conn_a, port, "pa", i)
            lat_a.append(a)
            lat_t.append(t)
            deltas.append(a - t)
        conn_a.close()
        conn_t.close()
        p50_a = stats.median(lat_a)
        p50_t = stats.median(lat_t)
        delta_pct = stats.median(deltas) / max(p50_t, 1e-9) * 100.0

        # Informational: per-request-connection pairs (S3Client opens
        # a fresh socket each time).
        def timed_put_conn(cl, tag, i) -> float:
            t0 = time.perf_counter()
            r = cl.put_object("bench", f"{tag}-{i}", body1m)
            if r.status != 200:
                raise RuntimeError(f"PUT failed: {r.status}")
            return (time.perf_counter() - t0) * 1e3

        rc_deltas, rc_t = [], []
        for i in range(10):
            if i % 2 == 0:
                a = timed_put_conn(client, "ra", i)
                t = timed_put_conn(client_t, "rt", i)
            else:
                t = timed_put_conn(client_t, "rt", i)
                a = timed_put_conn(client, "ra", i)
            rc_t.append(t)
            rc_deltas.append(a - t)
        reconnect_delta_pct = stats.median(rc_deltas) \
            / max(stats.median(rc_t), 1e-9) * 100.0

        fanout = _bench_fanout_fabric(stats, workdir, access, secret)

        return {
            "metric": "front_door",
            "value": round(p99_10k / max(p99_100, 1e-9), 3),
            "unit": "p99_ratio_10k_vs_100_conns",
            "sweep": sweep,
            "qps_paced": 150.0,
            "get_p99_100_ms": p99_100,
            "get_p99_10k_ms": p99_10k,
            "idle_conn_rss_bytes": round(rss_idle_per_conn, 1),
            "idle_conns_held": peak,
            "open_connections_after": open_after,
            "slot_leaks": srv.qos.foreground_inflight(),
            "put_p50_async_ms": round(p50_a, 3),
            "put_p50_threaded_ms": round(p50_t, 3),
            # Median of PAIRED keep-alive deltas over the threaded
            # median — the tripwire number (<= ~2% = the event loop is
            # free at today's workloads; this VM's unpaired drift is
            # +/-20%). Negative = the async door is FASTER (NODELAY +
            # single-segment coalesced responses).
            "put_p50_paired_delta_pct": round(delta_pct, 2),
            # Per-request-connection variant: pays the accept-path
            # loop hop per socket (real SDKs keep connections alive).
            "put_p50_reconnect_delta_pct": round(reconnect_delta_pct,
                                                 2),
            "fanout": fanout,
        }
    finally:
        if srv_t is not None:
            srv_t.stop()
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)


def _bench_fanout_fabric(stats, workdir: str, access: str,
                         secret: str) -> dict:
    """Distributed fan-out step: 2-node cluster, half of every erasure
    stripe behind peer RPC.

    (a) Paired RPC-fabric PUTs: each front-door PUT on node 0 fans its
    remote shards out over the internal RPC plane; MINIO_RPC_FABRIC is
    flipped per call (the knob is read at dispatch time) in
    alternating pair order, so VM drift cancels and the async fabric's
    cost shows up as a paired delta, not an absolute.

    (b) In-flight census: 1k concurrent peer calls submitted straight
    onto the RPC loop against a registered nap service on node 1 —
    client-side in-flight peaks near 1k while the process grows ~zero
    threads (the in-process SERVER'S bounded rpc pool is pre-warmed to
    cap so it cannot pollute the delta).
    """
    import http.client as _hc

    from minio_tpu.rpc import aio as _aio
    from minio_tpu.rpc.cluster import build_cluster_node, \
        derive_cluster_key
    from minio_tpu.rpc.transport import RPCClient, RPCRegistry
    from minio_tpu.s3 import sigv4 as _sigv4
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server

    croot = os.path.join(workdir, "cfg_fd_cluster")
    key = derive_cluster_key(access, secret)
    servers, ports = [], []
    for _ in range(2):
        reg = RPCRegistry(key)
        srv = S3Server(None, access, secret, rpc_registry=reg)
        ports.append(srv.start("127.0.0.1", 0))
        servers.append((srv, reg))
    endpoints = [f"http://127.0.0.1:{p}{croot}/n{i}/d{d}"
                 for i, p in enumerate(ports) for d in (1, 2)]

    nodes = [None, None]
    errors: list = []

    def boot_node(i):
        try:
            srv, reg = servers[i]
            node = build_cluster_node(
                endpoints, "127.0.0.1", ports[i], access, secret,
                block_size=256 * 1024, registry=reg,
                format_timeout=30.0)
            srv.set_layer(node.layer)
            nodes[i] = node
        except Exception as e:  # pragma: no cover - bench plumbing
            errors.append(e)

    threads = [threading.Thread(target=boot_node, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    if errors or any(n is None for n in nodes):
        raise RuntimeError(f"cluster boot failed: {errors}")

    rcl = None
    prev_fabric = os.environ.get("MINIO_RPC_FABRIC")
    try:
        cl0 = S3Client("127.0.0.1", ports[0], access, secret)
        if cl0.make_bucket("fan").status != 200:
            raise RuntimeError("cluster make_bucket failed")
        body = os.urandom(1024 * 1024)

        def timed_put(conn, tag, i) -> float:
            path = f"/fan/{tag}-{i}"
            hdrs = _sigv4.sign_request(
                "PUT", path, "",
                {"host": f"127.0.0.1:{ports[0]}",
                 "content-length": str(len(body))},
                body, access, secret, "us-east-1")
            t0 = time.perf_counter()
            conn.request("PUT", path, body=body, headers=hdrs)
            r = conn.getresponse()
            r.read()
            if r.status != 200:
                raise RuntimeError(f"cluster PUT failed: {r.status}")
            return (time.perf_counter() - t0) * 1e3

        def fabric_put(conn, fabric, tag, i) -> float:
            os.environ["MINIO_RPC_FABRIC"] = fabric
            try:
                return timed_put(conn, tag, i)
            finally:
                if prev_fabric is None:
                    os.environ.pop("MINIO_RPC_FABRIC", None)
                else:
                    os.environ["MINIO_RPC_FABRIC"] = prev_fabric

        conn = _hc.HTTPConnection("127.0.0.1", ports[0], timeout=60)
        for i in range(2):  # warm both fabrics' pools + codec
            fabric_put(conn, "async", "wa", i)
            fabric_put(conn, "threaded", "wt", i)
        lat_a, lat_t, deltas = [], [], []
        for i in range(12):
            if i % 2 == 0:
                a = fabric_put(conn, "async", "fa", i)
                t = fabric_put(conn, "threaded", "ft", i)
            else:
                t = fabric_put(conn, "threaded", "ft", i)
                a = fabric_put(conn, "async", "fa", i)
            lat_a.append(a)
            lat_t.append(t)
            deltas.append(a - t)
        conn.close()
        rpc_p50_a, rpc_p50_t = stats.median(lat_a), stats.median(lat_t)
        rpc_p99_a = sorted(lat_a)[-1]
        rpc_p99_t = sorted(lat_t)[-1]
        rpc_delta_pct = stats.median(deltas) \
            / max(rpc_p50_t, 1e-9) * 100.0

        # -- census: 1k concurrent peer calls, ~zero new threads -----
        class _Nap:
            def rpc_nap(self, args, payload):
                time.sleep(args.get("sleepS", 0.02))
                return {}, b""

        servers[1][1].register("benchnap", _Nap())
        rcl = RPCClient("127.0.0.1", ports[1], key)
        # Pre-warm the in-process SERVER's bounded rpc worker pool to
        # its cap so pool spin-up can't masquerade as client threads.
        warm = [_aio.RPC_LOOP.submit(_aio.call_async(
            rcl, "benchnap", "nap", {"sleepS": 0.01}, timeout=30.0))
            for _ in range(64)]
        for f in warm:
            f.result(timeout=60)
        n = 1000
        threads_before = threading.active_count()
        futs = [_aio.RPC_LOOP.submit(_aio.call_async(
            rcl, "benchnap", "nap", {"sleepS": 0.02}, timeout=60.0))
            for _ in range(n)]
        peak = 0
        threads_at_peak = threads_before
        deadline = time.time() + 30
        while time.time() < deadline:
            cur = _aio.CENSUS.current()
            if cur > peak:
                peak = cur
                threads_at_peak = threading.active_count()
            if all(f.done() for f in futs):
                break
            time.sleep(0.002)
        fails = 0
        for f in futs:
            try:
                f.result(timeout=120)
            except Exception:
                fails += 1
        extra_threads = threads_at_peak - threads_before
        if fails:
            raise RuntimeError(f"{fails}/{n} census peer calls failed")
        if peak < 900:
            raise RuntimeError(
                f"census never saw the fleet in flight: peak {peak}")
        if extra_threads > 8:
            raise RuntimeError(
                f"async fabric grew {extra_threads} threads at {peak} "
                "in-flight peer calls — the zero-thread claim broke")
        return {
            "rpc_put_p50_async_ms": round(rpc_p50_a, 3),
            "rpc_put_p50_threaded_ms": round(rpc_p50_t, 3),
            "rpc_put_p99_async_ms": round(rpc_p99_a, 3),
            "rpc_put_p99_threaded_ms": round(rpc_p99_t, 3),
            # Median PAIRED delta over the threaded median — negative
            # = the async fabric is faster end-to-end.
            "rpc_put_paired_delta_pct": round(rpc_delta_pct, 2),
            "census_calls": n,
            "census_peak_inflight": peak,
            "threads_before": threads_before,
            "threads_at_peak": threads_at_peak,
            "extra_threads_at_peak": extra_threads,
        }
    finally:
        if prev_fabric is None:
            os.environ.pop("MINIO_RPC_FABRIC", None)
        else:
            os.environ["MINIO_RPC_FABRIC"] = prev_fabric
        if rcl is not None:
            rcl.close()
        for srv, _reg in servers:
            srv.stop()
        shutil.rmtree(croot, ignore_errors=True)


def bench_crash_recovery(np, workdir: str) -> dict:
    """PR-11 acceptance: a real `python -m minio_tpu server` is
    SIGKILL-ed mid-PUT-loop and restarted on the same disks; report
    (a) time-to-first-served-request after the restart exec, (b) the
    boot recovery sweep's duration + census, and (c) the `storage
    fsync=on` commit-path overhead as PAIRED on/off put_p50 deltas
    (PR-4's method — this VM drifts +/-20% on second timescales, so
    only paired deltas survive the noise)."""
    import signal
    import socket
    import subprocess
    import sys as _sys

    from minio_tpu.s3.admin_client import AdminClient
    from minio_tpu.s3.client import S3Client

    access, secret = "benchadmin", "benchadmin-secret"
    # Deliberately DISK-backed (unlike the other configs' tmpfs):
    # crash recovery is about durable media, and `fsync=on` measured
    # on tmpfs reads ~0 — the number would flatter the knob.
    root = tempfile.mkdtemp(prefix="minio-tpu-crash-")
    disks = [os.path.join(root, f"d{i}") for i in range(1, 7)]
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ, MINIO_ACCESS_KEY=access,
               MINIO_SECRET_KEY=secret, JAX_PLATFORMS="cpu",
               MINIO_RECOVERY_TMP_AGE="1",
               MINIO_CRAWLER_INTERVAL="3600",
               MINIO_HEAL_NEWDISK_INTERVAL="3600")
    log_path = os.path.join(root, "node.log")
    os.makedirs(root, exist_ok=True)

    def boot():
        log = open(log_path, "ab")
        p = subprocess.Popen(
            [_sys.executable, "-m", "minio_tpu", "server", *disks,
             "--address", f"127.0.0.1:{port}"],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        log.close()
        return p

    def wait_serving(client, key, want, timeout=90.0):
        t0 = time.perf_counter()
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                g = client.get_object("bench", key)
                if g.status == 200 and g.body == want:
                    return time.perf_counter() - t0
            except Exception:
                pass
            time.sleep(0.02)
        raise RuntimeError("restarted server never served")

    client = S3Client("127.0.0.1", port, access, secret)
    adm = AdminClient("127.0.0.1", port, access, secret)
    rng = np.random.default_rng(11)
    body = rng.integers(0, 256, 256 * 1024).astype(np.uint8).tobytes()
    proc = boot()
    try:
        wait_serving_boot = time.time() + 90
        while time.time() < wait_serving_boot:
            try:
                if client.make_bucket("bench").status in (200, 409):
                    break
            except Exception:
                pass
            time.sleep(0.1)  # every retry backs off, not just refusals
        client.put_object("bench", "anchor", body)

        # Kill -9 mid-PUT-loop: the loop runs in its own thread so the
        # SIGKILL lands while a PUT is actually in flight on the
        # commit path (a synchronous loop is ~always between requests
        # at these object sizes).
        counted = [0]
        halt = threading.Event()

        def put_loop():
            put_client = S3Client("127.0.0.1", port, access, secret)
            while not halt.is_set():
                try:
                    put_client.put_object(
                        "bench", f"k-{counted[0]}", body)
                    counted[0] += 1
                except Exception:
                    return  # the kill landed mid-request
        # mtpu-lint: disable=R1 -- bench driver thread, no request context to carry
        putter = threading.Thread(target=put_loop, daemon=True)
        putter.start()
        deadline = time.time() + 30
        while time.time() < deadline and counted[0] < 20:
            time.sleep(0.01)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)
        halt.set()
        putter.join(timeout=10)
        killed_after = counted[0]
        time.sleep(1.2)  # orphans must clear the 1s recovery age gate

        t_restart = time.perf_counter()
        proc = boot()
        wait_serving(client, "anchor", body)
        ttfs_s = time.perf_counter() - t_restart
        rep = adm.recovery()
        sweep_ms = sum(s_.get("durationS", 0.0)
                       for s_ in rep["sweeps"]) * 1e3
        census = {k: sum(s_.get(k, 0) for s_ in rep["sweeps"])
                  for k in ("found", "cleaned", "stageFiles",
                            "journalReplayed")}
        census["requeued"] = sum(len(s_.get("requeued", []))
                                 for s_ in rep["sweeps"])

        # Paired fsync on/off PUT p50 (the toggle is one config write,
        # applied live through storage/xl.py set_fsync).
        lat_on: list = []
        lat_off: list = []
        for i in range(24):
            order = (True, False) if i % 2 == 0 else (False, True)
            for on in order:
                adm.set_config_kv(
                    f"storage fsync={'on' if on else 'off'}")
                t0 = time.perf_counter()
                r = client.put_object("bench", f"fs-{i}-{int(on)}",
                                      body)
                dt = time.perf_counter() - t0
                if r.status != 200:
                    raise RuntimeError(f"fsync PUT failed: {r.status}")
                (lat_on if on else lat_off).append(dt)
        adm.set_config_kv("storage fsync=off")
        p50_on = statistics.median(lat_on) * 1e3
        p50_off = statistics.median(lat_off) * 1e3
        delta = statistics.median(
            [(a - b) * 1e3 for a, b in zip(lat_on, lat_off)])
        return {
            "metric": "crash_recovery_time_to_first_served",
            "value": round(ttfs_s * 1e3, 1), "unit": "ms",
            "kill_after_puts": killed_after,
            "object_bytes": len(body),
            "workdir": "disk",
            "recovery_sweep_ms": round(sweep_ms, 2),
            "recovery_census": census,
            # storage fsync=on paired overhead (default-off ships; the
            # knob buys power-cut durability at this measured cost).
            "fsync_on_put_p50_ms": round(p50_on, 3),
            "fsync_off_put_p50_ms": round(p50_off, 3),
            "fsync_overhead_pct": round(
                delta / max(p50_off, 1e-9) * 100.0, 2),
        }
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
            try:
                proc.wait(timeout=10)
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)


def bench_select_scan(np, workdir: str) -> dict:
    """Columnar S3 Select scan engine vs the row-engine oracle.

    Two paired fixtures (numeric-heavy 256MiB Parquet, string-heavy
    256MiB CSV), scan GiB/s both ways with BYTE-IDENTICAL payload
    verification at the paired point, a selectivity sweep
    (0.1%/10%/90% pass rates) on the columnar side, and a brownout
    phase: a capped `select` class flooded with scans must shed 503
    while paired fg PUT/GET p99 stays within noise of the no-scan
    baseline.  backend_mix is stamped by the config harness like
    every other config, so a host-mode run can't masquerade as a
    device number."""
    from minio_tpu.s3select import parquet as pqm
    from minio_tpu.s3select.message import decode_messages
    from minio_tpu.s3select.select import parse_request, run_select

    def _req(expr: str, inp: str) -> dict:
        from xml.sax.saxutils import escape
        xml = ("<SelectObjectContentRequest><Expression>"
               f"{escape(expr)}</Expression>"
               "<ExpressionType>SQL</ExpressionType>"
               f"<InputSerialization>{inp}</InputSerialization>"
               "<OutputSerialization><JSON/></OutputSerialization>"
               "</SelectObjectContentRequest>")
        return parse_request(xml.encode())

    def timed_select(req: dict, data: bytes, engine: str):
        os.environ["MINIO_SELECT_ENGINE"] = engine
        try:
            t0 = time.perf_counter()
            body = run_select(req, data)
            wall = time.perf_counter() - t0
        finally:
            os.environ.pop("MINIO_SELECT_ENGINE", None)
        msgs = decode_messages(body)
        if msgs and msgs[0]["headers"].get(":message-type") == "error":
            raise RuntimeError(f"select errored: {msgs[0]['headers']}")
        payload = b"".join(
            m["payload"] for m in msgs
            if m["headers"].get(":event-type") == "Records")
        return wall, payload

    out: dict = {"metric": "select_scan",
                 "unit": "columnar_over_row_speedup"}

    # -- numeric-heavy 256MiB Parquet (the acceptance config) ----------
    n = 8_388_608  # 4 x float64 columns = 256 MiB of data
    rng = np.random.default_rng(14)
    cols = [pqm.Column(c, pqm.DOUBLE, optional=False)
            for c in ("c0", "c1", "c2", "c3")]
    pdata = pqm.write_parquet_columns(
        cols, {c.name: rng.uniform(0.0, 1.0, n) for c in cols}, n)
    pq_gib = len(pdata) / (1 << 30)
    sweep = []
    row_wall = row_payload = None
    col_wall_paired = None
    for sel in (0.001, 0.1, 0.9):
        req = _req(f"SELECT c1 FROM S3Object WHERE c0 < {sel}",
                   "<Parquet/>")
        wall, payload = timed_select(req, pdata, "")
        if sel == 0.1:
            # Paired point: the row oracle runs the SAME query on the
            # SAME bytes immediately after, and the payloads must be
            # byte-identical (the differential suite, at full scale).
            # Row wall time is selectivity-independent (decode
            # dominates), so one row run prices all three points.
            col_wall_paired = wall
            row_wall, row_payload = timed_select(req, pdata, "row")
            if row_payload != payload:
                raise RuntimeError(
                    "columnar payload diverged from the row oracle "
                    f"({len(payload)} vs {len(row_payload)} bytes)")
        sweep.append({
            "selectivity": sel,
            "columnar_s": round(wall, 3),
            "columnar_gibs": round(pq_gib / wall, 3),
        })
    pq_speedup = row_wall / col_wall_paired
    out["value"] = round(pq_speedup, 2)
    out["parquet"] = {
        "bytes": len(pdata), "rows": n,
        "row_s": round(row_wall, 3),
        "row_gibs": round(pq_gib / row_wall, 4),
        "columnar_gibs": round(pq_gib / col_wall_paired, 3),
        "speedup": round(pq_speedup, 2),
        "selectivity_sweep": sweep,
    }
    if pq_speedup < 5.0:
        raise RuntimeError(
            f"select_scan speedup {pq_speedup:.2f}x < 5x on the "
            "numeric-heavy 256MiB Parquet config")

    # -- string-heavy CSV ----------------------------------------------
    # 96MiB, not 256: the ROW oracle needs ~4 min for 256MiB of CSV
    # (the whole reason this engine exists) and the paired run prices
    # both sides; the acceptance-gated 256MiB config is the Parquet
    # one above.
    words = np.asarray(["alphaville", "betatronic", "gammaray",
                        "deltaforce", "epsilonic", "zetapotential",
                        "etacarinae", "thetawaves"])
    rows_csv = 2_100_000   # ~96 MiB of ~48-byte lines
    w1 = words[rng.integers(0, len(words), rows_csv)]
    w2 = words[rng.integers(0, len(words), rows_csv)]
    nums = rng.integers(0, 100000, rows_csv).astype("U6")
    lines = np.char.add(np.char.add(np.char.add(np.char.add(
        w1, ","), nums), ","), w2)
    cdata = ("h1,h2,h3\n" + "\n".join(lines.tolist()) + "\n").encode()
    del lines, w1, w2, nums
    csv_gib = len(cdata) / (1 << 30)
    creq = _req("SELECT h2 FROM S3Object WHERE h1 LIKE 'gamma%' "
                "AND h2 > 90000",
                "<CSV><FileHeaderInfo>USE</FileHeaderInfo></CSV>")
    c_wall, c_payload = timed_select(creq, cdata, "")
    r_wall, r_payload = timed_select(creq, cdata, "row")
    if r_payload != c_payload:
        raise RuntimeError("CSV columnar payload diverged from the "
                           "row oracle")
    out["csv"] = {
        "bytes": len(cdata), "rows": rows_csv,
        "row_s": round(r_wall, 3),
        "row_gibs": round(csv_gib / r_wall, 4),
        "columnar_s": round(c_wall, 3),
        "columnar_gibs": round(csv_gib / c_wall, 3),
        "speedup": round(r_wall / c_wall, 2),
    }
    del cdata

    # -- brownout: capped select class vs fg PUT/GET -------------------
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.obs.metrics2 import METRICS2
    from minio_tpu.s3.client import S3Client
    from minio_tpu.s3.server import S3Server
    from minio_tpu.storage.xl import XLStorage
    root = os.path.join(workdir, "cfgsel")
    disks = [XLStorage(os.path.join(root, f"disk{i}"))
             for i in range(6)]
    layer = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
    srv = S3Server(layer, "benchadmin", "benchadmin-secret")
    port = srv.start()
    try:
        # The server boot kicks the background probe ladder (RS rungs,
        # jit compiles, select probes); on a 2-core box it would crush
        # the paired p99 measurement below — drain it first.
        from minio_tpu.ops.autotune import AUTOTUNE as _AT
        _AT.ensure_probed(background=False)
        client = S3Client("127.0.0.1", port, "benchadmin",
                          "benchadmin-secret")
        client.make_bucket("selbench")
        # a 2MiB slice of the parquet fixture as the scan target
        small_n = 65_536
        sdata = pqm.write_parquet_columns(
            cols, {c.name: rng.uniform(0.0, 1.0, small_n)
                   for c in cols}, small_n)
        client.put_object("selbench", "t.parquet", sdata)
        body = rng.integers(0, 256, 1024 * 1024).astype(
            np.uint8).tobytes()
        for i in range(4):
            client.put_object("selbench", f"warm-{i}", body)
        sel_xml = (
            "<SelectObjectContentRequest><Expression>"
            "SELECT c1 FROM S3Object WHERE c0 &lt; 0.5"
            "</Expression><ExpressionType>SQL</ExpressionType>"
            "<InputSerialization><Parquet/></InputSerialization>"
            "<OutputSerialization><JSON/></OutputSerialization>"
            "</SelectObjectContentRequest>").encode()

        def fg_lat(tag: str, ops: int = 40):
            put, get = [], []
            for i in range(ops):
                t0 = time.perf_counter()
                r = client.put_object("selbench", f"{tag}-{i}", body)
                put.append(time.perf_counter() - t0)
                if r.status != 200:
                    raise RuntimeError(f"PUT {r.status}")
                t0 = time.perf_counter()
                r = client.get_object("selbench", f"{tag}-{i}")
                get.append(time.perf_counter() - t0)
                if r.status != 200:
                    raise RuntimeError(f"GET {r.status}")
            return put, get

        def p99(xs):
            return sorted(xs)[max(0, int(len(xs) * 0.99) - 1)] * 1e3

        put_off1, get_off1 = fg_lat("off1")
        srv.config.set_kv("api requests_max_select=1 "
                          "requests_deadline=250ms")
        stop = threading.Event()
        shed = [0]
        okc = [0]

        def scan_forever():
            sc = S3Client("127.0.0.1", port, "benchadmin",
                          "benchadmin-secret")
            while not stop.is_set():
                r = sc.request("POST", "/selbench/t.parquet",
                               query="select=&select-type=2",
                               body=sel_xml)
                if r.status == 503:
                    shed[0] += 1
                elif r.status == 200:
                    okc[0] += 1

        threads = [threading.Thread(target=scan_forever, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # flood reaches the cap
        put_on, get_on = fg_lat("on")
        stop.set()
        for t in threads:
            t.join(timeout=30)
        srv.config.set_kv("api requests_max_select=0 "
                          "requests_deadline=10s")
        put_off2, get_off2 = fg_lat("off2")
        put_off = put_off1 + put_off2
        get_off = get_off1 + get_off2
        if shed[0] < 1:
            raise RuntimeError(
                "capped select class never shed under the scan flood "
                f"(ok={okc[0]})")
        put_ratio = p99(put_on) / max(p99(put_off), 1e-9)
        get_ratio = p99(get_on) / max(p99(get_off), 1e-9)
        out["brownout"] = {
            "select_cap": 1, "scan_threads": 4,
            "select_ok": okc[0], "select_shed_503": shed[0],
            "fg_put_p99_off_ms": round(p99(put_off), 2),
            "fg_put_p99_on_ms": round(p99(put_on), 2),
            "fg_put_p99_ratio": round(put_ratio, 3),
            "fg_get_p99_off_ms": round(p99(get_off), 2),
            "fg_get_p99_on_ms": round(p99(get_on), 2),
            "fg_get_p99_ratio": round(get_ratio, 3),
            "select_sheds_total": METRICS2.get(
                "minio_tpu_v2_qos_shed_total",
                {"class": "select", "reason": "wait-deadline"}),
        }
        # Two python processes' worth of work on 2 cores: allow real
        # scheduling noise, catch real starvation.
        if put_ratio > 3.0 or get_ratio > 3.0:
            raise RuntimeError(
                "fg p99 degraded past noise under the capped scan "
                f"flood (put x{put_ratio:.2f}, get x{get_ratio:.2f})")
        out["fg_p99_ratio"] = round(max(put_ratio, get_ratio), 3)
    finally:
        srv.stop()
        shutil.rmtree(root, ignore_errors=True)

    from minio_tpu.ops.autotune import AUTOTUNE
    out["select_plan"] = AUTOTUNE.plan_compact().get("select_scan", {})
    return out


class _DeviceHunt(threading.Thread):
    """Background device acquisition for the WHOLE bench run.

    Round-4 verdict weak #1: bench.py probed twice in the first five
    minutes and gave up, so an outage at bench time erased the round's
    kernels from the record. Now a daemon thread keeps probing (each
    probe is a subprocess with a hard timeout — the relay hangs rather
    than refusing) and, the moment a device answers, runs the full
    device bench (tools/device_bench.py) in a subprocess and persists
    the result to the watcher state file. The main process stays pinned
    to CPU throughout, so it can never hang on the relay.
    """

    def __init__(self):
        super().__init__(daemon=True, name="device-hunt")
        self.result: dict | None = None
        self.device_seen = False
        self.last_error = ""
        self.probes = 0
        # Named _halt, not _stop: threading.Thread has a private
        # _stop() METHOD that join() calls internally; shadowing it
        # with an Event makes join() raise once the thread finishes.
        self._halt = threading.Event()

    def run(self) -> None:
        from tools import device_watch as dw
        while not self._halt.is_set():
            self.probes += 1
            ok, err = dw.probe()
            if self._halt.is_set():
                return
            if not ok:
                self.last_error = f"device-probe: {err}"
                if "no accelerator" in err:
                    return  # deterministic: this host has no device
                # Probes run niced (device_watch.probe), but even so:
                # a hung relay means ~150s per attempt, so within one
                # bench window few retries are possible anyway.
                self._halt.wait(120)
                continue
            self.device_seen = True
            _progress("device up; running device bench subprocess")
            res = dw.run_device_bench()
            if res.get("ok"):
                res["measured_at"] = int(time.time())
                self.result = res
                try:  # persist so later runs see it even if relay drops
                    dw.merge_result(res)
                except Exception:
                    pass
                return
            self.last_error = f"device-bench: {res.get('error')}"
            self._halt.wait(30)

    def stop(self) -> None:
        self._halt.set()


# --- config: regen_repair — RS vs REGEN heal repair traffic -----------------


def bench_regen_repair(np, workdir: str) -> dict:
    """Paired RS-vs-REGEN heal of the SAME dataset on a 4+2 layout:
    identical objects stored under both classes, the same single-disk
    shard loss inflicted on each, and each class healed separately so
    the repair-traffic ledger (erasure/regen/repair.REPAIR_BYTES)
    yields per-mode bytes moved (net + disk) and per-mode heal GiB/s.
    The headline value is the rs/regen disk-traffic ratio — the
    repair-by-transfer construction predicts B/d (RS moves ~1 block
    per repaired block, regen moves d stripe rows of block/B bytes):
    for 4+2, B=14, d=5, exactly 2.8x.  The ratio is measured on one
    box so VM drift cancels (host-mode caveat: absolute GiB/s is
    whatever lane the autotuner picked — trust the paired ratio,
    which counts bytes, not seconds)."""
    from minio_tpu.erasure.engine import ErasureObjects
    from minio_tpu.erasure.regen.repair import REPAIR_BYTES
    from minio_tpu.storage.metadata import REGEN_ALGORITHM
    from minio_tpu.storage.xl import XLStorage

    root = os.path.join(workdir, "cfg-regen")
    n_objects, obj_bytes = 4, 24 * 1024 * 1024  # 96 MiB per class
    rng = np.random.default_rng(11)
    try:
        roots = [os.path.join(root, f"disk{i}") for i in range(6)]
        disks = [XLStorage(r) for r in roots]
        eng = ErasureObjects(disks, 4, 2, block_size=1024 * 1024)
        eng.make_bucket("bench")
        for i in range(n_objects):
            body = rng.integers(0, 256, obj_bytes).astype(
                np.uint8).tobytes()
            eng.put_object("bench", f"rs-{i}", body)
            eng.put_object("bench", f"regen-{i}", body,
                           algorithm=REGEN_ALGORITHM)

        def lose_and_heal(prefix: str) -> tuple[dict, float]:
            for i in range(n_objects):
                shutil.rmtree(os.path.join(roots[0], "bench",
                                           f"{prefix}-{i}"))
            REPAIR_BYTES.reset()
            t0 = time.perf_counter()
            for i in range(n_objects):
                res = eng.healer.heal_object("bench", f"{prefix}-{i}")
                if not res.healed_disks:
                    raise RuntimeError(
                        f"heal of {prefix}-{i} repaired nothing")
            dt = time.perf_counter() - t0
            return REPAIR_BYTES.snapshot(), dt

        rs_bytes, rs_dt = lose_and_heal("rs")
        regen_bytes, regen_dt = lose_and_heal("regen")
        total = n_objects * obj_bytes
        ratio_disk = rs_bytes["rs"]["disk"] / regen_bytes["regen"]["disk"]
        ratio_net = rs_bytes["rs"]["net"] / regen_bytes["regen"]["net"]
        if min(ratio_disk, ratio_net) < 2.0:
            raise RuntimeError(
                f"regen repair reduction below 2x (disk {ratio_disk:.2f}, "
                f"net {ratio_net:.2f})")
        return {"metric": "regen_repair", "layout": "4+2",
                "value": round(ratio_disk, 3), "unit": "x_less_disk",
                "repair_bytes": {"rs": rs_bytes["rs"],
                                 "regen": regen_bytes["regen"]},
                "ratio_net": round(ratio_net, 3),
                "rs_heal_gibps": round(total / rs_dt / (1 << 30), 3),
                "regen_heal_gibps": round(
                    total / regen_dt / (1 << 30), 3),
                "total_bytes_per_class": total,
                "note": "ratio counts bytes (drift-free); GiB/s is "
                        "host-lane dependent"}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main() -> None:
    import numpy as np

    errors: dict[str, str] = {}

    # The main process NEVER touches the relay: pin in-process jax to
    # CPU; every device measurement happens in the hunt's subprocess.
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        cache_dir = os.environ.get(
            "MINIO_TPU_JIT_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "minio_tpu_jit"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    hunt = _DeviceHunt()
    hunt.start()

    out: dict = {"metric": "rs_encode+decode_8+4_1MiB_GiB_per_s_per_chip",
                 "value": 0.0, "unit": "GiB/s", "vs_baseline": 0.0,
                 "baseline": "host codec (C++ nibble-shuffle native/rs.cc "
                             "when built; stand-in for the reference's "
                             "AVX2 reedsolomon)"}

    # Honest degraded-mode north star: the engine's REAL host fallback
    # (native C++ codec through the same folded applies the serving path
    # uses), not jit-on-CPU. Overridden below if a device answers.
    _progress("host-native north star")
    host_native = 0.0
    try:
        host_native = bench_host_native_north_star(np)
        out["value"] = round(host_native, 3)
        out["vs_baseline"] = 1.0
        out["value_source"] = "host-native"
    except Exception as exc:  # noqa: BLE001
        errors["north_star_host"] = f"{type(exc).__name__}: {exc}"
    out["host_native_GiBs"] = round(host_native, 3)

    # All five configs in host mode (device_asserted=False); the hunt
    # measures the device-backed variants concurrently in its subprocess.
    # Workdir on tmpfs when available: the VM disk's writeback
    # throttling swings single-shard writes 2-12ms run to run, drowning
    # the codec/engine signal these configs track (labeled so the
    # record says what was measured).
    workdir = tempfile.mkdtemp(
        prefix="minio-tpu-bench-",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None)
    out["workdir"] = ("tmpfs" if workdir.startswith("/dev/shm")
                      else "disk")
    # Which data-plane pipeline (utils/pipeline.py PIPE_STATS name) each
    # config exercises; its overlap factor (stage busy seconds / wall
    # seconds, > 1.0 = stages genuinely overlapped) is attached to the
    # config record so BENCH_r0N.json files track pipelining
    # regressions. put_p50's 1MiB objects fit one encode batch, so its
    # pipeline never engages and no factor is reported there.
    from minio_tpu.utils.pipeline import PIPE_STATS, PipelineStats
    # Silent-degradation tripwires per config: slowlog captures during
    # the run plus the drive-health suspect/faulty census afterwards —
    # a future regression that makes a config quietly slow (or drags
    # one disk) shows up in the BENCH record, not just in the value.
    from minio_tpu.obs.drivemon import DRIVEMON
    from minio_tpu.obs.kernprof import KERNPROF
    from minio_tpu.obs.slowlog import SLOWLOG
    from minio_tpu.obs.watchdog import WATCHDOG
    config_pipeline = {"put_p50": "put", "multipart": "put",
                       "get_2lost": "get", "heal": "heal"}
    configs: list[dict] = []
    for name, fn in (("put_p50", lambda: bench_put_p50(np, workdir)),
                     ("codec_autotune",
                      lambda: bench_codec_autotune(np)),
                     ("encode_verify",
                      lambda: bench_encode_verify(np, False)),
                     ("multipart", lambda: bench_multipart(np, workdir)),
                     ("get_2lost",
                      lambda: bench_get_with_loss(np, workdir, False)),
                     ("heal", lambda: bench_heal(np, workdir, False)),
                     ("degraded_tail",
                      lambda: bench_degraded_tail(np, workdir)),
                     ("qos_brownout",
                      lambda: bench_qos_brownout(np, workdir)),
                     ("hot_get",
                      lambda: bench_hot_get(np, workdir)),
                     ("noisy_neighbor",
                      lambda: bench_noisy_neighbor(np, workdir)),
                     ("front_door",
                      lambda: bench_front_door(np, workdir)),
                     ("loop_health",
                      lambda: bench_loop_health(np, workdir)),
                     ("crash_recovery",
                      lambda: bench_crash_recovery(np, workdir)),
                     ("select_scan",
                      lambda: bench_select_scan(np, workdir)),
                     ("regen_repair",
                      lambda: bench_regen_repair(np, workdir))):
        _progress(f"config {name} (host mode)")
        pipe = config_pipeline.get(name)
        factor_box: dict = {}

        def run_measured(fn=fn, pipe=pipe, factor_box=factor_box):
            # Snapshot per ATTEMPT: a failed first try's partial
            # pipeline stats must not pollute the successful run's
            # overlap factor. The drive monitor RESETS per attempt —
            # a suspect frozen from an earlier config's destroyed
            # disks must not leak into this config's tripwire.
            DRIVEMON.reset()
            # The watchdog resets with it: a firing alert frozen from
            # an earlier config's deliberate faults must not leak into
            # this config's alerts_fired tripwire.
            WATCHDOG.reset()
            before = PIPE_STATS.snapshot()
            slow_before = SLOWLOG.total
            mix_before = KERNPROF.mix_snapshot()
            out = fn()
            if pipe is not None:
                factor_box["factor"] = PipelineStats.overlap_factor(
                    before, PIPE_STATS.snapshot(), pipe)
            factor_box["slowlog"] = SLOWLOG.total - slow_before
            factor_box["mix"] = _backend_mix(mix_before,
                                             KERNPROF.mix_snapshot())
            return out

        res, err = _retrying(run_measured, name, attempts=2,
                             base_sleep=1.0)
        if res is not None:
            res["device_asserted"] = False
            if factor_box.get("factor") is not None:
                res["overlap_factor"] = round(factor_box["factor"], 3)
            res["slowlog_entries"] = factor_box.get("slowlog", 0)
            # Which dispatch backend actually did this config's math
            # (kernprof byte fractions): a host-mode run can never
            # masquerade as a device number again — the exact r04/r05
            # ambiguity the ROADMAP bench caveat flags.
            res["backend_mix"] = factor_box.get("mix", {})
            # The codec dispatch plan in force when this config ran —
            # the lane story behind the backend_mix fractions.
            from minio_tpu.ops.autotune import AUTOTUNE as _AT
            res.setdefault("codec_plan", _AT.plan_compact())
            suspect, faulty = DRIVEMON.counts()
            res["drive_suspect"] = suspect
            res["drive_faulty"] = faulty
            # Watchdog tripwire (like drive_suspect): firing
            # transitions during this config. qos_brownout fires the
            # shed built-in BY DESIGN and asserts it resolves; any
            # other config alerting is a silent regression surfaced
            # in the BENCH record.
            res["alerts_fired"] = WATCHDOG.fired_total
            configs.append(res)
        else:
            errors[name] = err or "unknown"
    shutil.rmtree(workdir, ignore_errors=True)

    # Wait for the hunt: up to MINIO_TPU_BENCH_DEVICE_WAIT seconds from
    # bench start (default 900) — extended when a probe has already
    # succeeded, because then a real number is minutes away.
    deadline = _T0 + float(os.environ.get("MINIO_TPU_BENCH_DEVICE_WAIT",
                                          "900"))
    while hunt.is_alive() and hunt.result is None:
        now = time.monotonic()
        limit = deadline + (2400 if hunt.device_seen else 0)
        if now >= limit:
            break
        hunt.join(timeout=min(10.0, limit - now))
    hunt.stop()

    device_res = hunt.result
    source = "device-live"
    if device_res is None:
        # Relay down for this whole run: fall back to the best device-
        # backed result the round-long watcher ever persisted.
        from tools import device_watch as dw
        state = dw.load_state()
        if state.get("best", {}).get("ok"):
            device_res = state["best"]
            age = int(time.time()) - int(state.get("best_at", 0))
            source = f"device-persisted(age_s={age})"
        if hunt.last_error:
            errors["device"] = hunt.last_error
        errors["device_probes"] = (
            f"{hunt.probes} probes; device answered but its bench "
            "failed" if hunt.device_seen
            else f"{hunt.probes} probes, none answered")

    if device_res is not None:
        ns = device_res.get("north_star", {})
        if ns.get("value"):
            out["value"] = ns["value"]
            out["kernel"] = ns.get("kernel")
            out["value_source"] = source
            base = ns.get("host_native_GiBs") or host_native
            out["vs_baseline"] = round(ns["value"] / max(base, 1e-9), 2)
        out["device"] = device_res

    from minio_tpu.ops import batching
    out["configs"] = configs
    out["stats"] = batching.STATS.snapshot()
    # Whole-run dispatch honesty stamp: byte fractions per kernprof
    # backend plus the backend health states at exit. The device hunt
    # measures in its own subprocess, so this records what THIS
    # process's configs actually ran on.
    out["backend_mix"] = _backend_mix({}, KERNPROF.mix_snapshot())
    out["kernel_backends"] = {
        b: info["state"]
        for b, info in KERNPROF.snapshot()["backends"].items()}
    # Whole-run codec-plan stamp (next to backend_mix): which lane the
    # measured planner routed each (kernel, bucket) to by run end.
    from minio_tpu.ops.autotune import AUTOTUNE
    out["codec_plan"] = AUTOTUNE.plan_compact()
    # n_devices-aware scaling curve ({} on a single-device box).
    scaling = bench_north_star_scaling(np)
    if scaling:
        out["north_star_scaling"] = scaling
    if errors:
        out["errors"] = errors
    print(json.dumps(out))


if __name__ == "__main__":
    main()
