"""minio_tpu — a TPU-native, S3-compatible, erasure-coded object store.

A from-scratch rebuild of the capabilities of chiefsh/minio (pure Go,
reference at /root/reference) designed TPU-first:

- The data plane (Reed-Solomon GF(2^8) encode/decode, bitrot hashing) runs as
  batched JAX/Pallas kernels on TPU: GF(2^8) linear algebra is lowered to
  GF(2) bit-plane matmuls that map directly onto the MXU, instead of the
  reference's table-lookup SIMD assembly (klauspost/reedsolomon, ref
  cmd/erasure-coding.go).
- The host runtime (S3 front end, topology, disk I/O, quorum orchestration,
  locks, healing) is Python + C++ where hot.
- Multi-chip scaling uses jax.sharding.Mesh + shard_map over batch/shard axes;
  multi-host control plane is REST like the reference (cmd/routers.go:26-37).

Layout:
  ops/       TPU + CPU kernels (GF(2^8), Reed-Solomon, HighwayHash, batching)
  models/    declarative data-plane pipelines (the "flagship model" = EC pipeline)
  parallel/  mesh/sharding + host-side parallel quorum machinery
  erasure/   erasure codec orchestration, metadata quorum, healing
  storage/   per-disk storage (xl-storage analog), on-disk formats
  s3/        S3 API surface: SigV4, routers, handlers, errors
  utils/     small shared helpers
"""

__version__ = "0.1.0"

# Opt-in runtime lock-order sanitizer: MTPU_LOCKTRACE=1 in the
# environment traces every lock constructed after this import (a
# server booted with the flag runs fully sanitized; unset, this is one
# env read). tests/conftest.py also calls it explicitly so the install
# lands before jax fills the import cache.
from .utils.locktrace import maybe_install as _locktrace_maybe_install

_locktrace_maybe_install()
del _locktrace_maybe_install
