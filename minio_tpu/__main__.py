"""CLI entry: `python -m minio_tpu server /data/disk{1...4}`
(ref main.go:36, cmd/server-main.go:388 serverMain)."""

from __future__ import annotations

import argparse
import os
import signal
import sys


def _honor_jax_platforms_env() -> None:
    """A site may pin the JAX platform via sitecustomize, defeating the
    JAX_PLATFORMS environment variable; re-assert the operator's choice
    through jax.config before any device use (e.g. JAX_PLATFORMS=cpu to
    keep server startup off the accelerator)."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    try:
        import jax
        jax.config.update("jax_platforms", want)
    except Exception:
        pass  # jax may be absent/initialized; codec falls back itself


def main(argv: list[str] | None = None) -> int:
    _honor_jax_platforms_env()
    parser = argparse.ArgumentParser(
        prog="minio-tpu",
        description="TPU-native S3-compatible erasure-coded object store")
    sub = parser.add_subparsers(dest="command", required=True)

    srv = sub.add_parser("server", help="start the object-store server")
    srv.add_argument("disks", nargs="+",
                     help="disk paths; ellipses supported: /data/d{1...4}")
    srv.add_argument("--address", default="0.0.0.0:9000",
                     help="listen address (host:port)")
    srv.add_argument("--block-size", type=int, default=None,
                     help="erasure stripe block size in bytes")

    gw = sub.add_parser("gateway",
                        help="serve S3 over a foreign backend "
                             "(ref cmd/gateway-main.go)")
    gw.add_argument("backend",
                    choices=["nas", "s3", "azure", "gcs", "hdfs"])
    gw.add_argument("target",
                    help="nas: a directory; s3/azure/gcs/hdfs: "
                         "http(s)://host:port of the backend "
                         "(azure: MINIO_AZURE_ACCOUNT/_KEY; "
                         "gcs: MINIO_GCS_PROJECT/_TOKEN; "
                         "hdfs: MINIO_HDFS_ROOT/_USER env)")
    gw.add_argument("--address", default="0.0.0.0:9000")
    gw.add_argument("--meta-dir", default="",
                    help="s3 gateway: local dir for bucket metadata "
                         "(default <target-hash> under ~/.minio-tpu)")
    up = sub.add_parser("update",
                        help="check for / apply a newer release "
                             "(ref cmd/update.go)")
    up.add_argument("--endpoint",
                    default=os.environ.get(
                        "MINIO_UPDATE_URL",
                        "https://dl.min.io"),
                    help="release endpoint serving "
                         "/minio-tpu/release.json")
    up.add_argument("--dry-run", action="store_true",
                    help="only report whether an update exists")

    args = parser.parse_args(argv)

    if args.command == "server":
        return _serve(args)
    if args.command == "gateway":
        return _serve_gateway(args)
    if args.command == "update":
        return _update(args)
    return 2


def _update(args) -> int:
    from . import __version__
    from .utils.update import UpdateError, run_update
    try:
        info = run_update(args.endpoint, dry_run=args.dry_run)
    except UpdateError as e:
        print(f"update failed: {e}", file=sys.stderr)
        return 1
    if not info["newer"]:
        print(f"minio-tpu {__version__} is up to date "
              f"(latest: {info['latest'] or 'unknown'})")
    elif info["applied"]:
        print(f"updated {info['current']} -> {info['latest']}; "
              "restart the server to pick up the new code")
    else:
        print(f"update available: {info['current']} -> "
              f"{info['latest']} (run without --dry-run to apply)")
    return 0


def _parse_address(address: str) -> tuple[str, int]:
    host, _, port_s = address.rpartition(":")
    return host or "0.0.0.0", int(port_s)


def _env_creds() -> tuple[str, str]:
    return (os.environ.get("MINIO_ACCESS_KEY", "minioadmin"),
            os.environ.get("MINIO_SECRET_KEY", "minioadmin"))


def _announce(msg: str, access: str) -> None:
    from .logger import Logger
    Logger.get().info(msg)
    print(msg)
    print(f"   access key: {access}")
    sys.stdout.flush()


def _wait_for_sigterm() -> None:
    # An Event + timed wait, NOT signal.pause(): the kernel delivers a
    # process-directed SIGTERM to ANY thread with it unblocked, and
    # pause() only returns when THIS thread takes a signal — with the
    # front door's loop/worker threads in the mix, a SIGTERM landing
    # on one of them left the main thread paused forever (~1-in-3).
    # The Python-level handler always runs on the main thread; the
    # timed wait guarantees a bytecode boundary for it soon after.
    import threading
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        while not stop.wait(1.0):
            pass
    except KeyboardInterrupt:
        pass


def _serve_gateway(args) -> int:
    """`minio-tpu gateway nas /mnt` / `gateway s3 http://host:port`
    (ref gateway-main.go startup: build layer from Gateway, same
    router)."""
    import hashlib

    from .s3.server import S3Server

    host, port = _parse_address(args.address)
    access, secret = _env_creds()

    if args.backend == "nas":
        from .gateway import NASGateway
        os.makedirs(args.target, exist_ok=True)
        layer = NASGateway(args.target).new_gateway_layer()
    elif args.backend in ("azure", "gcs", "hdfs"):
        from .bucket.replication import BucketTargetSys
        ep = BucketTargetSys.normalize_endpoint(args.target)
        h, _, prt = ep.partition(":")
        https = args.target.startswith("https://")
        meta_dir = args.meta_dir or os.path.join(
            os.path.expanduser("~/.minio-tpu"), "gateway",
            hashlib.sha256(ep.encode()).hexdigest()[:12])
        os.makedirs(meta_dir, exist_ok=True)
        if args.backend == "azure":
            from .gateway import AzureGateway
            layer = AzureGateway(
                h, int(prt),
                os.environ.get("MINIO_AZURE_ACCOUNT", ""),
                os.environ.get("MINIO_AZURE_KEY", ""), meta_dir,
                https=https).new_gateway_layer()
        elif args.backend == "gcs":
            from .gateway import GCSGateway
            layer = GCSGateway(
                h, int(prt),
                os.environ.get("MINIO_GCS_PROJECT", "default"),
                meta_dir, token=os.environ.get("MINIO_GCS_TOKEN", ""),
                https=https).new_gateway_layer()
        else:
            from .gateway import HDFSGateway
            layer = HDFSGateway(
                h, int(prt), meta_dir,
                root=os.environ.get("MINIO_HDFS_ROOT", "/minio-tpu"),
                user=os.environ.get("MINIO_HDFS_USER", "minio"),
                https=https).new_gateway_layer()
    else:
        from .bucket.replication import BucketTargetSys
        from .gateway import S3Gateway
        ep = BucketTargetSys.normalize_endpoint(args.target)
        h, _, prt = ep.partition(":")
        meta_dir = args.meta_dir or os.path.join(
            os.path.expanduser("~/.minio-tpu"), "gateway",
            hashlib.sha256(ep.encode()).hexdigest()[:12])
        os.makedirs(meta_dir, exist_ok=True)
        # Upstream credentials: same env pair (the reference reuses
        # MINIO_ACCESS_KEY/SECRET_KEY for the backend account too).
        layer = S3Gateway(h, int(prt), access, secret,
                          meta_dir).new_gateway_layer()

    layer = _maybe_wrap_cache(layer)
    server = S3Server(layer, access, secret,
                      iam=_make_iam(layer, access, secret))
    port = server.start(host, port, cert_manager=_certs())
    _announce(f"minio-tpu gateway [{args.backend}] -> {args.target}, "
              f"listening on {host}:{port}", access)
    _wait_for_sigterm()
    server.stop()
    return 0


def build_object_layer(disk_args: list[str],
                       block_size: int | None = None):
    """Construct the full topology: per-arg pools -> format.json
    bootstrap -> erasure sets -> server pools (ref newObjectLayer,
    cmd/server-main.go:538). A single plain path selects the FS
    backend (ref NEndpoints==1 -> NewFSObjectLayer)."""
    import threading

    from .erasure.pools import ErasureServerPools
    from .erasure.sets import ErasureSets
    from .storage.format import init_or_load_formats
    from .storage.xl import XLStorage
    from .utils.ellipses import expand, has_ellipses

    if (len(disk_args) == 1 and not has_ellipses(disk_args[0])
            and not disk_args[0].startswith(("http://", "https://"))):
        from .fs.backend import FSObjects
        os.makedirs(disk_args[0], exist_ok=True)
        return FSObjects(disk_args[0])

    # Each ellipses arg is a pool; plain args group into one pool
    # (ref createServerEndpoints, cmd/endpoint-ellipses.go:252).
    pool_paths: list[list[str]] = []
    if any(has_ellipses(a) for a in disk_args):
        for a in disk_args:
            pool_paths.append(expand(a))
    else:
        pool_paths.append(list(disk_args))

    kwargs = {}
    if block_size:
        kwargs["block_size"] = block_size

    pools = []
    fresh_all: list[tuple[ErasureSets, int]] = []
    for paths in pool_paths:
        if len(paths) < 2:
            raise ValueError("each pool needs at least 2 disks")
        for p in paths:
            os.makedirs(p, exist_ok=True)
        disks = [XLStorage(p) for p in paths]
        fmt, ordered, fresh = init_or_load_formats(disks)
        layout = [len(s) for s in fmt.sets]
        sets = ErasureSets(ordered, layout, fmt.deployment_id, **kwargs)
        pools.append(sets)
        for slot in fresh:
            fresh_all.append((sets, slot))

    layer = ErasureServerPools(pools)
    if fresh_all:
        # Replacement disks detected: heal each affected pool once, in
        # the background (ref monitorLocalDisksAndHeal).
        unique_sets = list(dict.fromkeys(s for s, _ in fresh_all))
        # mtpu-lint: disable=R1 -- boot-time background heal kickoff; no request context exists yet
        threading.Thread(target=lambda: [s.healer.heal_all()
                                         for s in unique_sets],
                         daemon=True).start()
    return layer


def _certs():
    """HTTPS when a cert pair exists (env or ~/.minio-tpu/certs; ref
    cmd/config-dir.go certsDir auto-detection)."""
    from .utils.certs import CertManager
    return CertManager.from_env()


def _make_iam(layer, access: str, secret: str):
    """IAM persisted on the store's own first erasure set — or on the
    single FS root (ref iam-object-store in .minio.sys)."""
    from .iam.iam import ConfigStore, IAMSys
    if hasattr(layer, "pools"):
        disks = layer.pools[0].sets[0].disks
    else:
        disks = [layer.meta_disk]
    return IAMSys(ConfigStore(disks), access, secret)


def _maybe_wrap_cache(layer):
    """The env-configured CacheObjectLayer wrapper is gone: caching is
    now the hot-object serving tier INSIDE the erasure data plane
    (cache/hotcache.py), configured via config-KV — e.g.
    `mc admin config set cache enable=on dirs=/mnt/d1/cache`. Warn
    anyone still setting the old env so the migration is visible."""
    if os.environ.get("MINIO_CACHE_DRIVES"):
        print("warning: MINIO_CACHE_DRIVES is no longer honored — "
              "the disk-cache wrapper was replaced by the hot-object "
              "serving tier; configure it with "
              "`mc admin config set cache enable=on "
              "dirs=<dir1,dir2,...>` instead", file=sys.stderr)
    return layer


def _serve(args) -> int:
    from .s3.server import S3Server

    host, port = _parse_address(args.address)
    access, secret = _env_creds()

    distributed = any(a.startswith(("http://", "https://"))
                      for a in args.disks)
    if any(a.startswith("https://") for a in args.disks) \
            and _certs() is None:
        print("error: https:// cluster endpoints require server "
              "certificates (MINIO_CERT_FILE/MINIO_KEY_FILE or "
              "~/.minio-tpu/certs/public.crt+private.key) — without "
              "them peers cannot complete TLS handshakes against this "
              "node", file=sys.stderr)
        return 1
    try:
        if distributed:
            # Start HTTP first (peers need our storage RPC during
            # format bootstrap; ref serverMain order,
            # cmd/server-main.go:463).
            from .rpc.cluster import build_cluster_node, derive_cluster_key
            from .rpc.transport import RPCRegistry
            boot_registry = RPCRegistry(
                derive_cluster_key(access, secret))
            server = S3Server(None, access, secret,
                              rpc_registry=boot_registry)
            port = server.start(host, port, cert_manager=_certs())
            my_host = "127.0.0.1" if host in ("0.0.0.0", "") else host
            node = build_cluster_node(args.disks, my_host, port,
                                      access, secret, args.block_size,
                                      registry=boot_registry)
            layer = _maybe_wrap_cache(node.layer)
            server.set_layer(layer)
            server.iam = _make_iam(node.layer, access, secret)
            # Peer control plane: bind the RPC service to this server
            # and wire push invalidation — the 1s freshness polls
            # become slow safety nets (ref NotificationSys,
            # cmd/notification.go:48).
            node.peer_service.bind(server)
            server.notification = node.notification
            server.iam.notify = node.notification.load_iam
            server.iam.reload_interval = 30.0
            server.bucket_meta.notify_update = \
                node.notification.load_bucket_metadata
            server.bucket_meta.notify_delete = \
                node.notification.delete_bucket_metadata
            # Hot-object cache coherence: every local overwrite/delete
            # pushes an invalidation (with its epoch stamp) to every
            # peer's cache (rpc/peer.py cache_invalidate).
            from .cache.hotcache import HOTCACHE
            HOTCACHE.peer_notify = node.notification.cache_invalidate
        else:
            layer = _maybe_wrap_cache(
                build_object_layer(args.disks, args.block_size))
            server = S3Server(layer, access, secret,
                              iam=_make_iam(layer, access, secret))
            port = server.start(host, port, cert_manager=_certs())
    except (ValueError, TimeoutError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1

    if hasattr(layer, "pools"):
        n_disks = sum(len(s.disks) for p in layer.pools for s in p.sets)
        eng = layer.pools[0].sets[0]
        msg = (f"minio-tpu server: {len(layer.pools)} pool(s), "
               f"{sum(len(p.sets) for p in layer.pools)} set(s), "
               f"{n_disks} disks, EC {eng.k}+{eng.m}, "
               f"listening on {host}:{port}")
    else:
        msg = (f"minio-tpu server: FS backend at {layer.root}, "
               f"listening on {host}:{port}")
    _announce(msg, access)

    # Notification targets from env (ref config/notify webhook subsys:
    # MINIO_NOTIFY_WEBHOOK_ENABLE/ENDPOINT/QUEUE_DIR).
    if os.environ.get("MINIO_NOTIFY_WEBHOOK_ENABLE", "") == "on":
        from .event.targets import QueueStoreTarget, WebhookTarget
        endpoint = os.environ.get("MINIO_NOTIFY_WEBHOOK_ENDPOINT", "")
        if endpoint:
            target = WebhookTarget(endpoint)
            qdir = os.environ.get("MINIO_NOTIFY_WEBHOOK_QUEUE_DIR", "")
            if qdir:
                target = QueueStoreTarget(target, qdir)
            server.notifier.register_target(target)
    # Federation: etcd-backed bucket DNS (ref globalDNSConfig,
    # pkg/dns/etcd_dns.go). MINIO_PUBLIC_ADDRESS is the address other
    # clusters should reach this one at (defaults to the bind address).
    from .bucket.federation import BucketDNS
    dns = BucketDNS.from_env()
    if dns is not None and server.handlers is not None:
        pub = os.environ.get("MINIO_PUBLIC_ADDRESS",
                             f"{host or '127.0.0.1'}:{port}")
        ph, sep, pp = pub.rpartition(":")
        if not sep or not pp.isdigit():
            print(f"error: MINIO_PUBLIC_ADDRESS must be host:port, "
                  f"got {pub!r}", file=sys.stderr)
            return 1
        server.handlers.bucket_dns = dns
        server.handlers.public_addr = (ph or "127.0.0.1", int(pp))
        # Re-register every existing local bucket so a cluster joining
        # (or restarting into) the federation is resolvable at once
        # (ref initFederatorBackend, cmd/server-main.go).
        try:
            for b in layer.list_buckets():
                dns.register(b["name"],
                             *server.handlers.public_addr)
        except Exception:
            from .logger import Logger
            Logger.get().log_once("bucket DNS boot registration failed",
                                  "bucket-dns")

    # Broker sinks (nats/nsq/mqtt/redis/es/kafka/amqp/postgres/mysql;
    # ref pkg/event/target suite) share the same env conventions.
    from .event.brokers import targets_from_env
    from .event.targets import QueueStoreTarget as _QS
    for target in targets_from_env():
        qdir = os.environ.get(
            f"MINIO_NOTIFY_{target.env_name}_QUEUE_DIR", "")
        if qdir:
            target = _QS(target, qdir)
        server.notifier.register_target(target)

    # Background data crawler: usage + lifecycle + heal sampling
    # (ref initDataCrawler, cmd/server-main.go:497).
    from .scanner.crawler import DataCrawler
    crawler = DataCrawler(
        layer, server.bucket_meta, notifier=server.notifier,
        interval=float(os.environ.get("MINIO_CRAWLER_INTERVAL", "60")),
        tiers=server.handlers.tiers)
    crawler.start()
    server.crawler = crawler

    # Auto-heal freshly replaced disks (ref monitorLocalDisksAndHeal,
    # cmd/background-newdisks-heal-ops.go:113).
    monitors = []
    for pool in getattr(layer, "pools", [layer]):
        for es in getattr(pool, "sets", [pool]):
            mon = getattr(es, "new_disk_monitor", None)
            if mon is not None:
                mon.interval = float(os.environ.get(
                    "MINIO_HEAL_NEWDISK_INTERVAL", "10"))
                mon.start()
                monitors.append(mon)
            # Probation probes close the quarantine loop: a drive the
            # health monitor pulled from the data plane earns its way
            # back through bitrot-verified shadow reads.
            prober = getattr(es, "quarantine_prober", None)
            if prober is not None:
                prober.interval = float(os.environ.get(
                    "MINIO_HEAL_PROBATION_INTERVAL", "5"))
                prober.start()
                monitors.append(prober)

    _wait_for_sigterm()
    for mon in monitors:
        mon.stop()
    crawler.stop()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
