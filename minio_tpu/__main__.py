"""CLI entry: `python -m minio_tpu server /data/disk{1...4}`
(ref main.go:36, cmd/server-main.go:388 serverMain)."""

from __future__ import annotations

import argparse
import os
import signal
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="minio-tpu",
        description="TPU-native S3-compatible erasure-coded object store")
    sub = parser.add_subparsers(dest="command", required=True)

    srv = sub.add_parser("server", help="start the object-store server")
    srv.add_argument("disks", nargs="+",
                     help="disk paths; ellipses supported: /data/d{1...4}")
    srv.add_argument("--address", default="0.0.0.0:9000",
                     help="listen address (host:port)")
    srv.add_argument("--block-size", type=int, default=None,
                     help="erasure stripe block size in bytes")
    args = parser.parse_args(argv)

    if args.command == "server":
        return _serve(args)
    return 2


def _serve(args) -> int:
    from .erasure.engine import ErasureObjects
    from .s3.server import S3Server
    from .storage.xl import XLStorage
    from .utils.ellipses import expand_all

    disk_paths = expand_all(args.disks)
    if len(disk_paths) < 2:
        print("error: need at least 2 disks for erasure coding",
              file=sys.stderr)
        return 1
    for p in disk_paths:
        os.makedirs(p, exist_ok=True)
    disks = [XLStorage(p) for p in disk_paths]

    kwargs = {}
    if args.block_size:
        kwargs["block_size"] = args.block_size
    layer = ErasureObjects(disks, **kwargs)

    host, _, port_s = args.address.rpartition(":")
    host = host or "0.0.0.0"
    access = os.environ.get("MINIO_ACCESS_KEY", "minioadmin")
    secret = os.environ.get("MINIO_SECRET_KEY", "minioadmin")
    server = S3Server(layer, access, secret)
    port = server.start(host, int(port_s))

    print(f"minio-tpu server: {len(disks)} disks, "
          f"EC {layer.k}+{layer.m}, listening on {host}:{port}")
    print(f"   access key: {access}")
    sys.stdout.flush()

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            signal.pause()
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
