"""Per-bucket metadata: versioning, policy, tagging, lifecycle,
notification, encryption, quota, object-lock, replication configs."""
