"""Bucket federation over etcd DNS (ref cmd/globals.go
globalDNSConfig + pkg/dns/etcd_dns.go: every cluster registers its
buckets as skydns-style SRV records; any cluster can then resolve a
foreign bucket to its owning endpoints).

The etcd client speaks the v3 JSON gRPC-gateway (/v3/kv/put, /v3/kv/
range, /v3/kv/deleterange; keys/values base64) — no etcd library
exists in this image, and the JSON gateway is etcd's stable public
surface.

Server integration (s3/server.py): a request for a bucket that is NOT
local but resolves in DNS answers 307 to the owning cluster — the
federation contract a dumb client can follow (the reference fronts
this with CoreDNS; the redirect covers clients addressing any
federated node directly).
"""

from __future__ import annotations

import base64
import json
import time
import urllib.parse


class EtcdError(Exception):
    pass


class EtcdClient:
    """Minimal etcd v3 JSON-gateway client."""

    def __init__(self, endpoint: str, timeout: float = 5.0):
        from ..utils.httpjson import parse_endpoint
        self.host, self.port, self.https = parse_endpoint(endpoint, 2379)
        self.timeout = timeout

    def _call(self, path: str, doc: dict) -> dict:
        from ..utils.httpjson import json_post
        return json_post(self.host, self.port, self.https, path, doc,
                         self.timeout, EtcdError)

    @staticmethod
    def _b64(s: bytes) -> str:
        return base64.b64encode(s).decode()

    def put(self, key: str, value: bytes) -> None:
        self._call("/v3/kv/put", {"key": self._b64(key.encode()),
                                  "value": self._b64(value)})

    def get_prefix(self, prefix: str) -> dict[str, bytes]:
        end = prefix[:-1] + chr(ord(prefix[-1]) + 1)
        doc = self._call("/v3/kv/range", {
            "key": self._b64(prefix.encode()),
            "range_end": self._b64(end.encode())})
        out = {}
        for kv in doc.get("kvs", []):
            out[base64.b64decode(kv["key"]).decode()] = \
                base64.b64decode(kv.get("value", ""))
        return out

    def delete_prefix(self, prefix: str) -> None:
        end = prefix[:-1] + chr(ord(prefix[-1]) + 1)
        self._call("/v3/kv/deleterange", {
            "key": self._b64(prefix.encode()),
            "range_end": self._b64(end.encode())})


class BucketDNS:
    """skydns-layout bucket records (ref pkg/dns/etcd_dns.go:
    /skydns/<reversed domain>/<bucket>/<node> -> {host, port})."""

    # Request-path lookups cache briefly so a slow/offline etcd can't
    # pin handler threads on every NoSuchBucket probe.
    LOOKUP_TTL = 3.0

    def __init__(self, etcd: EtcdClient, domain: str = "minio-tpu.local"):
        self.etcd = etcd
        self.domain = domain
        rev = "/".join(reversed(domain.split(".")))
        self._base = f"/skydns/{rev}"
        self._cache: dict[str, tuple[float, list]] = {}

    def _bucket_prefix(self, bucket: str) -> str:
        return f"{self._base}/{bucket}/"

    def register(self, bucket: str, host: str, port: int) -> None:
        rec = json.dumps({"host": host, "port": port,
                          "ttl": 30, "creation": time.time()}).encode()
        self.etcd.put(self._bucket_prefix(bucket) + f"{host}:{port}",
                      rec)
        self._cache.pop(bucket, None)

    def unregister(self, bucket: str) -> None:
        self.etcd.delete_prefix(self._bucket_prefix(bucket))
        self._cache.pop(bucket, None)

    def lookup(self, bucket: str,
               cached: bool = True) -> list[tuple[str, int]]:
        if cached:
            hit = self._cache.get(bucket)
            if hit and time.time() - hit[0] < self.LOOKUP_TTL:
                return hit[1]
        out = []
        try:
            records = sorted(self.etcd.get_prefix(
                self._bucket_prefix(bucket)).items())
        except EtcdError:
            if cached and bucket in self._cache:
                return self._cache[bucket][1]  # stale beats stalled
            raise
        for _k, raw in records:
            try:
                doc = json.loads(raw)
                out.append((doc["host"], int(doc["port"])))
            except (ValueError, KeyError):
                continue
        self._cache[bucket] = (time.time(), out)
        return out

    def list_buckets(self) -> dict[str, list[tuple[str, int]]]:
        out: dict[str, list[tuple[str, int]]] = {}
        for key, raw in sorted(self.etcd.get_prefix(
                self._base + "/").items()):
            rest = key[len(self._base) + 1:]
            bucket = rest.split("/", 1)[0]
            try:
                doc = json.loads(raw)
                out.setdefault(bucket, []).append(
                    (doc["host"], int(doc["port"])))
            except (ValueError, KeyError):
                continue
        return out

    @classmethod
    def from_env(cls, env=None) -> "BucketDNS | None":
        import os
        env = env if env is not None else os.environ
        ep = env.get("MINIO_ETCD_ENDPOINT", "")
        if not ep:
            return None
        return cls(EtcdClient(ep),
                   env.get("MINIO_DOMAIN", "minio-tpu.local"))
