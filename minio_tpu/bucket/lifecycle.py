"""Bucket lifecycle (ILM) rules engine.

Ref pkg/bucket/lifecycle/lifecycle.go (Lifecycle.ComputeAction),
rule.go, expiration.go, noncurrentversion.go. Parses the bucket's
<LifecycleConfiguration> XML and decides, per object version, whether
it should expire now. Transition-to-tier is parsed but reported as
unsupported (no remote tiers configured in this build).
"""

from __future__ import annotations

import time
import urllib.parse
from dataclasses import dataclass, field

from ..s3.xmlutil import parse

# Actions (ref lifecycle.go Action enum).
NONE = "none"
DELETE = "delete"                  # expire current version
DELETE_VERSION = "delete-version"  # expire a noncurrent version
DELETE_MARKER = "delete-marker"    # remove an expired delete marker
TRANSITION = "transition"          # move current version to a tier

_DAY = 24 * 3600.0


@dataclass
class Rule:
    rule_id: str = ""
    status: str = "Enabled"
    prefix: str = ""
    tags: dict = field(default_factory=dict)
    expiration_days: int = 0
    expiration_date: float = 0.0
    expired_object_delete_marker: bool = False
    noncurrent_days: int = 0
    transition_days: int = 0
    transition_date: float = 0.0
    transition_tier: str = ""      # <Transition><StorageClass>

    def enabled(self) -> bool:
        return self.status == "Enabled"

    def matches(self, name: str, tags: dict) -> bool:
        if self.prefix and not name.startswith(self.prefix):
            return False
        for k, v in self.tags.items():
            if tags.get(k) != v:
                return False
        return True


def _parse_date(text: str) -> float:
    for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%M:%S.000Z",
                "%Y-%m-%d"):
        try:
            return time.mktime(time.strptime(text, fmt)) - time.timezone
        except ValueError:
            continue
    raise ValueError(f"bad lifecycle date: {text}")


def parse_tags(raw: str) -> dict:
    """'a=1&b=2' url-encoded tag string -> dict (the xl.meta
    x-amz-tagging form)."""
    out = {}
    for pair in raw.split("&") if raw else []:
        k, _, v = pair.partition("=")
        out[urllib.parse.unquote_plus(k)] = urllib.parse.unquote_plus(v)
    return out


class Lifecycle:
    def __init__(self, rules: list[Rule]):
        self.rules = rules

    def __bool__(self) -> bool:
        return bool(self.rules)

    @classmethod
    def parse(cls, raw: str | bytes) -> "Lifecycle":
        if not raw:
            return cls([])
        doc = parse(raw.encode() if isinstance(raw, str) else raw)
        rules: list[Rule] = []
        for r in doc.findall("Rule"):
            rule = Rule(rule_id=r.findtext("ID") or "",
                        status=r.findtext("Status") or "Enabled")
            # Filter: bare <Prefix>, <Filter><Prefix>, or <Filter><And>.
            rule.prefix = r.findtext("Prefix") or ""
            filt = r.find("Filter")
            if filt is not None:
                rule.prefix = filt.findtext("Prefix") or rule.prefix
                and_el = filt.find("And")
                tag_els = filt.findall("Tag")
                if and_el is not None:
                    rule.prefix = (and_el.findtext("Prefix")
                                   or rule.prefix)
                    tag_els = and_el.findall("Tag")
                for t in tag_els:
                    rule.tags[t.findtext("Key") or ""] = \
                        t.findtext("Value") or ""
            exp = r.find("Expiration")
            if exp is not None:
                if exp.findtext("Days"):
                    rule.expiration_days = int(exp.findtext("Days"))
                if exp.findtext("Date"):
                    rule.expiration_date = _parse_date(
                        exp.findtext("Date"))
                if exp.findtext("ExpiredObjectDeleteMarker") == "true":
                    rule.expired_object_delete_marker = True
            tr = r.find("Transition")
            if tr is not None:
                if tr.findtext("Days"):
                    rule.transition_days = int(tr.findtext("Days"))
                if tr.findtext("Date"):
                    rule.transition_date = _parse_date(
                        tr.findtext("Date"))
                rule.transition_tier = (
                    tr.findtext("StorageClass") or "").upper()
            nce = r.find("NoncurrentVersionExpiration")
            if nce is not None and nce.findtext("NoncurrentDays"):
                rule.noncurrent_days = int(
                    nce.findtext("NoncurrentDays"))
            rules.append(rule)
        return cls(rules)

    def compute_action(self, name: str, mod_time: float,
                       is_latest: bool = True,
                       delete_marker: bool = False,
                       tags: dict | None = None,
                       sole_version: bool = True,
                       now: float | None = None) -> str:
        return self.compute_with_tier(
            name, mod_time, is_latest=is_latest,
            delete_marker=delete_marker, tags=tags,
            sole_version=sole_version, now=now)[0]

    def compute_with_tier(self, name: str, mod_time: float,
                          is_latest: bool = True,
                          delete_marker: bool = False,
                          tags: dict | None = None,
                          sole_version: bool = True,
                          now: float | None = None,
                          ) -> tuple[str, str]:
        """Decide this version's fate (ref Lifecycle.ComputeAction).
        mod_time for a noncurrent version is WHEN IT BECAME noncurrent
        in the reference (successor mod-time); the caller passes the
        successor's mod_time for noncurrent versions."""
        now = time.time() if now is None else now
        tags = tags or {}
        for rule in self.rules:
            if not rule.enabled() or not rule.matches(name, tags):
                continue
            if not is_latest:
                if rule.noncurrent_days and \
                        now >= mod_time + rule.noncurrent_days * _DAY:
                    return DELETE_VERSION, ""
                continue
            if delete_marker:
                # A delete marker with no remaining data versions is
                # removable once flagged (ref ExpiredObjectDeleteMarker).
                if rule.expired_object_delete_marker and sole_version:
                    return DELETE_MARKER, ""
                continue
            if rule.expiration_date and now >= rule.expiration_date:
                return DELETE, ""
            if rule.expiration_days and \
                    now >= mod_time + rule.expiration_days * _DAY:
                return DELETE, ""
            if rule.transition_tier:
                due = ((rule.transition_date
                        and now >= rule.transition_date)
                       or (rule.transition_days
                           and now >= mod_time
                           + rule.transition_days * _DAY)
                       or (not rule.transition_days
                           and not rule.transition_date))
                if due:
                    return TRANSITION, rule.transition_tier
        return NONE, ""
