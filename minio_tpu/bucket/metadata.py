"""Bucket metadata subsystem.

The reference persists one `.metadata.bin` per bucket under
`.minio.sys/buckets/<bucket>/` holding every bucket-scoped config —
policy, notification, lifecycle, SSE, tagging, quota, object-lock,
versioning, replication — loaded at startup and peer-invalidated on
change (ref cmd/bucket-metadata-sys.go, cmd/bucket-metadata.go).

Here the same document is canonical JSON stored through the quorum
ConfigStore on the system's own disks; reads are cached with a short
TTL so cross-node updates converge without a peer-notification channel
(same trade the IAM store makes).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..iam.iam import ConfigStore

BUCKET_META_PREFIX = "buckets"

VERSIONING_ENABLED = "Enabled"
VERSIONING_SUSPENDED = "Suspended"


@dataclass
class BucketMetadata:
    """All bucket-scoped configs (ref BucketMetadata,
    cmd/bucket-metadata.go:71-94 — which likewise stores each config as
    its raw serialized document)."""
    name: str = ""
    created: float = 0.0
    versioning: str = ""            # "", Enabled, Suspended
    policy: dict | None = None      # bucket policy JSON document
    tagging_xml: str = ""           # <Tagging> config
    lifecycle_xml: str = ""         # <LifecycleConfiguration>
    notification_xml: str = ""      # <NotificationConfiguration>
    sse_xml: str = ""               # <ServerSideEncryptionConfiguration>
    object_lock_xml: str = ""       # <ObjectLockConfiguration>
    replication_xml: str = ""       # <ReplicationConfiguration>
    quota: dict | None = None       # {"quota": bytes, "quotaType": "hard"}
    replication_targets: list = field(default_factory=list)
    cors_xml: str = ""              # <CORSConfiguration>

    _FIELDS = ("name", "created", "versioning", "policy", "tagging_xml",
               "lifecycle_xml", "notification_xml", "sse_xml",
               "object_lock_xml", "replication_xml", "quota",
               "replication_targets", "cors_xml")

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self._FIELDS}

    @classmethod
    def from_dict(cls, d: dict) -> "BucketMetadata":
        return cls(**{k: d[k] for k in cls._FIELDS if k in d})

    def versioning_enabled(self) -> bool:
        return self.versioning == VERSIONING_ENABLED

    def versioning_suspended(self) -> bool:
        return self.versioning == VERSIONING_SUSPENDED


class BucketMetadataSys:
    """Registry of per-bucket metadata (ref BucketMetadataSys,
    cmd/bucket-metadata-sys.go:36)."""

    CACHE_TTL = 1.0

    def __init__(self, store: ConfigStore):
        self.store = store
        self._mu = threading.RLock()
        self._cache: dict[str, tuple[float, BucketMetadata]] = {}
        # Peer push: set to NotificationSys.load_bucket_metadata /
        # delete_bucket_metadata in distributed mode so other nodes
        # drop their cache immediately instead of waiting out CACHE_TTL
        # (ref peerRESTMethodLoadBucketMetadata).
        self.notify_update = None
        self.notify_delete = None

    @classmethod
    def for_layer(cls, layer) -> "BucketMetadataSys":
        """Config store on the first erasure set's disks — the same
        place `.minio.sys` system config lives (works for a bare
        engine, ErasureSets, or ErasureServerPools)."""
        if hasattr(layer, "pools"):
            disks = layer.pools[0].sets[0].disks
        elif hasattr(layer, "sets"):
            disks = layer.sets[0].disks
        elif hasattr(layer, "meta_disk"):  # FS backend: single root
            disks = [layer.meta_disk]
        else:
            disks = layer.disks
        return cls(ConfigStore(disks))

    def _path(self, bucket: str) -> str:
        return f"{BUCKET_META_PREFIX}/{bucket}/bucket-metadata.json"

    def _load(self, bucket: str) -> BucketMetadata:
        doc = self.store.load(self._path(bucket))
        return (BucketMetadata.from_dict(doc) if doc
                else BucketMetadata(name=bucket, created=time.time()))

    def get(self, bucket: str) -> BucketMetadata:
        with self._mu:
            hit = self._cache.get(bucket)
            if hit and time.time() - hit[0] < self.CACHE_TTL:
                return hit[1]
        meta = self._load(bucket)
        with self._mu:
            self._cache[bucket] = (time.time(), meta)
        return meta

    def invalidate(self, bucket: str) -> None:
        """Drop the cache entry (peer-push target: next get() re-reads
        the quorum-stored document)."""
        with self._mu:
            self._cache.pop(bucket, None)

    def save(self, meta: BucketMetadata) -> None:
        self.store.save(self._path(meta.name), meta.to_dict())
        with self._mu:
            self._cache[meta.name] = (time.time(), meta)
        if self.notify_update is not None:
            self.notify_update(meta.name)

    def update(self, bucket: str, **fields) -> BucketMetadata:
        """Atomic read-modify-write of one or more config sections: the
        lock serializes concurrent updaters (no lost fields), the copy
        keeps a failed quorum save from polluting the read cache."""
        with self._mu:
            meta = BucketMetadata.from_dict(self._load(bucket).to_dict())
            for k, v in fields.items():
                if not hasattr(meta, k):
                    raise AttributeError(f"unknown bucket config: {k}")
                setattr(meta, k, v)
            meta.name = bucket
            self.store.save(self._path(bucket), meta.to_dict())
            self._cache[bucket] = (time.time(), meta)
        if self.notify_update is not None:
            self.notify_update(bucket)
        return meta

    def delete(self, bucket: str) -> None:
        self.store.delete(self._path(bucket))
        with self._mu:
            self._cache.pop(bucket, None)
        if self.notify_delete is not None:
            self.notify_delete(bucket)

    # -- convenience ----------------------------------------------------

    def versioning_enabled(self, bucket: str) -> bool:
        return self.get(bucket).versioning_enabled()

    def versioning_suspended(self, bucket: str) -> bool:
        return self.get(bucket).versioning_suspended()
