"""Object lock (WORM): bucket config, per-version retention + legal
hold, and delete/overwrite enforcement.

Mirrors the reference's object-lock semantics (ref
pkg/bucket/object/lock/lock.go: ParseObjectLockConfig,
GetObjectRetentionMeta:~, enforcement in cmd/object-handlers.go
checkRequestAuthType + enforceRetentionForDeletion,
cmd/erasure-object.go DeleteObject guards): retention rides in object
metadata (`x-amz-object-lock-mode`, `x-amz-object-lock-retain-until-date`,
`x-amz-object-lock-legal-hold`), bucket defaults come from
<ObjectLockConfiguration><Rule><DefaultRetention>, COMPLIANCE versions
are immutable until expiry, GOVERNANCE deletions need the bypass header
plus the s3:BypassGovernanceRetention grant.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..s3.xmlutil import parse

GOVERNANCE = "GOVERNANCE"
COMPLIANCE = "COMPLIANCE"

META_MODE = "x-amz-object-lock-mode"
META_RETAIN_UNTIL = "x-amz-object-lock-retain-until-date"
META_LEGAL_HOLD = "x-amz-object-lock-legal-hold"

H_BYPASS_GOVERNANCE = "x-amz-bypass-governance-retention"

ENABLED_XML = ("<ObjectLockConfiguration><ObjectLockEnabled>Enabled"
               "</ObjectLockEnabled></ObjectLockConfiguration>")

def parse_iso8601(s: str) -> float:
    """UTC ISO8601, fractional seconds tolerated and ignored."""
    import calendar
    s = s.strip()
    if "." in s:
        s = s.split(".")[0] + "Z"
    return calendar.timegm(time.strptime(s, "%Y-%m-%dT%H:%M:%SZ"))


def iso8601(t: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))


class ObjectLockError(Exception):
    pass


class PastRetainDate(ObjectLockError):
    """Retain-until date not in the future."""


class BadLockDate(ObjectLockError):
    """Unparseable retain-until date."""


@dataclass
class DefaultRetention:
    mode: str = ""
    days: int = 0
    years: int = 0

    @property
    def seconds(self) -> float:
        return self.days * 86400 + self.years * 365 * 86400


@dataclass
class ObjectLockConfig:
    """Parsed <ObjectLockConfiguration> (ref ParseObjectLockConfig,
    pkg/bucket/object/lock/lock.go)."""
    enabled: bool = False
    default: DefaultRetention | None = None

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "ObjectLockConfig":
        if not raw:
            return cls()
        doc = parse(raw if isinstance(raw, bytes) else raw.encode())
        cfg = cls(enabled=(doc.findtext("ObjectLockEnabled") == "Enabled"))
        rule = doc.find("Rule")
        if rule is not None:
            dr = rule.find("DefaultRetention")
            if dr is not None:
                mode = dr.findtext("Mode") or ""
                if mode not in (GOVERNANCE, COMPLIANCE):
                    raise ObjectLockError(f"bad default mode: {mode!r}")
                days = int(dr.findtext("Days") or "0")
                years = int(dr.findtext("Years") or "0")
                if (days > 0) == (years > 0):  # exactly one required
                    raise ObjectLockError("need exactly one of Days/Years")
                cfg.default = DefaultRetention(mode, days, years)
        return cfg


def parse_retention_xml(raw: bytes) -> tuple[str, float]:
    """<Retention><Mode/><RetainUntilDate/></Retention> -> (mode, ts)."""
    doc = parse(raw)
    mode = doc.findtext("Mode") or ""
    if mode not in (GOVERNANCE, COMPLIANCE):
        raise ObjectLockError(f"bad mode: {mode!r}")
    date = doc.findtext("RetainUntilDate") or ""
    return mode, parse_iso8601(date)


def parse_legal_hold_xml(raw: bytes) -> str:
    doc = parse(raw)
    status = doc.findtext("Status") or ""
    if status not in ("ON", "OFF"):
        raise ObjectLockError(f"bad legal hold status: {status!r}")
    return status


def apply_put_headers(headers: dict, config: ObjectLockConfig,
                      meta: dict, now: float | None = None) -> None:
    """Stamp lock metadata on a new object from the PUT's lock headers,
    falling back to the bucket's default retention (ref
    getObjectRetentionMeta + default-retention fill in PutObjectHandler,
    cmd/object-handlers.go)."""
    now = time.time() if now is None else now
    mode = headers.get(META_MODE, "")
    until = headers.get(META_RETAIN_UNTIL, "")
    hold = headers.get(META_LEGAL_HOLD, "")
    if mode or until:
        if mode not in (GOVERNANCE, COMPLIANCE) or not until:
            raise ObjectLockError("retention needs both a valid mode "
                                  "and a retain-until date")
        try:
            ts = parse_iso8601(until)
        except ValueError:
            raise BadLockDate(until)
        if ts <= now:
            raise PastRetainDate(until)
        meta[META_MODE] = mode
        meta[META_RETAIN_UNTIL] = iso8601(ts)
    elif config.enabled and config.default is not None:
        meta[META_MODE] = config.default.mode
        meta[META_RETAIN_UNTIL] = iso8601(now + config.default.seconds)
    if hold:
        if hold not in ("ON", "OFF"):
            raise ObjectLockError(f"bad legal hold: {hold!r}")
        meta[META_LEGAL_HOLD] = hold


def retention_active(meta: dict, now: float | None = None) -> str:
    """Returns the active retention mode ("" when expired/absent)."""
    now = time.time() if now is None else now
    mode = meta.get(META_MODE, "")
    until = meta.get(META_RETAIN_UNTIL, "")
    if not mode or not until:
        return ""
    try:
        return mode if parse_iso8601(until) > now else ""
    except ValueError:
        return ""


def check_version_delete(meta: dict, bypass_governance: bool,
                         now: float | None = None) -> None:
    """Raise ObjectLockError when deleting THIS version is forbidden
    (ref enforceRetentionBypassForDelete, cmd/bucket-object-lock.go).
    Plain (marker-writing) deletes never call this — only versioned
    deletes destroy data."""
    if meta.get(META_LEGAL_HOLD) == "ON":
        raise ObjectLockError("object is under legal hold")
    mode = retention_active(meta, now)
    if mode == COMPLIANCE:
        raise ObjectLockError("object is WORM protected (COMPLIANCE)")
    if mode == GOVERNANCE and not bypass_governance:
        raise ObjectLockError("object is WORM protected (GOVERNANCE); "
                              "bypass not granted")


def check_retention_update(old_meta: dict, new_mode: str, new_until: float,
                           bypass_governance: bool,
                           now: float | None = None) -> None:
    """A COMPLIANCE lock can only be extended, never shortened or
    re-moded; GOVERNANCE changes need bypass (ref
    enforceRetentionBypassForPut)."""
    mode = retention_active(old_meta, now)
    if not mode:
        return
    old_until = parse_iso8601(old_meta[META_RETAIN_UNTIL])
    if mode == COMPLIANCE:
        if new_mode != COMPLIANCE or new_until < old_until:
            raise ObjectLockError("COMPLIANCE retention cannot be "
                                  "shortened or downgraded")
    elif mode == GOVERNANCE and not bypass_governance:
        # Pure extension (same mode, later date) is always allowed;
        # only shortening/downgrading is privileged.
        if new_mode != GOVERNANCE or new_until < old_until:
            raise ObjectLockError("shortening GOVERNANCE retention "
                                  "requires bypass")
