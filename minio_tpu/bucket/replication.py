"""Bucket replication: remote-target registry + async replication pool.

The reference implements active CRR as a background worker pool that
re-PUTs each eligible object to a remote S3 target registered via the
admin API, tracking per-version replication status in object metadata
(ref cmd/bucket-replication.go: mustReplicate:100, replicateObject:428,
replicateDelete:215, worker pool replicationState:571-625; target
registry cmd/bucket-targets.go).

Here the decision + status protocol is the same — PENDING on write,
worker flips it to COMPLETED/FAILED, incoming replica writes carry
REPLICA — but transport is our own SigV4 S3Client and the pool is a
thread queue. Status updates are metadata-only xl.meta rewrites
(ErasureObjects.update_object_metadata), never a data rewrite.
"""

from __future__ import annotations

import queue
import threading
import urllib.parse
import uuid
from dataclasses import dataclass, field

from ..s3.xmlutil import parse

# Replication status values (ref replication.StatusType,
# pkg/bucket/replication/replication.go)
PENDING = "PENDING"
COMPLETED = "COMPLETED"
FAILED = "FAILED"
REPLICA = "REPLICA"

# Stored in object metadata / surfaced as the S3 response header.
META_REPLICATION_STATUS = "x-amz-replication-status"


class ReplicationError(Exception):
    pass


# ---------------------------------------------------------------------------
# Replication configuration (<ReplicationConfiguration> XML)
# ---------------------------------------------------------------------------


@dataclass
class ReplicationRule:
    """One <Rule> (ref pkg/bucket/replication/rule.go)."""
    rule_id: str = ""
    status: str = "Enabled"
    priority: int = 0
    prefix: str = ""
    delete_marker_replication: bool = False
    destination_arn: str = ""  # <Destination><Bucket> ARN

    def matches(self, key: str) -> bool:
        return self.status == "Enabled" and key.startswith(self.prefix)


@dataclass
class ReplicationConfig:
    """Parsed <ReplicationConfiguration> (ref
    pkg/bucket/replication/replication.go ParseConfig)."""
    role: str = ""
    rules: list[ReplicationRule] = field(default_factory=list)

    @classmethod
    def from_xml(cls, raw: str | bytes) -> "ReplicationConfig":
        doc = parse(raw if isinstance(raw, bytes) else raw.encode())
        cfg = cls(role=doc.findtext("Role") or "")
        for r in doc.findall("Rule"):
            rule = ReplicationRule(
                rule_id=r.findtext("ID") or "",
                status=r.findtext("Status") or "Enabled",
                priority=int(r.findtext("Priority") or "0"),
            )
            # Prefix may live at top level (legacy) or under Filter /
            # Filter.And (ref rule.Prefix()).
            for path in ("Prefix", "Filter/Prefix", "Filter/And/Prefix"):
                v = r.findtext(path)
                if v:
                    rule.prefix = v
                    break
            dmr = r.find("DeleteMarkerReplication")
            if dmr is not None and (dmr.findtext("Status") == "Enabled"):
                rule.delete_marker_replication = True
            dest = r.find("Destination")
            if dest is not None:
                rule.destination_arn = dest.findtext("Bucket") or ""
            cfg.rules.append(rule)
        # Highest priority first (ref FilterActionableRules sort).
        cfg.rules.sort(key=lambda r: -r.priority)
        return cfg

    def rule_for(self, key: str) -> ReplicationRule | None:
        for rule in self.rules:
            if rule.matches(key):
                return rule
        return None


# ---------------------------------------------------------------------------
# Remote-target registry
# ---------------------------------------------------------------------------


class BucketTargetSys:
    """Per-bucket remote replication targets, persisted in bucket
    metadata (ref BucketTargetSys, cmd/bucket-targets.go:470 — targets
    live in `.metadata.bin` and are addressed by ARN)."""

    def __init__(self, bucket_meta):
        self.bucket_meta = bucket_meta

    @staticmethod
    def normalize_endpoint(endpoint: str) -> str:
        """Accept `host:port` or `http(s)://host[:port]`; store
        `host:port`. Rejecting junk HERE surfaces config mistakes at
        registration, not as silent worker failures."""
        ep = endpoint
        if "://" in ep:
            u = urllib.parse.urlparse(ep)
            if u.scheme not in ("http", "https") or not u.hostname:
                raise ValueError(f"invalid endpoint: {endpoint!r}")
            port = u.port or (443 if u.scheme == "https" else 80)
            return f"{u.hostname}:{port}"
        host, _, port = ep.partition(":")
        if not host or not port.isdigit():
            raise ValueError(f"invalid endpoint: {endpoint!r} "
                             "(want host:port)")
        return ep

    def set_target(self, bucket: str, endpoint: str, target_bucket: str,
                   access_key: str, secret_key: str,
                   bandwidth_limit: int = 0) -> str:
        """Register a target, returns its ARN (ref SetBucketTarget +
        generateTargetArn). bandwidth_limit: replication bytes/sec cap
        toward this target, 0 = unlimited (ref BucketBandwidth /
        pkg/bandwidth LimitInBytesPerSecond)."""
        endpoint = self.normalize_endpoint(endpoint)
        if bandwidth_limit < 0:
            raise ValueError("bandwidth_limit must be >= 0")
        arn = f"arn:minio:replication::{uuid.uuid4().hex[:8]}:{target_bucket}"
        targets = list(self.bucket_meta.get(bucket).replication_targets)
        targets.append({
            "arn": arn, "endpoint": endpoint,
            "target_bucket": target_bucket,
            "access_key": access_key, "secret_key": secret_key,
            "bandwidth_limit": int(bandwidth_limit),
        })
        self.bucket_meta.update(bucket, replication_targets=targets)
        return arn

    def set_target_bandwidth(self, bucket: str, arn: str,
                             bandwidth_limit: int) -> None:
        """Update a registered target's replication rate cap (0 lifts
        it) — the `mc admin bucket remote edit --bandwidth` analog."""
        if bandwidth_limit < 0:
            raise ValueError("bandwidth_limit must be >= 0")
        targets = list(self.bucket_meta.get(bucket).replication_targets)
        for t in targets:
            if t["arn"] == arn:
                t["bandwidth_limit"] = int(bandwidth_limit)
                self.bucket_meta.update(bucket,
                                        replication_targets=targets)
                return
        raise KeyError(f"no such target {arn}")

    def list_targets(self, bucket: str) -> list[dict]:
        return list(self.bucket_meta.get(bucket).replication_targets)

    def remove_target(self, bucket: str, arn: str) -> None:
        targets = [t for t in self.bucket_meta.get(
            bucket).replication_targets if t["arn"] != arn]
        self.bucket_meta.update(bucket, replication_targets=targets)

    def target_for_arn(self, bucket: str, arn: str) -> dict | None:
        """Resolve a destination ARN; a plain `arn:aws:s3:::b` matches
        the registered target whose bucket is b (convenience parity
        with the reference's legacy-ARN handling)."""
        targets = self.bucket_meta.get(bucket).replication_targets
        for t in targets:
            if t["arn"] == arn:
                return t
        if arn.startswith("arn:aws:s3:::"):
            tb = arn[len("arn:aws:s3:::"):]
            for t in targets:
                if t["target_bucket"] == tb:
                    return t
        return None


# ---------------------------------------------------------------------------
# Async replication pool
# ---------------------------------------------------------------------------


@dataclass
class ReplicationTask:
    bucket: str
    key: str
    version_id: str
    op: str  # "put" | "delete"


class ReplicationPool:
    """Worker pool draining a queue of replication tasks (ref
    replicationState worker pool, cmd/bucket-replication.go:571-625).

    `reader(bucket, key, version_id) -> (plain_bytes, ObjectInfo)` is
    supplied by the API layer and yields the logical object (after
    SSE-S3 decrypt + decompression) plus its metadata; SSE-C objects
    are unreadable server-side and are skipped, as in the reference.
    """

    def __init__(self, bucket_meta, reader, layer, workers: int = 2):
        self.bucket_meta = bucket_meta
        self.targets = BucketTargetSys(bucket_meta)
        self.reader = reader
        self.layer = layer
        self._q: queue.Queue[ReplicationTask | None] = queue.Queue()
        self.stats = {"replicated_count": 0, "replicated_bytes": 0,
                      "failed_count": 0, "throttled_count": 0}
        self._cfg_cache: dict[str, ReplicationConfig] = {}
        self._limiters: dict[str, tuple[int, object]] = {}  # arn->(bps, bucket)
        self._stats_mu = threading.Lock()
        self._workers = [
            # mtpu-lint: disable=R1 -- replication drain daemons outlive the mutating requests that enqueue work
            threading.Thread(target=self._work, daemon=True,
                             name=f"replication-{i}")
            for i in range(workers)]
        for w in self._workers:
            w.start()

    # -- decision (ref mustReplicate, cmd/bucket-replication.go:100) ----

    def config(self, bucket: str) -> ReplicationConfig | None:
        raw = self.bucket_meta.get(bucket).replication_xml
        if not raw:
            return None
        hit = self._cfg_cache.get(raw)
        if hit is not None:
            return hit
        try:
            cfg = ReplicationConfig.from_xml(raw)
        except Exception:
            return None
        if len(self._cfg_cache) > 64:  # per-bucket configs; tiny
            self._cfg_cache.clear()
        self._cfg_cache[raw] = cfg
        return cfg

    def must_replicate(self, bucket: str, key: str) -> bool:
        cfg = self.config(bucket)
        return cfg is not None and cfg.rule_for(key) is not None

    def replicates_deletes(self, bucket: str, key: str) -> bool:
        cfg = self.config(bucket)
        if cfg is None:
            return False
        rule = cfg.rule_for(key)
        return rule is not None and rule.delete_marker_replication

    # -- queueing -------------------------------------------------------

    def queue_task(self, bucket: str, key: str, version_id: str,
                   op: str = "put") -> None:
        self._q.put(ReplicationTask(bucket, key, version_id, op))

    def close(self) -> None:
        for _ in self._workers:
            self._q.put(None)

    # -- worker ---------------------------------------------------------

    def _work(self) -> None:
        while True:
            task = self._q.get()
            if task is None:
                self._q.task_done()
                return
            try:
                self._replicate(task)
            except Exception:
                with self._stats_mu:
                    self.stats["failed_count"] += 1
                self._set_status(task, FAILED)
            finally:
                self._q.task_done()

    def _client_for(self, target: dict):
        from ..s3.client import S3Client
        host, _, port = target["endpoint"].partition(":")
        return S3Client(host, int(port or 80), target["access_key"],
                        target["secret_key"])

    def _resolve(self, task: ReplicationTask) -> tuple[dict, str] | None:
        cfg = self.config(task.bucket)
        if cfg is None:
            return None
        rule = cfg.rule_for(task.key)
        if rule is None:
            return None
        target = self.targets.target_for_arn(task.bucket,
                                             rule.destination_arn)
        if target is None:
            return None
        return target, target["target_bucket"]

    def _replicate(self, task: ReplicationTask) -> None:
        resolved = self._resolve(task)
        if resolved is None:
            return
        target, dest_bucket = resolved
        client = self._client_for(target)
        enc_key = urllib.parse.quote(task.key, safe="/-_.~")

        if task.op == "delete":
            # Delete-marker replication: plain DELETE creates the
            # marker on the target (ref replicateDelete,
            # cmd/bucket-replication.go:215).
            resp = client.request("DELETE", f"/{dest_bucket}/{enc_key}")
            if resp.status not in (200, 204):
                raise ReplicationError(f"delete -> {resp.status}")
            return

        data, info = self.reader(task.bucket, task.key, task.version_id)
        self._throttle(target, len(data))
        headers = {META_REPLICATION_STATUS: REPLICA}
        headers["content-type"] = info.metadata.get(
            "content-type", "application/octet-stream")
        for k, v in info.metadata.items():
            if k.startswith("x-amz-meta-") or k == "x-amz-tagging":
                headers[k] = v
        resp = client.put_object(dest_bucket, task.key, data,
                                 headers=headers)
        if resp.status != 200:
            raise ReplicationError(f"put -> {resp.status}")
        with self._stats_mu:
            self.stats["replicated_count"] += 1
            self.stats["replicated_bytes"] += len(data)
        self._set_status(task, COMPLETED)

    def _throttle(self, target: dict, nbytes: int) -> None:
        """Per-target token-bucket pacing (ref pkg/bandwidth
        LimitInBytesPerSecond wired into replication transfers): a
        capped target drains at ~its limit while uncapped targets
        proceed at full speed; workers on other targets are unaffected
        because each ARN has its own bucket."""
        limit = int(target.get("bandwidth_limit") or 0)
        if limit <= 0:
            return
        from ..utils.bandwidth import TokenBucket
        arn = target["arn"]
        with self._stats_mu:
            cur = self._limiters.get(arn)
            if cur is None or cur[0] != limit:
                cur = (limit, TokenBucket(limit))
                self._limiters[arn] = cur
        # A capped-but-idle target passes without sleeping; only count
        # a throttle when the token bucket actually stalled the worker.
        waited = cur[1].throttle(nbytes)
        if waited > 0:
            with self._stats_mu:
                self.stats["throttled_count"] += 1

    def _set_status(self, task: ReplicationTask, status: str) -> None:
        if task.op == "delete":
            return
        try:
            self.layer.update_object_metadata(
                task.bucket, task.key,
                {META_REPLICATION_STATUS: status}, task.version_id)
        except Exception:
            pass  # source version vanished meanwhile; nothing to mark
