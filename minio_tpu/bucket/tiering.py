"""Remote tiers: ILM transition targets + transitioned-object IO (ref
cmd/tier.go TierConfigMgr, cmd/bucket-lifecycle.go transition flow,
admin `mc ilm tier add`).

A tier is a remote S3 endpoint + bucket + prefix. Transition moves an
object's STORED bytes (post-SSE/compression, so the envelope stays
intact) to the tier and leaves a zero-byte local stub whose metadata
carries the tier name + remote key; reads stream back through the tier
transparently (the reference's GetObjectNInfo does the same for
transitioned objects). RestoreObject re-materializes the data locally.
"""

from __future__ import annotations

import threading
import time
import urllib.parse
import uuid

# Stub metadata keys (ref the xl.meta transition fields
# TransitionStatus/TransitionedObjName/TransitionTier).
META_TRANSITION_TIER = "x-minio-internal-transition-tier"
META_TRANSITION_KEY = "x-minio-internal-transition-key"
META_TRANSITION_SIZE = "x-minio-internal-transition-size"
META_TRANSITION_ETAG = "x-minio-internal-transition-etag"
META_RESTORE = "x-amz-restore"
META_RESTORE_EXPIRY = "x-minio-internal-restore-expiry"

TIERS_CONFIG_PATH = "tiers/config.json"


class TierError(Exception):
    pass


class TierManager:
    """Registry of remote tiers, persisted in the quorum ConfigStore
    (ref globalTierConfigMgr, cmd/tier.go)."""

    def __init__(self, store):
        self.store = store
        self._mu = threading.Lock()
        doc = store.load(TIERS_CONFIG_PATH)
        self._tiers: dict[str, dict] = doc["tiers"] if doc else {}

    # -- registry -------------------------------------------------------

    def add(self, name: str, endpoint: str, bucket: str,
            access_key: str, secret_key: str, prefix: str = "") -> None:
        from .replication import BucketTargetSys
        name = name.upper()
        if not name or not name.replace("-", "").replace(
                "_", "").isalnum():
            raise TierError(f"bad tier name {name!r}")
        endpoint = BucketTargetSys.normalize_endpoint(endpoint)
        with self._mu:
            if name in self._tiers:
                raise TierError(f"tier {name} already exists")
            self._tiers[name] = {
                "name": name, "endpoint": endpoint, "bucket": bucket,
                "access_key": access_key, "secret_key": secret_key,
                "prefix": prefix.strip("/"),
            }
            self._persist()

    def remove(self, name: str, layer=None) -> None:
        """Refuses removal while any object still references the tier
        (ref the in-use check of RemoveTier) when a layer is given."""
        name = name.upper()
        if layer is not None and self.get(name) is not None:
            for b in layer.list_buckets():
                for o in layer.list_objects(b["name"],
                                            max_keys=1_000_000):
                    if o.metadata.get(META_TRANSITION_TIER) == name:
                        raise TierError(
                            f"tier {name} is in use by "
                            f"{b['name']}/{o.name}")
        with self._mu:
            if self._tiers.pop(name, None) is not None:
                self._persist()

    def list(self) -> list[dict]:
        with self._mu:
            return [{k: v for k, v in t.items() if k != "secret_key"}
                    for t in self._tiers.values()]

    def get(self, name: str) -> dict | None:
        return self._tiers.get(name.upper())

    def _persist(self) -> None:
        self.store.save(TIERS_CONFIG_PATH, {"tiers": self._tiers})

    # -- remote IO ------------------------------------------------------

    def _client(self, tier: dict):
        from ..s3.client import S3Client
        host, _, port = tier["endpoint"].partition(":")
        return S3Client(host, int(port or 80), tier["access_key"],
                        tier["secret_key"])

    @staticmethod
    def _remote_key(tier: dict, bucket: str, key: str) -> str:
        # Unique remote name (ref TransitionedObjName uses a uuid).
        base = f"{bucket}/{key}/{uuid.uuid4().hex[:12]}"
        return f"{tier['prefix']}/{base}" if tier["prefix"] else base

    def upload(self, tier_name: str, bucket: str, key: str,
               data: bytes) -> str:
        """Push stored bytes to the tier; returns the remote key."""
        tier = self.get(tier_name)
        if tier is None:
            raise TierError(f"no such tier {tier_name!r}")
        remote_key = self._remote_key(tier, bucket, key)
        r = self._client(tier).put_object(tier["bucket"], remote_key,
                                          data)
        if r.status != 200:
            raise TierError(f"tier upload failed: {r.status}")
        return remote_key

    def read(self, meta: dict) -> bytes:
        """Stored bytes of a transitioned object, from its stub
        metadata."""
        tier = self.get(meta.get(META_TRANSITION_TIER, ""))
        if tier is None:
            raise TierError("tier vanished for transitioned object")
        r = self._client(tier).get_object(
            tier["bucket"], meta[META_TRANSITION_KEY])
        if r.status != 200:
            raise TierError(f"tier read failed: {r.status}")
        return r.body

    def delete_remote(self, meta: dict) -> None:
        tier = self.get(meta.get(META_TRANSITION_TIER, ""))
        if tier is None:
            return
        try:
            self._client(tier).delete_object(tier["bucket"],
                                             meta[META_TRANSITION_KEY])
        except Exception:
            pass  # best-effort GC; the tier bucket can be swept later


def is_transitioned(meta: dict) -> bool:
    """Object's data lives (also) on a tier."""
    return META_TRANSITION_TIER in meta


def restore_active(meta: dict, now: float | None = None) -> bool:
    raw = meta.get(META_RESTORE_EXPIRY)
    if raw is None:
        return False
    now = time.time() if now is None else now
    try:
        return float(raw) > now
    except ValueError:
        return False


def needs_tier_read(meta: dict, now: float | None = None) -> bool:
    """Reads must go to the tier: transitioned and no live restored
    copy (a restored object serves its LOCAL bytes until expiry, ref
    the restore semantics of GetObjectNInfo)."""
    return is_transitioned(meta) and not restore_active(meta, now)


def transition_object(layer, tiers: TierManager, bucket: str, key: str,
                      tier_name: str,
                      versioned: bool = False) -> bool:
    """Move one object's data to a tier, leaving a stub (ref
    transitionObject, cmd/bucket-lifecycle.go). Returns False when the
    object is not eligible (already transitioned / multipart /
    versioned bucket — a stub cannot replace a version in place)."""
    if versioned:
        return False
    info = layer.get_object_info(bucket, key)
    if is_transitioned(info.metadata):
        return False
    if len(info.parts) > 1:
        # Multipart SSE decryption needs per-part geometry the stub
        # wouldn't keep; skip (same effect as the reference's
        # restrictions on what a tier admits).
        return False
    data, info = layer.get_object(bucket, key)
    remote_key = tiers.upload(tier_name, bucket, key, data)

    meta = dict(info.metadata)
    meta[META_TRANSITION_TIER] = tier_name.upper()
    meta[META_TRANSITION_KEY] = remote_key
    meta[META_TRANSITION_SIZE] = str(info.size)
    meta[META_TRANSITION_ETAG] = info.etag
    meta["x-amz-storage-class"] = tier_name.upper()
    # Close the read-then-overwrite window: if anything re-wrote the
    # object since we read it, abandon the transition (the fresh data
    # wins) and GC the remote upload. The final race remains narrower
    # than one metadata read; a full fix needs an ns-lock spanning the
    # upload, which would stall the data path for the whole transfer.
    try:
        now_info = layer.get_object_info(bucket, key)
    except Exception:
        now_info = None
    if (now_info is None or now_info.etag != info.etag
            or now_info.mod_time != info.mod_time):
        tiers.delete_remote(meta)
        return False
    layer.put_object(bucket, key, b"", metadata=meta)
    try:
        layer.update_object_metadata(bucket, key,
                                     {"etag": info.etag})
    except Exception:
        pass
    return True


def restore_object(layer, tiers: TierManager, bucket: str, key: str,
                   days: int) -> None:
    """Re-materialize a transitioned object locally for `days`; the
    tier pointer stays so the crawler can re-stub after expiry and the
    remote copy is never duplicated (ref RestoreTransitionedObject /
    PostRestoreObjectHandler + restore-expiry handling)."""
    info = layer.get_object_info(bucket, key)
    meta = dict(info.metadata)
    if not is_transitioned(meta):
        raise TierError("object is not transitioned")
    data = tiers.read(meta)
    expiry = time.time() + days * 86400
    expiry_s = time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                             time.gmtime(expiry))
    restored = dict(meta)
    restored[META_RESTORE] = (f'ongoing-request="false", '
                              f'expiry-date="{expiry_s}"')
    restored[META_RESTORE_EXPIRY] = str(expiry)
    orig_etag = meta.get(META_TRANSITION_ETAG, info.etag)
    layer.put_object(bucket, key, data, metadata=restored)
    try:
        layer.update_object_metadata(bucket, key, {"etag": orig_etag})
    except Exception:
        pass


def restub_if_restore_expired(layer, bucket: str, key: str, meta: dict,
                              now: float | None = None) -> bool:
    """Turn an EXPIRED restored copy back into a stub (the crawler's
    restore-expiry sweep; the remote bytes never moved)."""
    now = time.time() if now is None else now
    if not (is_transitioned(meta) and META_RESTORE_EXPIRY in meta
            and not restore_active(meta, now)):
        return False
    stub = {k: v for k, v in meta.items()
            if k not in (META_RESTORE, META_RESTORE_EXPIRY)}
    orig_etag = stub.get(META_TRANSITION_ETAG, "")
    layer.put_object(bucket, key, b"", metadata=stub)
    if orig_etag:
        try:
            layer.update_object_metadata(bucket, key,
                                         {"etag": orig_etag})
        except Exception:
            pass
    return True
