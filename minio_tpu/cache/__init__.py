"""SSD edge-cache ObjectLayer wrapper (ref cmd/disk-cache.go)."""

from .diskcache import CacheConfig, CacheObjectLayer  # noqa: F401
