"""Hot-object serving tier (two-level decoded-object cache with
single-flight fill; see hotcache.py). The former ``CacheObjectLayer``
env-configured gateway wrapper was replaced by this tier in the
erasure data plane — configure it via config-KV (``cache`` subsystem),
not ``MINIO_CACHE_DRIVES``."""

from .hotcache import HOTCACHE, HotObjectCache  # noqa: F401
