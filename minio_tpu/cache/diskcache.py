"""Disk cache: an ObjectLayer wrapper that serves hot reads from local
cache drives (ref cacheObjects, cmd/disk-cache.go:88,
newServerCacheObjects:748; per-drive backend cmd/disk-cache-backend.go).

Semantics mirrored from the reference:
  - object -> cache drive by consistent hash of the key
  - GET validates against the backend's ETag; hit = serve local bytes,
    miss = read backend and populate (async in the reference; inline
    here, it's one local file write)
  - backend unreachable -> serve the cached copy (edge mode)
  - PUT/DELETE write through to the backend and invalidate the entry
  - watermark GC: past `high_watermark`% usage evict by LRU atime
    until under `low_watermark`%
  - only objects <= max_object_size are cached; ranges are sliced out
    of the cached full object
Layout per drive: `<drive>/<sha(bucket/key)>/cache.json` + `data`.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import uuid
from dataclasses import dataclass

from ..erasure.engine import (BucketNotFound, MethodNotAllowed,
                              ObjectInfo, ObjectNotFound)


@dataclass
class CacheConfig:
    drives: list[str] | None = None
    max_object_size: int = 128 * 1024 * 1024
    quota_bytes: int = 0          # 0 = whole drive
    high_watermark: int = 90      # % of quota
    low_watermark: int = 70

    @classmethod
    def from_env(cls, env=os.environ) -> "CacheConfig | None":
        drives = env.get("MINIO_CACHE_DRIVES", "")
        if not drives:
            return None
        return cls(
            drives=[d for d in drives.split(",") if d],
            quota_bytes=int(env.get("MINIO_CACHE_QUOTA_BYTES", "0")),
            high_watermark=int(env.get("MINIO_CACHE_WATERMARK_HIGH",
                                       "90")),
            low_watermark=int(env.get("MINIO_CACHE_WATERMARK_LOW",
                                      "70")),
        )


class _CacheDrive:
    """One cache directory: entry store + LRU GC (ref diskCache,
    cmd/disk-cache-backend.go)."""

    def __init__(self, root: str, quota_bytes: int, hi: int, lo: int):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        if not quota_bytes:
            # "whole drive": cap at the filesystem's capacity so GC
            # still runs before the drive wedges at 100%.
            import shutil as _shutil
            quota_bytes = _shutil.disk_usage(self.root).total
        self.quota = quota_bytes
        self.hi, self.lo = hi, lo
        self._mu = threading.Lock()
        self.hits = 0
        self.misses = 0
        # Running usage counter: a full tree walk only happens once at
        # startup, not on every populate.
        self._used = self.usage_bytes()

    def _entry_dir(self, bucket: str, key: str) -> str:
        h = hashlib.sha256(f"{bucket}/{key}".encode()).hexdigest()
        return os.path.join(self.root, h[:2], h)

    def get(self, bucket: str, key: str) -> tuple[dict, bytes] | None:
        d = self._entry_dir(bucket, key)
        try:
            with open(os.path.join(d, "cache.json")) as f:
                meta = json.load(f)
            with open(os.path.join(d, "data"), "rb") as f:
                data = f.read()
        except (OSError, ValueError):
            return None
        if len(data) != meta.get("size", -1):
            return None  # torn write
        # LRU bump (atime may be disabled by the fs mount).
        try:
            os.utime(os.path.join(d, "cache.json"))
        except OSError:
            pass
        return meta, data

    def put(self, bucket: str, key: str, info: ObjectInfo,
            data: bytes) -> None:
        d = self._entry_dir(bucket, key)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".tmp-{uuid.uuid4().hex[:8]}")
        meta = {"bucket": bucket, "key": key, "etag": info.etag,
                "size": len(data), "mod_time": info.mod_time,
                "metadata": dict(info.metadata),
                "cached_at": time.time()}
        try:
            old_sz = self._entry_size(d)
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, os.path.join(d, "data"))
            with open(tmp, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, os.path.join(d, "cache.json"))
            with self._mu:
                self._used += self._entry_size(d) - old_sz
        except OSError:
            return  # cache is best-effort; never fail the read
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        self.maybe_gc()

    @staticmethod
    def _entry_size(d: str) -> int:
        total = 0
        for fn in ("cache.json", "data"):
            try:
                total += os.path.getsize(os.path.join(d, fn))
            except OSError:
                pass
        return total

    def delete(self, bucket: str, key: str) -> None:
        d = self._entry_dir(bucket, key)
        freed = self._entry_size(d)
        for fn in ("cache.json", "data"):
            try:
                os.remove(os.path.join(d, fn))
            except OSError:
                pass
        with self._mu:
            self._used = max(0, self._used - freed)

    # -- GC -------------------------------------------------------------

    def usage_bytes(self) -> int:
        total = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    pass
        return total

    def maybe_gc(self) -> None:
        """Evict LRU entries once past the high watermark until under
        the low one (ref diskCache.purge watermark loop)."""
        with self._mu:
            used = self._used
            if used * 100 < self.quota * self.hi:
                return
            entries = []  # (mtime, dir, bytes)
            for sub in os.listdir(self.root):
                subp = os.path.join(self.root, sub)
                if not os.path.isdir(subp):
                    continue
                for ent in os.listdir(subp):
                    d = os.path.join(subp, ent)
                    cj = os.path.join(d, "cache.json")
                    try:
                        sz = (os.path.getsize(cj) + os.path.getsize(
                            os.path.join(d, "data")))
                        entries.append((os.path.getmtime(cj), d, sz))
                    except OSError:
                        continue
            entries.sort()  # oldest first
            for _, d, sz in entries:
                if used * 100 <= self.quota * self.lo:
                    break
                for fn in ("cache.json", "data"):
                    try:
                        os.remove(os.path.join(d, fn))
                    except OSError:
                        pass
                used -= sz
            self._used = max(0, used)


class CacheObjectLayer:
    """ObjectLayer wrapper: reads fall back through the cache; writes
    pass through and invalidate (ref cacheObjects GetObjectNInfo /
    PutObject flow, cmd/disk-cache.go)."""

    def __init__(self, backend, config: CacheConfig):
        self.backend = backend
        self.config = config
        self.drives = [
            _CacheDrive(d, config.quota_bytes, config.high_watermark,
                        config.low_watermark)
            for d in (config.drives or [])]
        if not self.drives:
            raise ValueError("disk cache needs at least one drive")

    # Everything not overridden goes straight to the backend —
    # multipart, healer, listings, bucket ops, metadata updates.
    def __getattr__(self, name):
        return getattr(self.backend, name)

    def _drive(self, bucket: str, key: str) -> _CacheDrive:
        h = int.from_bytes(hashlib.sha256(
            f"{bucket}/{key}".encode()).digest()[:4], "big")
        return self.drives[h % len(self.drives)]

    # -- reads ----------------------------------------------------------

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1, version_id: str = "",
                   ) -> tuple[bytes, ObjectInfo]:
        if version_id:  # versioned reads bypass the cache (latest-only)
            return self.backend.get_object(bucket, object_name,
                                           offset=offset, length=length,
                                           version_id=version_id)
        drive = self._drive(bucket, object_name)
        cached = drive.get(bucket, object_name)
        try:
            info = self.backend.get_object_info(bucket, object_name)
        except (ObjectNotFound, BucketNotFound, MethodNotAllowed):
            # Semantic answers (404s) must propagate — a stale cached
            # copy of a deleted object is not "edge mode".
            drive.delete(bucket, object_name)
            raise
        except Exception:
            # Backend down: serve the edge copy if we hold one (ref
            # the cache-on-offline path in cacheObjects.GetObjectNInfo).
            if cached is not None:
                drive.hits += 1
                meta, data = cached
                return self._slice(data, offset, length), \
                    self._info_from_cache(meta)
            raise
        if cached is not None and cached[0]["etag"] == info.etag:
            drive.hits += 1
            return self._slice(cached[1], offset, length), info
        if info.size > self.config.max_object_size:
            # Never cacheable: stream just the requested range.
            return self.backend.get_object(bucket, object_name,
                                           offset=offset, length=length)
        drive.misses += 1
        data, info = self.backend.get_object(bucket, object_name)
        drive.put(bucket, object_name, info, data)
        return self._slice(data, offset, length), info

    def get_object_info(self, bucket: str, object_name: str,
                        version_id: str = "") -> ObjectInfo:
        """HEAD falls back to the cached copy when the backend is
        unreachable — the S3 GET handler stats before reading, so edge
        mode must cover this path too."""
        if version_id:
            return self.backend.get_object_info(bucket, object_name,
                                                version_id)
        try:
            return self.backend.get_object_info(bucket, object_name)
        except (ObjectNotFound, BucketNotFound, MethodNotAllowed):
            raise
        except Exception:
            cached = self._drive(bucket, object_name).get(bucket,
                                                          object_name)
            if cached is not None:
                return self._info_from_cache(cached[0])
            raise

    @staticmethod
    def _slice(data: bytes, offset: int, length: int) -> bytes:
        if offset == 0 and length < 0:
            return data
        if length < 0:
            return data[offset:]
        return data[offset:offset + length]

    @staticmethod
    def _info_from_cache(meta: dict) -> ObjectInfo:
        return ObjectInfo(bucket=meta["bucket"], name=meta["key"],
                          size=meta["size"], etag=meta["etag"],
                          mod_time=meta["mod_time"],
                          metadata=dict(meta["metadata"]))

    # -- writes (through + invalidate) ----------------------------------

    @property
    def supports_streaming_put(self):
        return getattr(self.backend, "supports_streaming_put", False)

    def get_object_stream(self, bucket: str, object_name: str,
                          offset: int = 0, length: int = -1,
                          version_id: str = ""):
        """The cache serves whole objects (ref disk-cache whole-object
        fills, cmd/disk-cache-backend.go): streaming reads route
        through the caching get_object so hits/fills keep working."""
        data, info = self.get_object(bucket, object_name, offset=offset,
                                     length=length,
                                     version_id=version_id)
        return info, iter((data,) if data else ())

    def put_object(self, bucket: str, object_name: str, data,
                   **kw) -> ObjectInfo:
        info = self.backend.put_object(bucket, object_name, data, **kw)
        self._drive(bucket, object_name).delete(bucket, object_name)
        return info

    def delete_object(self, bucket: str, object_name: str,
                      version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        out = self.backend.delete_object(bucket, object_name,
                                         version_id,
                                         versioned=versioned)
        self._drive(bucket, object_name).delete(bucket, object_name)
        return out

    def update_object_metadata(self, bucket: str, object_name: str,
                               updates: dict,
                               version_id: str = "") -> None:
        self.backend.update_object_metadata(bucket, object_name,
                                            updates, version_id)
        # Metadata lives in the cached entry too: drop it.
        self._drive(bucket, object_name).delete(bucket, object_name)

    def put_object_tags(self, bucket: str, object_name: str, tags: str,
                        version_id: str = "") -> None:
        self.backend.put_object_tags(bucket, object_name, tags,
                                     version_id)
        self._drive(bucket, object_name).delete(bucket, object_name)

    # -- stats ----------------------------------------------------------

    def cache_stats(self) -> dict:
        return {
            "drives": [{
                "root": d.root, "hits": d.hits, "misses": d.misses,
                "usedBytes": d.usage_bytes(), "quotaBytes": d.quota,
            } for d in self.drives],
        }
