"""Hot-object serving tier: a two-level (memory + disk) decoded-object
cache consulted by the erasure engine's GET path before shard fan-out.

A million-user workload is dominated by many GETs of few objects, and
without this tier every GET — even of the hottest key — pays a full
k-shard erasure read plus RS decode. The online-EC-on-SSD study
(arXiv:1709.05365) shows queueing on repeated reads, not codec speed,
dominates at that scale. This module is the read-side counterpart of
the PR-3 EncodeCoalescer: where that coalesces concurrent PUT encodes
into one device dispatch, this coalesces concurrent GETs of one key
into one erasure read.

Shape:

  - **Single-flight fill**: concurrent GETs of the same cold key
    register one ``_Fill``; the first reader performs the erasure read
    and tees every decoded chunk into the fill buffer, waiters stream
    from the filling entry as chunks land (``_WaitStream``) — N cold
    GETs of one key cost exactly one shard fan-out + decode. A fill
    that raises (or whose client abandons the stream) wakes and fails
    its waiters, who transparently fall back to their own erasure read
    at the byte position they had reached (mtpu-lint R2 counts fill
    registrations as a resource: no orphaned-waiters path).
  - **QoS-aware admission and eviction**: a TinyLFU-style count-min
    frequency sketch decides retention (``min_hits`` floor, and a
    candidate never displaces a hotter victim), the memory tier is a
    segmented LRU (probation + protected) so one huge scan cannot
    flush the hot set, and background-lane reads (heal, crawler,
    replication sweeps) neither fill nor count frequency — they can
    hit, but a bg sweep can never shape the cache.
  - **Invalidation with versioned epochs**: every overwrite / delete /
    multipart-complete invalidates locally and fans out a
    ``cache_invalidate`` peer RPC carrying a monotonic per-key epoch.
    In-flight fills stamped with an older epoch are discarded at
    finish (overwrite-during-fill can never insert stale bytes). A
    LOST invalidation cannot serve stale bytes either: disk-tier hits
    always revalidate the entry's ETag against a metadata-quorum read,
    and memory-tier hits revalidate once their ``revalidate`` window
    expires — worst-case staleness after a lost RPC is that window,
    not forever.
  - **Drivemon-informed disk-tier placement**: disk-tier directories
    map (by path prefix) to the drive-health monitor's endpoints;
    suspect / faulty / quarantined drives neither receive new cache
    files nor serve existing ones.

Config-KV subsystem ``cache`` (live-reloadable): ``enable``,
``mem_bytes``, ``disk_bytes``, ``dirs``, ``min_hits``,
``max_object_bytes``, ``revalidate``. Everything reports through
metrics2 (hit/miss/fill/coalesced-wait/evict/stale/invalidation
series + byte/entry gauges), lands ``cache.hit`` / ``cache.fill``
span events on the request trace (slowlog blame and timeline
exemplars see it), and the timeline carries a cache row rendered by
``tools/mtpu_top.py``.

Migration note: this tier replaces the former ``CacheObjectLayer``
gateway wrapper (``MINIO_CACHE_DRIVES`` env). The env-only path is
gone; configure the serving tier through config-KV instead, e.g.::

    mc admin config set cache enable=on dirs=/mnt/d1/cache,/mnt/d2/cache
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import shutil
import threading
import time
import uuid
from array import array
from collections import OrderedDict

# One read chunk for disk-tier streaming: ranges are served by seeking
# and reading windows, never by materializing the whole entry.
DISK_READ_CHUNK = 256 * 1024
# Fraction of the memory tier reserved for the protected SLRU segment.
PROTECTED_FRACTION = 0.8
# All disk-tier files live under this subdirectory of each configured
# dir, so (re)configuration can wipe stale files without touching
# anything else on the drive.
DISK_SUBDIR = "mtpu-cache"

MEM, DISK = "mem", "disk"


class FillAborted(Exception):
    """The single-flight fill a waiter was streaming from failed (its
    source raised, or its client abandoned the stream). Carries the
    cause; waiters use it to trigger their fallback read."""


class ClientAbandoned(Exception):
    """The filling client closed its stream before the fill finished."""


class _Sketch:
    """Count-min frequency sketch with TinyLFU-style aging: counters
    halve once the sample window saturates, so frequency estimates
    track the RECENT access mix instead of all history (a scan from an
    hour ago must not outvote today's hot set)."""

    ROWS = 4

    def __init__(self, width: int = 8192):
        self.width = width
        self._rows = [array("I", [0] * width) for _ in range(self.ROWS)]
        self._adds = 0
        # Aging threshold: ~8 samples per counter on average.
        self._sample_max = 8 * width

    def _indexes(self, key) -> list[int]:
        h = hash(key)
        out = []
        for r in range(self.ROWS):
            h = hash((r, h))
            out.append(h % self.width)
        return out

    def add(self, key) -> None:
        for r, i in enumerate(self._indexes(key)):
            self._rows[r][i] += 1
        self._adds += 1
        if self._adds >= self._sample_max:
            self._adds //= 2
            for row in self._rows:
                for i in range(self.width):
                    row[i] >>= 1

    def estimate(self, key) -> int:
        return min(row[i]
                   for row, i in zip(self._rows, self._indexes(key)))


class _Entry:
    """One cached decoded object (either tier)."""

    __slots__ = ("full_key", "nk", "data", "path", "dir", "info",
                 "etag", "size", "epoch", "filled_at", "last_validated",
                 "pins", "dead")

    def __init__(self, full_key, nk, info, etag, size, epoch):
        self.full_key = full_key          # (ns, bucket, key)
        self.nk = nk                      # (bucket, key)
        self.data: bytes | None = None    # memory tier
        self.path: str | None = None      # disk tier file
        self.dir: str | None = None
        self.info = info
        self.etag = etag
        self.size = size
        self.epoch = epoch
        self.filled_at = time.monotonic()
        self.last_validated = self.filled_at
        self.pins = 0                     # active disk-tier readers
        self.dead = False                 # evicted while pinned


class _Fill:
    """One in-flight single-flight fill. The registering reader owns
    it: exactly one of finish() / abort() must run (the reader()
    wrapper guarantees it on every exit path, and mtpu-lint R2 flags
    registrations without a structural release)."""

    def __init__(self, cache: "HotObjectCache", full_key, nk,
                 etag: str, size: int, info, epoch0: int):
        self._cache = cache
        self.full_key = full_key
        self.nk = nk
        self.etag = etag
        self.size = size
        self.info = info
        self.epoch0 = epoch0
        self.invalidated = False          # set under cache._mu
        self.cv = threading.Condition()
        self.chunks: list[bytes] = []
        self.nbytes = 0
        self.done = False
        self.error: BaseException | None = None
        self.waiters = 0

    # Chunks are appended by the single filling thread; waiters only
    # ever read them under cv, so append takes cv alone (never the
    # cache lock — the two locks are never nested, in either order).
    def append(self, chunk: bytes) -> None:
        with self.cv:
            self.chunks.append(bytes(chunk))
            self.nbytes += len(chunk)
            self.cv.notify_all()

    def finish(self) -> None:
        self._cache._finish_fill(self)

    def abort(self, exc: BaseException) -> None:
        self._cache._abort_fill(self, exc)

    def reader(self, source) -> "_FillReader":
        """Wrap the filling reader's chunk iterator: ownership of this
        fill transfers into the returned stream, which finishes or
        aborts it on every exit path."""
        return _FillReader(self, source)


class _FillReader:
    """The filling client's stream: yields source chunks while teeing
    them into the fill buffer. Exhaustion finishes the fill (admission
    decision), any error — including the client abandoning the
    response mid-body — aborts it and wakes the waiters."""

    def __init__(self, fill: _Fill, source):
        self._fill = fill
        self._source = iter(source)
        self._settled = False

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if self._settled:
            raise StopIteration
        try:
            chunk = next(self._source)
        except StopIteration:
            self._settled = True
            self._fill.finish()
            raise
        except BaseException as e:
            self._settled = True
            self._fill.abort(e)
            raise
        self._fill.append(chunk)
        return chunk

    def close(self) -> None:
        if self._settled:
            return
        self._settled = True
        try:
            self._fill.abort(ClientAbandoned(
                f"fill of {self._fill.nk} abandoned mid-stream"))
        finally:
            close = getattr(self._source, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _WaitStream:
    """A coalesced waiter's stream over [offset, offset+length) of a
    fill in progress. If the fill fails, the waiter falls back to its
    own erasure read at the byte position it had reached (``resume``),
    so a dying filler never strands its waiters."""

    def __init__(self, fill: _Fill, offset: int, length: int, resume):
        self._fill = fill
        self._offset = offset
        self._want = length
        self._resume = resume
        self._yielded = 0
        self._chunk_i = 0          # next fill chunk index
        self._chunk_pos = 0        # absolute byte offset of chunk_i
        self._fallback = None
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if self._closed or self._yielded >= self._want:
            raise StopIteration
        if self._fallback is not None:
            chunk = next(self._fallback)
            self._yielded += len(chunk)
            if self._yielded >= self._want:
                # The fallback read covers exactly the remaining range:
                # observe its exhaustion now so a fill it registered
                # settles as finished, not abandoned.
                try:
                    next(self._fallback)
                except StopIteration:
                    pass
            return chunk
        fill = self._fill
        while True:
            with fill.cv:
                while (self._chunk_i >= len(fill.chunks)
                       and not fill.done):
                    # Bounded slices so a lost notify can never hang a
                    # request thread forever.
                    fill.cv.wait(1.0)
                chunks = fill.chunks
                n = len(chunks)
                error = fill.error
                done = fill.done
            while self._chunk_i < n:
                chunk = chunks[self._chunk_i]
                start = self._chunk_pos
                self._chunk_i += 1
                self._chunk_pos += len(chunk)
                lo = max(self._offset, start)
                hi = min(self._offset + self._want, start + len(chunk))
                if hi > lo:
                    piece = chunk[lo - start:hi - start]
                    self._yielded += len(piece)
                    return piece
            if self._yielded >= self._want:
                raise StopIteration
            if done:
                if error is None:
                    # Fill complete and range satisfied short — the
                    # object really ended here.
                    raise StopIteration
                return self._fail_over(error)

    def _fail_over(self, error: BaseException) -> bytes:
        if self._resume is None:
            raise FillAborted(str(error)) from error
        from ..obs.metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_cache_fills_total",
                     {"result": "waiter_fallback"})
        self._fallback = iter(self._resume(self._yielded))
        return self.__next__()

    def close(self) -> None:
        self._closed = True
        fb, self._fallback = self._fallback, None
        if fb is not None and hasattr(fb, "close"):
            try:
                fb.close()
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _DiskStream:
    """Range reader over one disk-tier file: seeks and reads bounded
    windows (never materializes the entry), holding a pin on the entry
    so eviction defers the unlink until the last reader drains."""

    def __init__(self, cache: "HotObjectCache", entry: _Entry,
                 offset: int, length: int):
        self._cache = cache
        self._entry = entry
        self._remaining = length
        self._f = open(entry.path, "rb")
        if offset:
            self._f.seek(offset)
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if self._closed or self._remaining <= 0:
            self.close()
            raise StopIteration
        chunk = self._f.read(min(DISK_READ_CHUNK, self._remaining))
        if not chunk:
            self.close()
            raise StopIteration
        self._remaining -= len(chunk)
        return chunk

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._f.close()
        finally:
            self._cache._unpin(self._entry)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _copy_info(info):
    """Handlers mutate ObjectInfo.metadata; never hand out the cached
    instance itself."""
    out = copy.copy(info)
    out.metadata = dict(info.metadata)
    out.parts = list(info.parts)
    return out


def _span_event(name: str, **attrs) -> None:
    from ..obs.span import TRACER
    sp = TRACER.current()
    if sp is not None:
        sp.add_event(name, **attrs)


class HotObjectCache:
    """Process-wide serving tier (``HOTCACHE``). Keys carry a per-engine
    namespace (``ErasureObjects.cache_ns``) so unrelated engines in one
    process can never serve each other's bytes; invalidation addresses
    ``(bucket, key)`` and clears every namespace (over-invalidation is
    always safe)."""

    def __init__(self):
        self.enabled = False
        self.mem_bytes = 128 * 1024 * 1024
        self.disk_bytes = 1024 * 1024 * 1024
        self.min_hits = 1
        self.max_object_bytes = 32 * 1024 * 1024
        self.revalidate_s: float | None = 1.0   # None = never
        # Called (bucket, key, epoch) after a local invalidation while
        # enabled; the cluster wiring points it at
        # NotificationSys.cache_invalidate (async peer fan-out).
        self.peer_notify = None
        self._mu = threading.Lock()
        self._dirs: list[str] = []
        self._dir_eps: dict[str, str | None] = {}
        self._prob: OrderedDict[tuple, _Entry] = OrderedDict()
        self._prot: OrderedDict[tuple, _Entry] = OrderedDict()
        self._prot_used = 0   # protected-segment bytes, kept incrementally
        self._disk: OrderedDict[tuple, _Entry] = OrderedDict()
        self._by_name: dict[tuple, set[tuple]] = {}
        self._fills: dict[tuple, _Fill] = {}
        self._fill_bytes = 0
        self._mem_used = 0
        self._disk_used = 0
        self._epochs: dict[tuple, int] = {}
        self._sketch = _Sketch()
        self.counters = {
            "hit_mem": 0, "hit_disk": 0, "miss": 0, "fill": 0,
            "coalesced": 0, "evict": 0, "stale": 0, "invalidate": 0}

    # -- config ---------------------------------------------------------

    def configure(self, *, enable: bool, mem_bytes: int,
                  disk_bytes: int, dirs: list[str], min_hits: int,
                  max_object_bytes: int,
                  revalidate_s: float | None) -> None:
        """Live reload (config-KV ``cache`` subsystem). Disabling
        clears both tiers; shrinking evicts down to the new budgets;
        changing the dir set wipes and re-creates the disk tier (cache
        files are ephemeral by contract)."""
        dirs = [os.path.abspath(d) for d in dirs if d]
        with self._mu:
            dirs_changed = dirs != self._dirs
            was_enabled = self.enabled
            self.mem_bytes = int(mem_bytes)
            self.disk_bytes = int(disk_bytes)
            self.min_hits = int(min_hits)
            self.max_object_bytes = int(max_object_bytes)
            self.revalidate_s = revalidate_s
            self.enabled = bool(enable)
        if (was_enabled and not enable) or dirs_changed:
            self.clear()
        if dirs_changed:
            with self._mu:
                self._dirs = dirs
                self._dir_eps = {}
            for d in dirs:
                sub = os.path.join(d, DISK_SUBDIR)
                shutil.rmtree(sub, ignore_errors=True)
                try:
                    os.makedirs(sub, exist_ok=True)
                except OSError:
                    pass
        if enable:
            self._shrink_to_budget()
        self._publish_gauges()

    def clear(self) -> None:
        """Drop every entry and epoch (config disable, tests)."""
        with self._mu:
            unlink = [e for e in self._disk.values() if e.pins == 0]
            for e in self._disk.values():
                e.dead = True
            self._prob.clear()
            self._prot.clear()
            self._disk.clear()
            self._by_name.clear()
            self._epochs.clear()
            self._mem_used = 0
            self._prot_used = 0
            self._disk_used = 0
        for e in unlink:
            self._unlink(e)
        self._publish_gauges()

    def reset(self) -> None:
        """Test hook: clear() plus counters and the frequency sketch."""
        self.clear()
        with self._mu:
            self._sketch = _Sketch()
            for k in self.counters:
                self.counters[k] = 0

    # -- serving --------------------------------------------------------

    def serve(self, ns: str, bucket: str, key: str, offset: int,
              length: int, info_fn):
        """Serve [offset, offset+length) of bucket/key from the cache,
        or return None (miss / bypass). ``info_fn()`` must perform an
        UNCACHED metadata-quorum read returning the current ObjectInfo
        (raising the engine's not-found errors) — it is the ETag
        revalidation oracle for disk-tier hits and for memory-tier
        hits past the revalidation window."""
        if not self.enabled:
            return None
        full_key = (ns, bucket, key)
        fg = self._foreground()
        data = None
        with self._mu:
            if fg:
                self._sketch.add(full_key)
            entry, tier = self._lookup_locked(full_key, touch=fg)
            if entry is not None and tier == DISK:
                if not self._dir_healthy(entry.dir):
                    entry = None    # drive degraded: don't read it
                else:
                    entry.pins += 1
            elif entry is not None:
                # Capture the bytes UNDER the lock: a concurrent
                # capacity demotion rewrites entry.data to None after
                # staging the file — the reference we hold here stays
                # valid regardless.
                data = entry.data
            if entry is None:
                self.counters["miss"] += 1
        if entry is None:
            from ..obs.metrics2 import METRICS2
            METRICS2.inc("minio_tpu_v2_cache_misses_total")
            return None
        try:
            if not self._revalidated(entry, tier, info_fn):
                self.counters["stale"] += 1
                from ..obs.metrics2 import METRICS2
                METRICS2.inc("minio_tpu_v2_cache_stale_total",
                             {"tier": tier})
                METRICS2.inc("minio_tpu_v2_cache_misses_total")
                if tier == DISK:
                    # Release our pin BEFORE invalidating: invalidate
                    # marks the entry dead and defers the unlink to
                    # the last unpin — a pin held across it would leak
                    # the file (and its bytes) forever.
                    self._unpin(entry)
                    tier = None
                self.invalidate(bucket, key, propagate=False,
                                source="stale")
                return None
        except BaseException:
            if tier == DISK:
                self._unpin(entry)
            raise
        size = entry.size
        if offset < 0 or offset > size:
            if tier == DISK:
                self._unpin(entry)
            raise ValueError("invalid range")
        if length < 0:
            length = size - offset
        if offset + length > size:
            if tier == DISK:
                self._unpin(entry)
            raise ValueError("invalid range")
        info = _copy_info(entry.info)
        with self._mu:
            self.counters["hit_mem" if tier == MEM else "hit_disk"] += 1
        from ..obs.metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_cache_hits_total", {"tier": tier})
        _span_event("cache.hit", tier=tier, bytes=length)
        if length == 0 or size == 0:
            if tier == DISK:
                self._unpin(entry)
            return info, iter(())
        if tier == MEM:
            return info, iter((data[offset:offset + length],))
        try:
            return info, _DiskStream(self, entry, offset, length)
        except OSError:
            # File vanished under us (operator wiped the dir): treat
            # as a miss and drop the entry.
            self._unpin(entry)
            self.invalidate(bucket, key, propagate=False,
                            source="stale")
            return None

    def lookup_info(self, ns: str, bucket: str, key: str, info_fn):
        """Serve a HEAD / stat from the MEMORY tier (same revalidation
        policy as data hits; disk-tier stats gain nothing — the
        revalidating metadata read IS the uncached stat)."""
        if not self.enabled:
            return None
        full_key = (ns, bucket, key)
        fg = self._foreground()
        with self._mu:
            entry, tier = self._lookup_locked(full_key, touch=fg)
        if entry is None or tier != MEM:
            return None
        if not self._revalidated(entry, tier, info_fn):
            self.invalidate(bucket, key, propagate=False,
                            source="stale")
            return None
        return _copy_info(entry.info)

    def _lookup_locked(self, full_key, touch: bool = True):
        """Find an entry; when ``touch`` (foreground traffic only),
        LRU-bump it and promote probation -> protected (segmented
        LRU). Background sweeps pass touch=False: they may READ the
        cache but must never refresh recency or flood the protected
        segment — the same scan-pollution shield as the lane-gated
        frequency sketch."""
        e = self._prot.get(full_key)
        if e is not None:
            if touch:
                self._prot.move_to_end(full_key)
            return e, MEM
        if touch:
            e = self._prob.pop(full_key, None)
            if e is not None:
                self._prot[full_key] = e
                self._prot_used += e.size
                self._rebalance_protected()
                return e, MEM
        else:
            e = self._prob.get(full_key)
            if e is not None:
                return e, MEM
        e = self._disk.get(full_key)
        if e is not None:
            if touch:
                self._disk.move_to_end(full_key)
            return e, DISK
        return None, None

    def _rebalance_protected(self) -> None:
        # _prot_used is maintained incrementally: summing the segment
        # here would put O(resident entries) work under the cache lock
        # on every promotion — the hot path this tier exists to trim.
        cap = int(self.mem_bytes * PROTECTED_FRACTION)
        while self._prot_used > cap and len(self._prot) > 1:
            k, e = self._prot.popitem(last=False)
            self._prob[k] = e
            self._prot_used -= e.size

    def _revalidated(self, entry: _Entry, tier: str, info_fn) -> bool:
        """True when the entry may be served. Disk hits ALWAYS check
        the current ETag (a lost invalidation must not serve stale
        bytes from a tier that survives long); memory hits check once
        their revalidation window lapses."""
        now = time.monotonic()
        if tier == MEM:
            if self.revalidate_s is None:
                return True
            if now - entry.last_validated < self.revalidate_s:
                return True
        try:
            info = info_fn()
        except Exception:
            # Not-found or backend failure: either way this copy is
            # not servable without confirmation.
            return False
        if getattr(info, "etag", None) != entry.etag:
            return False
        entry.last_validated = now
        return True

    # -- single-flight fill ---------------------------------------------

    def join_fill(self, ns: str, bucket: str, key: str, etag: str,
                  offset: int, length: int, resume):
        """Join an in-flight fill of the same key+etag: returns a
        waiter stream over the requested range, or None when no
        matching fill is in flight."""
        if not self.enabled:
            return None
        full_key = (ns, bucket, key)
        with self._mu:
            f = self._fills.get(full_key)
            if f is None or f.etag != etag:
                return None
            f.waiters += 1
            self.counters["coalesced"] += 1
        from ..obs.metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_cache_coalesced_waits_total")
        _span_event("cache.fill", coalesced=True, bytes=length)
        return _WaitStream(f, offset, length, resume)

    def begin_fill(self, ns: str, bucket: str, key: str, info):
        """Register the single-flight fill for a FULL-object read, or
        return None (ineligible / someone else already filling / not
        foreground / object too large / fill budget exhausted). The
        returned fill is a resource: route it through ``reader()`` or
        ``abort()`` on every exit path (mtpu-lint R2)."""
        if not self.enabled or not self._foreground():
            return None
        size = int(info.size)
        if size <= 0 or size > self.max_object_bytes:
            return None
        full_key = (ns, bucket, key)
        nk = (bucket, key)
        with self._mu:
            if full_key in self._fills:
                return None
            # In-flight fill buffers are bounded by the memory budget:
            # past it, reads simply pass through uncoalesced.
            if self._fill_bytes + size > max(self.mem_bytes,
                                             self.max_object_bytes):
                return None
            fill = _Fill(self, full_key, nk, info.etag, size,
                         _copy_info(info), self._epochs.get(nk, 0))
            self._fills[full_key] = fill
            self._fill_bytes += size
        _span_event("cache.fill", bytes=size)
        return fill

    def _finish_fill(self, fill: _Fill) -> None:
        # Chunks are appended only by the (single) filling thread —
        # the same one calling finish — so they are stable here.
        data = b"".join(fill.chunks)
        from ..obs.metrics2 import METRICS2
        result = "cached"
        demote = None
        with self._mu:
            self._fills.pop(fill.full_key, None)
            self._fill_bytes -= fill.size
            nk = fill.nk
            if not self.enabled or fill.invalidated or \
                    self._epochs.get(nk, 0) != fill.epoch0:
                # enabled check: a config disable mid-fill already
                # cleared both tiers — admitting this straggler would
                # park unreachable bytes in a cache serve() no longer
                # consults.
                result = "invalidated"
            elif len(data) != fill.size:
                result = "short"   # truncated source; never retain
            elif self._sketch.estimate(fill.full_key) < self.min_hits:
                result = "uncached"
            else:
                entry = _Entry(fill.full_key, nk, fill.info,
                               fill.etag, fill.size,
                               self._epochs.get(nk, 0))
                entry.data = data
                demote = self._admit_mem_locked(entry)
            self.counters["fill"] += 1
            self._prune_epoch_locked(nk)
        METRICS2.inc("minio_tpu_v2_cache_fills_total",
                     {"result": result})
        with fill.cv:
            fill.done = True
            fill.cv.notify_all()
        # Demotions write files — strictly outside the cache lock.
        if demote:
            self._demote_to_disk(demote)
        self._publish_gauges()

    def _abort_fill(self, fill: _Fill, exc: BaseException) -> None:
        from ..obs.metrics2 import METRICS2
        with self._mu:
            self._fills.pop(fill.full_key, None)
            self._fill_bytes -= fill.size
            self.counters["fill"] += 1
            self._prune_epoch_locked(fill.nk)
        METRICS2.inc(
            "minio_tpu_v2_cache_fills_total",
            {"result": "abandoned" if isinstance(exc, ClientAbandoned)
             else "error"})
        with fill.cv:
            fill.error = exc
            fill.done = True
            fill.cv.notify_all()

    # -- admission / eviction -------------------------------------------

    def _admit_mem_locked(self, entry: _Entry) -> list[_Entry]:
        """Insert into the probation segment, evicting LRU victims to
        make room — but never displacing a victim the frequency sketch
        says is hotter than the candidate (TinyLFU admission: scans
        lose to the resident hot set). Returns victims to demote to
        the disk tier (file I/O happens outside the lock)."""
        demote: list[_Entry] = []
        if entry.size > self.mem_bytes:
            demote.append(entry)
            return demote
        cand_freq = self._sketch.estimate(entry.full_key)
        while self._mem_used + entry.size > self.mem_bytes:
            victim_map = self._prob if self._prob else self._prot
            if not victim_map:
                break
            vk = next(iter(victim_map))
            if self._sketch.estimate(vk) > cand_freq:
                # Resident set is hotter: the candidate loses and goes
                # to the disk tier instead.
                demote.append(entry)
                return demote
            victim = victim_map.pop(vk)
            if victim_map is self._prot:
                self._prot_used -= victim.size
            self._mem_used -= victim.size
            self.counters["evict"] += 1
            self._count_evict(MEM, "capacity")
            self._index_discard(victim)
            demote.append(victim)
        if self._mem_used + entry.size > self.mem_bytes:
            demote.append(entry)
            return demote
        self._prob[entry.full_key] = entry
        self._mem_used += entry.size
        self._index_add(entry)
        return demote

    def _prune_epoch_locked(self, nk: tuple) -> None:
        """Drop a key's epoch stamp once nothing references it (no
        entries, no in-flight fill) — epochs must stay bounded under
        write-heavy workloads that never re-read."""
        if not self._by_name.get(nk) and not any(
                f.nk == nk for f in self._fills.values()):
            self._epochs.pop(nk, None)

    def _count_evict(self, tier: str, reason: str) -> None:
        from ..obs.metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_cache_evictions_total",
                     {"tier": tier, "reason": reason})

    def _index_add(self, entry: _Entry) -> None:
        self._by_name.setdefault(entry.nk, set()).add(entry.full_key)

    def _index_discard(self, entry: _Entry) -> None:
        keys = self._by_name.get(entry.nk)
        if keys is not None:
            keys.discard(entry.full_key)
            if not keys:
                self._by_name.pop(entry.nk, None)
                self._prune_epoch_locked(entry.nk)

    def _demote_to_disk(self, victims: list[_Entry]) -> None:
        """Write demoted memory entries into the disk tier (outside the
        cache lock), honoring drive health for placement."""
        unlink: list[_Entry] = []
        for entry in victims:
            if entry.data is None:
                continue
            d = self._pick_dir(entry.full_key)
            if d is None:
                continue
            h = hashlib.sha256(repr(entry.full_key).encode()).hexdigest()
            sub = os.path.join(d, DISK_SUBDIR, h[:2])
            path = os.path.join(sub, h)
            try:
                os.makedirs(sub, exist_ok=True)
                tmp = f"{path}.tmp-{uuid.uuid4().hex[:8]}"
                with open(tmp, "wb") as f:
                    f.write(entry.data)
                os.replace(tmp, path)
                with open(f"{path}.meta", "w") as f:
                    json.dump({"bucket": entry.nk[0],
                               "key": entry.nk[1],
                               "etag": entry.etag,
                               "size": entry.size}, f)
            except OSError:
                continue   # cache is best-effort
            entry.data = None
            entry.path = path
            entry.dir = d
            with self._mu:
                if entry.full_key in self._disk or entry.dead:
                    unlink.append(entry)
                    continue
                self._disk[entry.full_key] = entry
                self._disk_used += entry.size
                self._index_add(entry)
                while self._disk_used > self.disk_bytes and \
                        len(self._disk) > 1:
                    vk, v = self._disk.popitem(last=False)
                    self._disk_used -= v.size
                    self.counters["evict"] += 1
                    self._count_evict(DISK, "capacity")
                    self._index_discard(v)
                    v.dead = True
                    if v.pins == 0:
                        unlink.append(v)
        for e in unlink:
            self._unlink(e)
        self._publish_gauges()

    def _shrink_to_budget(self) -> None:
        unlink: list[_Entry] = []
        with self._mu:
            while self._mem_used > self.mem_bytes and (
                    self._prob or self._prot):
                m = self._prob if self._prob else self._prot
                _, v = m.popitem(last=False)
                if m is self._prot:
                    self._prot_used -= v.size
                self._mem_used -= v.size
                self._count_evict(MEM, "capacity")
                self._index_discard(v)
            while self._disk_used > self.disk_bytes and self._disk:
                _, v = self._disk.popitem(last=False)
                self._disk_used -= v.size
                self._count_evict(DISK, "capacity")
                self._index_discard(v)
                v.dead = True
                if v.pins == 0:
                    unlink.append(v)
        for e in unlink:
            self._unlink(e)

    def _unpin(self, entry: _Entry) -> None:
        with self._mu:
            entry.pins -= 1
            gone = entry.dead and entry.pins == 0
        if gone:
            self._unlink(entry)

    def _unlink(self, entry: _Entry) -> None:
        for p in (entry.path, f"{entry.path}.meta"):
            if not p or p.endswith("None"):
                continue
            try:
                os.remove(p)
            except OSError:
                pass

    # -- invalidation ---------------------------------------------------

    def invalidate(self, bucket: str, key: str, *, propagate: bool = True,
                   source: str = "local", epoch: int | None = None) -> None:
        """Drop every cached copy of bucket/key (all namespaces, both
        tiers) and poison in-flight fills. ``epoch`` carries a peer's
        version stamp (max-merged); local invalidations bump the local
        stamp. Cheap no-op while nothing is cached."""
        nk = (bucket, key)
        if not self.enabled and not self._by_name and not self._fills:
            return
        unlink: list[_Entry] = []
        notify_epoch = None
        with self._mu:
            touched = False
            for full_key in list(self._by_name.get(nk, ())):
                touched = True
                e = self._prob.pop(full_key, None)
                if e is None:
                    e = self._prot.pop(full_key, None)
                    if e is not None:
                        self._prot_used -= e.size
                if e is not None:
                    self._mem_used -= e.size
                    self._count_evict(MEM, "invalidate")
                e = self._disk.pop(full_key, None)
                if e is not None:
                    self._disk_used -= e.size
                    self._count_evict(DISK, "invalidate")
                    e.dead = True
                    if e.pins == 0:
                        unlink.append(e)
            self._by_name.pop(nk, None)
            fills = [f for f in self._fills.values() if f.nk == nk]
            for f in fills:
                f.invalidated = True
                touched = True
            cur = self._epochs.get(nk, 0)
            new = max(cur + 1, epoch or 0)
            if touched:
                self._epochs[nk] = new
                self._prune_epoch_locked(nk)
                self.counters["invalidate"] += 1
            if propagate and self.enabled and \
                    self.peer_notify is not None:
                notify_epoch = new
        if touched:
            from ..obs.metrics2 import METRICS2
            METRICS2.inc("minio_tpu_v2_cache_invalidations_total",
                         {"source": source})
        for e in unlink:
            self._unlink(e)
        if notify_epoch is not None:
            try:
                self.peer_notify(bucket, key, notify_epoch)
            except Exception:
                pass   # peers degrade to their revalidation backstop
        if touched:
            self._publish_gauges()

    def apply_peer_invalidation(self, bucket: str, key: str,
                                epoch: int) -> None:
        """Server side of the ``cache_invalidate`` peer RPC: apply
        without re-propagating (no invalidation storms)."""
        self.invalidate(bucket, key, propagate=False, source="peer",
                        epoch=int(epoch))

    def invalidate_bucket(self, bucket: str) -> None:
        """Bucket deletion: drop every entry under the bucket."""
        with self._mu:
            names = [nk for nk in self._by_name if nk[0] == bucket]
        for nk in names:
            self.invalidate(nk[0], nk[1], propagate=False,
                            source="bucket")

    # -- placement ------------------------------------------------------

    def _dir_endpoint(self, d: str) -> str | None:
        """Map a disk-tier dir to the drivemon endpoint whose path is
        its longest prefix (operators put cache dirs under the data
        mounts, e.g. ``<drive>/cache``); None = no known drive."""
        if d in self._dir_eps:
            return self._dir_eps[d]
        from ..obs.drivemon import DRIVEMON
        best = None
        for ep in DRIVEMON.endpoints():
            root = os.path.abspath(ep)
            if (d == root or d.startswith(root + os.sep)) and \
                    (best is None or len(root) > len(best)):
                best = root
        self._dir_eps[d] = best
        return best

    def _dir_healthy(self, d: str | None) -> bool:
        """Drivemon-informed placement: never place cache files on —
        or serve them from — suspect / faulty / quarantined drives."""
        if d is None:
            return True
        ep = self._dir_endpoint(d)
        if ep is None:
            return True
        from ..obs.drivemon import DRIVEMON, OK
        return (not DRIVEMON.is_quarantined(ep)
                and DRIVEMON.state_of(ep) == OK)

    def _pick_dir(self, full_key) -> str | None:
        healthy = [d for d in self._dirs if self._dir_healthy(d)]
        if not healthy:
            return None
        h = int.from_bytes(hashlib.sha256(
            repr(full_key).encode()).digest()[:4], "big")
        return healthy[h % len(healthy)]

    # -- misc -----------------------------------------------------------

    @staticmethod
    def _foreground() -> bool:
        from ..qos.scheduler import BACKGROUND, current_lane
        return current_lane() != BACKGROUND

    def _publish_gauges(self) -> None:
        from ..obs.metrics2 import METRICS2
        with self._mu:
            mem_used, disk_used = self._mem_used, self._disk_used
            mem_n = len(self._prob) + len(self._prot)
            disk_n = len(self._disk)
        METRICS2.set_gauge("minio_tpu_v2_cache_bytes",
                           {"tier": MEM}, mem_used)
        METRICS2.set_gauge("minio_tpu_v2_cache_bytes",
                           {"tier": DISK}, disk_used)
        METRICS2.set_gauge("minio_tpu_v2_cache_entries",
                           {"tier": MEM}, mem_n)
        METRICS2.set_gauge("minio_tpu_v2_cache_entries",
                           {"tier": DISK}, disk_n)

    def snapshot(self) -> dict:
        """Admin ``/cache-stats`` document."""
        with self._mu:
            c = dict(self.counters)
            hits = c["hit_mem"] + c["hit_disk"]
            lookups = hits + c["miss"]
            return {
                "enabled": self.enabled,
                "memBytesUsed": self._mem_used,
                "memBytesMax": self.mem_bytes,
                "diskBytesUsed": self._disk_used,
                "diskBytesMax": self.disk_bytes,
                "memEntries": len(self._prob) + len(self._prot),
                "diskEntries": len(self._disk),
                "fillsInFlight": len(self._fills),
                "dirs": list(self._dirs),
                "hitRatio": round(hits / lookups, 4) if lookups else 0.0,
                "counters": c,
            }


# The process-wide serving tier every erasure engine consults.
HOTCACHE = HotObjectCache()
