"""Configuration subsystems (ref cmd/config/ tree)."""
