"""Subsystem=KV configuration system (ref cmd/config/config.go:202-273
`Config`/`KVS`, RegisterDefaultKVS:178, SetKVS:636; persistence in
`.minio.sys/config/config.json` via cmd/config-current.go; history +
rollback via the admin `config-history` APIs).

Model: config[subsystem][target] = {key: value}; target "_" is the
default. Environment wins over stored config (`MINIO_<SUBSYS>_<KEY>`,
the reference's env-first rule). Every successful change snapshots the
previous document into `config/history/<ulid>.json` for rollback.
"""

from __future__ import annotations

import copy
import os
import threading
import time
import uuid

DEFAULT_TARGET = "_"

CONFIG_PATH = "config/config.json"
HISTORY_PREFIX = "config/history"
MAX_HISTORY = 10

# Default KVS per subsystem (ref RegisterDefaultKVS callers across
# cmd/config-current.go). Only subsystems this framework actually
# consumes are registered; unknown subsystems are rejected like the
# reference's `Errorf("unknown sub-system")`.
DEFAULT_KVS: dict[str, dict[str, str]] = {
    "api": {
        "requests_max": "0",
        "requests_deadline": "10s",
        # QoS per-class admission caps (0 = unlimited); the global
        # requests_max still bounds the sum (minio_tpu/qos/admission.py).
        "requests_max_read": "0",
        "requests_max_write": "0",
        "requests_max_list": "0",
        "requests_max_admin": "0",
        # SelectObjectContent runs as its OWN admission class: a
        # capped analytics sweep sheds 503 SlowDown instead of
        # competing with PUT/GET for slots (scan kernel dispatches
        # additionally ride the background QoS lane).
        "requests_max_select": "0",
        "cors_allow_origin": "*",
    },
    "compression": {
        "enable": "off",
        "extensions": ".txt,.log,.csv,.json,.tar,.xml,.bin",
        "mime_types": "text/*,application/json,application/xml",
    },
    "scanner": {
        "delay": "10",
        "max_wait": "15s",
    },
    "heal": {
        "bitrotscan": "off",
        "max_sleep": "1s",
        "max_io": "10",
    },
    "storage_class": {
        "standard": "",
        "rrs": "EC:2",
        # Comma-separated buckets whose PUTs default to the REGEN
        # (regenerating-code) class; live-reloadable.
        "regen_buckets": "",
    },
    "region": {
        "name": "us-east-1",
    },
    "logger_webhook": {
        "enable": "off",
        "endpoint": "",
        "auth_token": "",
    },
    "audit_webhook": {
        "enable": "off",
        "endpoint": "",
        "auth_token": "",
    },
    # Internal RPC transport knobs (rpc/transport.py): offline_retry
    # is how long a peer stays health-gated after a failure before a
    # reconnect probe (jittered +0-50% per mark so a restarted peer
    # is not thundering-herded by the whole cluster at once).
    "rpc": {
        "offline_retry": "2s",
    },
    # Commit-path durability (storage/xl.py commit_replace): fsync=on
    # routes every commit rename through fsync-file + fsync-parent-dir
    # so a power cut cannot lose an acknowledged write to the page
    # cache. Default off — the reference's fsync-less reliable-rename
    # — because the overhead is real (bench.py crash_recovery measures
    # it paired; docs/robustness.md documents the tradeoff).
    "storage": {
        "fsync": "off",
    },
    # Runtime fault injection (minio_tpu/faultinject): enable=on with
    # a plan (COMPACT JSON — no spaces — or set it via the admin
    # /fault-inject API) loads the deterministic fault plan at apply
    # time; enable=off clears any config-loaded plan.
    "fault_inject": {
        "enable": "off",
        "plan": "",
    },
    # Hot-object serving tier (cache/hotcache.py): a two-level
    # (memory + disk) decoded-object cache in the erasure GET path
    # with single-flight fill and cross-peer invalidation. `dirs` is a
    # comma-separated list of disk-tier directories (ideally one per
    # data drive — placement is drive-health-aware); empty = memory
    # tier only. `revalidate` bounds worst-case staleness after a LOST
    # peer invalidation ("0" = revalidate every memory hit, "off" =
    # trust invalidation alone). Replaces the removed
    # MINIO_CACHE_DRIVES CacheObjectLayer wrapper.
    "cache": {
        "enable": "off",
        "mem_bytes": "134217728",
        "disk_bytes": "1073741824",
        "dirs": "",
        "min_hits": "1",
        "max_object_bytes": "33554432",
        "revalidate": "1s",
    },
    # Codec dispatch autotuner (ops/autotune.py): autotune=off pins
    # the legacy static device-first policy; hysteresis is the
    # challenger-over-incumbent throughput factor a plan flip needs
    # (>= 1.0 — 1.0 flips on any faster sample); probe_on_boot=off
    # skips the boot probe ladder (the plan then builds from live
    # dispatch samples only).
    "codec": {
        "autotune": "on",
        "hysteresis": "1.25",
        "probe_on_boot": "on",
    },
    # Structured logging (logger/logger.py): json=on makes every
    # console line a JSON object with structured fields (alert lines
    # carry alert_id/rule join keys). MINIO_LOG_JSON=1 is the legacy
    # env spelling and wins over config.
    "logger": {
        "json": "off",
    },
    # SLO watchdog (obs/watchdog.py): multi-window burn-rate alerting
    # over the timeline ring plus built-in event rules (drive census,
    # kernel backend down, MRF backlog, cache collapse, counter-reset
    # storms) — default ON. `rules` is a JSON array of user threshold
    # rules over registered metrics2 series (validated before
    # persist); `webhook_endpoint` enables async alert delivery with
    # bounded queue + bounded retry/backoff.
    "alerts": {
        "enable": "on",
        "fast_window": "1m",
        "slow_window": "15m",
        "burn_threshold": "0.10",
        "pending_ticks": "2",
        "resolve_ticks": "3",
        "rules": "",
        "webhook_endpoint": "",
        "webhook_auth_token": "",
    },
    # Tenant/workload attribution (obs/usage.py): per-bucket/per-key
    # exact accounts over fast/slow windows + SpaceSaving top-K
    # sketches per QoS class. `cardinality_cap` bounds the distinct
    # bucket/tenant names tracked (and the usage_* metric labels) —
    # overflow folds into `_other`; `noisy_share`/`noisy_min_requests`
    # tune the watchdog's noisy_neighbor built-in rule.
    "usage": {
        "enable": "on",
        "top_k": "10",
        "cardinality_cap": "64",
        "fast_window": "1m",
        "slow_window": "15m",
        "noisy_share": "0.5",
        "noisy_min_requests": "20",
    },
    # Slow-request capture SLOs (obs/slowlog.py): any request past its
    # class threshold (ms) lands in the slowlog ring with per-layer
    # blame. Per-class keys override the default; empty = inherit;
    # 0 disables the latency trigger (5xx capture stays on).
    "obs": {
        "slow_ms": "1000",
        "slow_ms_read": "",
        "slow_ms_write": "",
        "slow_ms_list": "",
        "slow_ms_admin": "",
        "slow_ms_select": "",
        "profile_on_slow": "off",
        # Timeline sample ring (obs/timeline.py): one sample every
        # `timeline_sample`, kept for `timeline_retention` at fixed
        # memory (the ring is capacity-clamped; see MAX_SAMPLES).
        "timeline_sample": "1s",
        "timeline_retention": "15m",
        # Event-loop health plane (obs/loopmon.py): a heartbeat
        # overdue past `loop_stall_ms` triggers the stall flight
        # recorder (stack capture + watchdog loop_stall rule);
        # `profile_continuous` keeps the ~1% duty-cycle whole-process
        # profiler running (admin /profile).
        "loop_stall_ms": "250",
        "profile_continuous": "on",
    },
}


class UnknownSubsystem(ValueError):
    pass


class UnknownKey(ValueError):
    pass


def parse_kv_line(line: str) -> tuple[str, str, dict[str, str]]:
    """Parse `subsys[:target] key=value key2="v w"` — the `mc admin
    config set` wire format (ref config.Config.SetKVS)."""
    parts = _split_kv(line.strip())
    if not parts:
        raise ValueError("empty config line")
    subsys, _, target = parts[0].partition(":")
    kvs: dict[str, str] = {}
    for item in parts[1:]:
        k, sep, v = item.partition("=")
        if not sep:
            raise ValueError(f"malformed kv {item!r}")
        kvs[k] = v.strip('"')
    return subsys, target or DEFAULT_TARGET, kvs


def _split_kv(line: str) -> list[str]:
    """Split on spaces, respecting double quotes."""
    out: list[str] = []
    cur = []
    in_q = False
    for ch in line:
        if ch == '"':
            in_q = not in_q
            cur.append(ch)
        elif ch == " " and not in_q:
            if cur:
                out.append("".join(cur))
                cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


class ConfigSys:
    """Stored config + env overrides + history, persisted through the
    quorum ConfigStore (ref globalConfigSys / lookupConfigs)."""

    def __init__(self, store, env=os.environ):
        self.store = store
        self.env = env
        self._apply_hooks: list = []  # callables(config_sys)
        # callables(subsys, target, kvs) raising ValueError to REJECT a
        # change before it persists (ref per-subsystem validation in
        # lookupConfigs).
        self.validators: list = []
        # Coarse TRANSACTION lock: a config write's in-memory mutation,
        # history snapshot, and quorum persist must stay atomic and
        # ordered end-to-end (two racing writers must never persist out
        # of mutation order), so the critical section deliberately
        # spans disk I/O — declared to the runtime sanitizer, which
        # still watches it for lock-order cycles.
        from ..utils.locktrace import transaction_lock
        self._write_mu = transaction_lock(threading.Lock())
        doc = store.load(CONFIG_PATH)
        self._config: dict = doc["config"] if doc else {}

    # -- reads ----------------------------------------------------------

    def get(self, subsys: str, key: str,
            target: str = DEFAULT_TARGET) -> str:
        """Env > stored > default (ref env-first lookup order)."""
        if subsys not in DEFAULT_KVS:
            raise UnknownSubsystem(subsys)
        if key not in DEFAULT_KVS[subsys]:
            raise UnknownKey(f"{subsys}/{key}")
        env_key = f"MINIO_{subsys.upper()}_{key.upper()}"
        if env_key in self.env:
            return self.env[env_key]
        stored = self._config.get(subsys, {}).get(target, {})
        if key in stored:
            return stored[key]
        return DEFAULT_KVS[subsys][key]

    def get_subsys(self, subsys: str,
                   target: str = DEFAULT_TARGET) -> dict[str, str]:
        if subsys not in DEFAULT_KVS:
            raise UnknownSubsystem(subsys)
        return {k: self.get(subsys, k, target)
                for k in DEFAULT_KVS[subsys]}

    def dump(self) -> dict:
        """Full effective config, env overrides applied; every stored
        target appears, not just the default."""
        out: dict = {}
        for sub in sorted(DEFAULT_KVS):
            targets = {DEFAULT_TARGET} | set(
                self._config.get(sub, {}))
            out[sub] = {t: self.get_subsys(sub, t)
                        for t in sorted(targets)}
        return out

    # -- writes ---------------------------------------------------------

    def set_kv(self, line: str) -> None:
        subsys, target, kvs = parse_kv_line(line)
        if subsys not in DEFAULT_KVS:
            raise UnknownSubsystem(subsys)
        for k in kvs:
            if k not in DEFAULT_KVS[subsys]:
                raise UnknownKey(f"{subsys}/{k}")
        for validate in self.validators:
            validate(subsys, target, kvs)  # ValueError rejects
        with self._write_mu:
            self._snapshot_history()
            self._config.setdefault(subsys, {}).setdefault(
                target, {}).update(kvs)
            self._persist()
        self._run_hooks()

    def del_kv(self, spec: str) -> None:
        """Reset `subsys[:target]` back to defaults (same addressing
        as set)."""
        subsys, _, target = spec.strip().partition(":")
        target = target or DEFAULT_TARGET
        if subsys not in DEFAULT_KVS:
            raise UnknownSubsystem(subsys)
        if subsys in self._config:
            with self._write_mu:
                self._snapshot_history()
                self._config[subsys].pop(target, None)
                if not self._config[subsys]:
                    del self._config[subsys]
                self._persist()
            self._run_hooks()

    def _persist(self) -> None:
        self.store.save(CONFIG_PATH, {"version": 1,
                                      "config": self._config})

    # -- history --------------------------------------------------------

    def _snapshot_history(self) -> None:
        # ns resolution: snapshots in the same second must still sort
        # in creation order (restore picks "the latest").
        hid = f"{time.time_ns():020d}-{uuid.uuid4().hex[:6]}"
        self.store.save(f"{HISTORY_PREFIX}/{hid}.json",
                        {"id": hid, "time": time.time(),
                         "config": copy.deepcopy(self._config)})
        # Bound history (ref minioConfigHistoryPrefix GC).
        entries = sorted(self.history_ids())
        for old in entries[:-MAX_HISTORY]:
            self.store.delete(f"{HISTORY_PREFIX}/{old}.json")

    def history_ids(self) -> list[str]:
        names = self.store.list(HISTORY_PREFIX) or []
        return sorted(n.rsplit("/", 1)[-1][:-len(".json")]
                      for n in names if n.endswith(".json"))

    def restore(self, history_id: str) -> None:
        doc = self.store.load(f"{HISTORY_PREFIX}/{history_id}.json")
        if doc is None:
            raise KeyError(history_id)
        with self._write_mu:
            self._snapshot_history()
            self._config = doc["config"]
            self._persist()
        self._run_hooks()

    # -- dynamic apply ---------------------------------------------------

    def on_change(self, hook) -> None:
        """Register a callable(config_sys) run after every successful
        change (the reference's dynamic-subsystem reload,
        config.Config SetKVS dynamic flag)."""
        self._apply_hooks.append(hook)

    def _run_hooks(self) -> None:
        for hook in self._apply_hooks:
            try:
                hook(self)
            except Exception:
                from ..logger import Logger
                Logger.get().log_once(
                    f"config apply hook failed: {hook!r}", "config")
