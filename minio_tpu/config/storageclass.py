"""Storage-class config: per-request data/parity split via
`x-amz-storage-class` (ref cmd/config/storageclass/storage-class.go:
STANDARD/RRS classes, `EC:m` value syntax, GetParityForSC:33-96).

Env (same shape as the reference's MINIO_STORAGE_CLASS_*):
    MINIO_STORAGE_CLASS_STANDARD="EC:4"   parity for STANDARD puts
    MINIO_STORAGE_CLASS_RRS="EC:2"        parity for REDUCED_REDUNDANCY
"""

from __future__ import annotations

import os
from dataclasses import dataclass

STANDARD = "STANDARD"
RRS = "REDUCED_REDUNDANCY"
# Regenerating-code class (this repo's extension): same k+m durability
# as STANDARD but objects are coded with the repair-by-transfer
# product-matrix MBR code (ops/rs_regen.py) — single-shard repair moves
# a fraction of the traffic, at a higher raw-storage overhead.
REGEN = "REGEN"

# Stored in object metadata when the class is non-default (ref
# xhttp.AmzStorageClass handling in putObject).
META_STORAGE_CLASS = "x-amz-storage-class"

DEFAULT_RRS_PARITY = 2  # ref defaultRRSParity


class InvalidStorageClass(Exception):
    pass


def _parse_ec(v: str) -> int | None:
    """Parse 'EC:m' (ref parseStorageClass)."""
    if not v:
        return None
    if not v.startswith("EC:"):
        raise InvalidStorageClass(f"malformed storage class value {v!r}")
    try:
        return int(v[3:])
    except ValueError:
        raise InvalidStorageClass(f"malformed storage class value {v!r}")


def _parse_buckets(v: str) -> frozenset[str]:
    """Parse the comma-separated regen_buckets list (whitespace
    tolerated, empty entries dropped)."""
    return frozenset(b.strip() for b in (v or "").split(",")
                     if b.strip())


@dataclass
class StorageClassConfig:
    """Parity-per-class table for one erasure set size."""
    standard_parity: int | None = None  # None = set default (n/2)
    rrs_parity: int | None = None
    # Buckets whose PUTs default to the REGEN class without a header
    # (config-KV `storage_class regen_buckets=a,b`, live-reloadable).
    regen_buckets: frozenset[str] = frozenset()

    @classmethod
    def from_env(cls, env=os.environ) -> "StorageClassConfig":
        return cls(
            standard_parity=_parse_ec(
                env.get("MINIO_STORAGE_CLASS_STANDARD", "")),
            rrs_parity=_parse_ec(env.get("MINIO_STORAGE_CLASS_RRS", "")),
            regen_buckets=_parse_buckets(
                env.get("MINIO_STORAGE_CLASS_REGEN_BUCKETS", "")),
        )

    def parity_for(self, storage_class: str, n_disks: int,
                   set_default: int) -> int:
        """Parity for a PUT's storage class (ref GetParityForSC).
        Raises InvalidStorageClass for unknown classes or a parity that
        the set geometry cannot hold (need 0 < m <= n/2)."""
        sc = storage_class or STANDARD
        if sc in (STANDARD, REGEN):
            # REGEN keeps STANDARD's parity: equal k+m durability, the
            # repair math is what differs (erasure/regen/).
            m = (set_default if self.standard_parity is None
                 else self.standard_parity)
        elif sc == RRS:
            m = (min(DEFAULT_RRS_PARITY, set_default)
                 if self.rrs_parity is None else self.rrs_parity)
        else:
            raise InvalidStorageClass(f"unknown storage class {sc!r}")
        if not (0 < m <= n_disks // 2):
            raise InvalidStorageClass(
                f"parity {m} invalid for {n_disks}-disk set")
        return m

    def use_regen(self, storage_class: str, bucket: str) -> bool:
        """Should this PUT store under the REGEN class? Per-request
        header wins; otherwise the bucket's config-KV default applies
        (an explicit STANDARD/RRS header opts a single PUT back out)."""
        if storage_class:
            return storage_class == REGEN
        return bucket in self.regen_buckets
