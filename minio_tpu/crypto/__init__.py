"""Server-side encryption (ref cmd/crypto/, cmd/encryption-v1.go)."""
