"""Server-side encryption: SSE-C and SSE-S3 with streaming AEAD.

Ref cmd/encryption-v1.go (EncryptRequest:228, DecryptBlocksRequestR:356,
DecryptObjectInfo:780), cmd/crypto/key.go (ObjectKey seal/unseal),
cmd/crypto/sse-c.go / sse-s3.go (header conventions), and minio/sio's
DARE format (the reference's streaming AEAD dependency).

Scheme (envelope, as the reference):
  - per-object random 256-bit OBJECT KEY encrypts the data;
  - the object key is SEALED (AES-256-GCM, AAD binds bucket/object and
    the SSE domain) by the CLIENT KEY (SSE-C) or the KMS MASTER KEY
    (SSE-S3) and stored in object metadata — rotation/re-keying never
    touches data;
  - data is chunked into 64 KiB packages, each AES-256-GCM sealed with
    a monotonically increasing nonce (DARE 2.0's package structure);
    tampering, truncation and reordering all fail authentication.
"""

from __future__ import annotations

import base64
import hashlib
import os
import struct

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ModuleNotFoundError:  # gated dep: serve plain objects without it
    AESGCM = None

from ..utils.streams import Reader as _StreamsReader


def _require_aesgcm():
    """SSE needs the AES-GCM primitive; without the cryptography
    package the server still boots and serves PLAIN objects — only
    encryption requests fail, at use time, with a clear error. Every
    SSE path passes through seal_key/unseal_key first, so gating those
    two covers the package."""
    if AESGCM is None:
        raise SSEError("SSE unavailable: the 'cryptography' package "
                       "is not installed")

# Metadata keys persisted in xl.meta (ref cmd/crypto/metadata.go —
# X-Minio-Internal-Server-Side-Encryption-* namespace).
META_ALGORITHM = "x-internal-sse-algorithm"      # "sse-c" | "sse-s3"
META_SEALED_KEY = "x-internal-sse-sealed-key"    # b64(nonce|ct|tag)
META_KEY_MD5 = "x-internal-sse-c-key-md5"        # SSE-C key fingerprint
META_KMS_KEY_ID = "x-internal-sse-kms-key-id"
META_KMS_DATA_KEY = "x-internal-sse-kms-data-key"  # KES-wrapped DEK
META_ACTUAL_SIZE = "x-internal-actual-size"      # plaintext length
META_SSE_MULTIPART = "x-internal-sse-multipart"  # per-part derived keys

SSE_C = "sse-c"
SSE_S3 = "sse-s3"

PKG_SIZE = 64 * 1024          # DARE package payload (ref sio maxPayload)
TAG_SIZE = 16
NONCE_SIZE = 12
PKG_OVERHEAD = TAG_SIZE       # per-package ciphertext growth


class SSEError(Exception):
    pass


class KeyMismatch(SSEError):
    """Wrong SSE-C key / tampered sealed key."""


# ---------------------------------------------------------------------------
# key handling


def new_object_key() -> bytes:
    return os.urandom(32)


def derive_part_key(object_key: bytes, part_number: int) -> bytes:
    """Distinct AES key per multipart part (ref ObjectKey.DerivePartKey,
    cmd/crypto/key.go) — one upload-wide key with only random per-part
    nonce bases would risk birthday-bound GCM nonce reuse across
    thousands of parts."""
    import hmac
    return hmac.new(object_key, b"part-%d" % part_number,
                    hashlib.sha256).digest()


def _seal_aad(domain: str, bucket: str, obj: str) -> bytes:
    return f"{domain}:{bucket}/{obj}".encode()


def seal_key(master: bytes, object_key: bytes, domain: str, bucket: str,
             obj: str) -> str:
    """Wrap the object key under a master/client key (ref
    ObjectKey.Seal, cmd/crypto/key.go:71)."""
    _require_aesgcm()
    nonce = os.urandom(NONCE_SIZE)
    ct = AESGCM(master).encrypt(nonce, object_key,
                                _seal_aad(domain, bucket, obj))
    return base64.b64encode(nonce + ct).decode()


def unseal_key(master: bytes, sealed: str, domain: str, bucket: str,
               obj: str) -> bytes:
    _require_aesgcm()
    try:
        raw = base64.b64decode(sealed)
        return AESGCM(master).decrypt(
            raw[:NONCE_SIZE], raw[NONCE_SIZE:],
            _seal_aad(domain, bucket, obj))
    except Exception:
        raise KeyMismatch("cannot unseal object key")


# ---------------------------------------------------------------------------
# streaming AEAD (DARE-style packages)


def _package_nonce(base: bytes, seq: int, final: bool) -> bytes:
    """96-bit nonce = 64-bit random base ^ package sequence, with the
    high bit marking the FINAL package (prevents truncation attacks —
    ref DARE 2.0 final-package flag)."""
    n = struct.unpack(">Q", base[:8])[0] ^ seq
    flag = 0x80000000 if final else 0
    return struct.pack(">QI", n, flag)


def encrypt_stream(data: bytes, object_key: bytes) -> bytes:
    """[8-byte nonce base][pkg0][pkg1]...; each pkg = AESGCM(64KiB)."""
    aead = AESGCM(object_key)
    base = os.urandom(8)
    out = [base]
    npkg = max(1, -(-len(data) // PKG_SIZE))
    for i in range(npkg):
        chunk = data[i * PKG_SIZE:(i + 1) * PKG_SIZE]
        final = i == npkg - 1
        out.append(aead.encrypt(_package_nonce(base, i, final), chunk,
                                None))
    return b"".join(out)


def decrypt_stream(blob: bytes, object_key: bytes) -> bytes:
    aead = AESGCM(object_key)
    base, blob = blob[:8], blob[8:]
    full = PKG_SIZE + PKG_OVERHEAD
    npkg = max(1, -(-len(blob) // full))
    out = []
    for i in range(npkg):
        chunk = blob[i * full:(i + 1) * full]
        final = i == npkg - 1
        try:
            out.append(aead.decrypt(_package_nonce(base, i, final),
                                    chunk, None))
        except Exception:
            raise SSEError(f"package {i}: authentication failed")
    return b"".join(out)


def ciphertext_size(plain_size: int) -> int:
    npkg = max(1, -(-plain_size // PKG_SIZE))
    return 8 + plain_size + npkg * PKG_OVERHEAD


def decrypt_range(read_fn, object_key: bytes, offset: int,
                  length: int) -> bytes:
    """Decrypt only the packages covering [offset, offset+length) of
    the plaintext. read_fn(off, ln) returns ciphertext bytes; caller
    passes the object's stored (ciphertext) size semantics. The final-
    package auth flag needs the total package count, so read_fn(None)
    must return the full ciphertext length (ref DecryptBlocksRequestR
    package-aligned range math, cmd/encryption-v1.go:356)."""
    total_ct = read_fn(None, None)
    full = PKG_SIZE + PKG_OVERHEAD
    npkg = max(1, -(-(total_ct - 8) // full))
    first = offset // PKG_SIZE
    last = (offset + max(length, 1) - 1) // PKG_SIZE
    last = min(last, npkg - 1)
    base = read_fn(0, 8)
    aead = AESGCM(object_key)
    out = []
    for i in range(first, last + 1):
        chunk = read_fn(8 + i * full, full)
        try:
            out.append(aead.decrypt(
                _package_nonce(base, i, i == npkg - 1), chunk, None))
        except Exception:
            raise SSEError(f"package {i}: authentication failed")
    plain = b"".join(out)
    skip = offset - first * PKG_SIZE
    return plain[skip:skip + length]


# ---------------------------------------------------------------------------
# local KMS (master key registry)


class LocalKMS:
    """Single-master-key KMS (ref cmd/crypto/kms.go masterKeyKMS — the
    reference's non-Vault default). Key from MINIO_KMS_SECRET_KEY
    ('name:base64(32B)') or generated ephemeral."""

    def __init__(self, key_id: str = "default",
                 master: bytes | None = None):
        self.key_id = key_id
        # `configured` guards SSE-S3: encrypting under an ephemeral
        # random master would make objects unrecoverable after restart
        # (the reference refuses SSE-S3 without a configured KMS).
        self.configured = master is not None
        self.master = master or os.urandom(32)

    @classmethod
    def from_env(cls, env: str = "") -> "LocalKMS":
        env = env or os.environ.get("MINIO_KMS_SECRET_KEY", "")
        if env and ":" in env:
            name, _, b64 = env.partition(":")
            key = base64.b64decode(b64)
            if len(key) != 32:
                raise SSEError("KMS master key must be 32 bytes")
            return cls(name, key)
        return cls()


# ---------------------------------------------------------------------------
# request-level helpers (header conventions, ref cmd/crypto/sse-c.go)

H_SSE = "x-amz-server-side-encryption"
H_SSEC_ALGO = "x-amz-server-side-encryption-customer-algorithm"
H_SSEC_KEY = "x-amz-server-side-encryption-customer-key"
H_SSEC_KEY_MD5 = "x-amz-server-side-encryption-customer-key-md5"
H_COPY_SSEC_ALGO = \
    "x-amz-copy-source-server-side-encryption-customer-algorithm"
H_COPY_SSEC_KEY = "x-amz-copy-source-server-side-encryption-customer-key"
H_COPY_SSEC_KEY_MD5 = \
    "x-amz-copy-source-server-side-encryption-customer-key-md5"


def parse_ssec_key(headers: dict, copy_source: bool = False) -> bytes | None:
    """Extract + validate an SSE-C customer key from request headers
    (ref ParseSSECustomerRequest, cmd/crypto/sse-c.go)."""
    algo_h = H_COPY_SSEC_ALGO if copy_source else H_SSEC_ALGO
    key_h = H_COPY_SSEC_KEY if copy_source else H_SSEC_KEY
    md5_h = H_COPY_SSEC_KEY_MD5 if copy_source else H_SSEC_KEY_MD5
    if algo_h not in headers:
        return None
    if headers.get(algo_h) != "AES256":
        raise SSEError("SSE-C algorithm must be AES256")
    try:
        key = base64.b64decode(headers.get(key_h, ""))
    except Exception:
        raise SSEError("invalid SSE-C key encoding")
    if len(key) != 32:
        raise SSEError("SSE-C key must be 32 bytes")
    md5 = base64.b64encode(hashlib.md5(key).digest()).decode()
    if headers.get(md5_h, "") != md5:
        raise SSEError("SSE-C key MD5 mismatch")
    return key


def is_encrypted(metadata: dict) -> str:
    """Returns the SSE mode stored in object metadata ('' if plain)."""
    return metadata.get(META_ALGORITHM, "")


# ---------------------------------------------------------------------------
# streaming transforms (O(package) memory)


class EncryptingReader(_StreamsReader):
    """Reader-shaped streaming encryptor: pulls plaintext, emits the
    SAME [8B nonce base][pkg...] DARE stream as encrypt_stream, one
    64KiB package at a time (ref sio's io.Reader pipeline in
    cmd/encryption-v1.go:201 — the buffered round-1..3 path held the
    whole object; round-3 verdict weak #4).

    The final-package flag is part of the nonce, so the reader keeps
    one package of lookahead. At EOF it records the plaintext length
    into `meta` under META_ACTUAL_SIZE (unless compression already
    did) and exposes etag() over the EMITTED ciphertext — matching the
    buffered path's etag. verify() delegates inward.
    """

    def __init__(self, inner, object_key: bytes,
                 meta: dict | None = None):
        import hashlib as _hashlib
        self._inner = inner
        self._aead = AESGCM(object_key)
        self._base = os.urandom(8)
        self._meta = meta
        self._buf = bytearray(self._base)
        self._ahead: bytes | None = None   # lookahead plaintext pkg
        self._started = False
        self._eof = False
        self._seq = 0
        self._md5 = _hashlib.md5()
        self.plain_size = 0

    def _next_plain(self) -> bytes:
        from ..utils.streams import read_exactly
        return read_exactly(self._inner, PKG_SIZE)

    def _pump(self) -> None:
        if not self._started:
            self._ahead = self._next_plain()
            self._started = True
        cur = self._ahead
        nxt = self._next_plain() if cur else b""
        final = not nxt
        # encrypt_stream seals at least one (possibly empty) package.
        self._buf += self._aead.encrypt(
            _package_nonce(self._base, self._seq, final), cur, None)
        self.plain_size += len(cur)
        self._seq += 1
        self._ahead = nxt
        if final:
            self._eof = True
            if self._meta is not None:
                self._meta.setdefault(META_ACTUAL_SIZE,
                                      str(self.plain_size))

    def read(self, n: int) -> bytes:
        while len(self._buf) < n and not self._eof:
            self._pump()
        out = bytes(self._buf[:n])
        del self._buf[:n]
        self._md5.update(out)
        return out

    def etag(self) -> str:
        return self._md5.hexdigest()

    def verify(self) -> None:
        if hasattr(self._inner, "verify"):
            self._inner.verify()


def iter_decrypt(chunks, object_key: bytes, total_ct: int,
                 first_pkg: int = 0, last_pkg: int | None = None):
    """Streaming decrypt: ciphertext-chunk iterator -> plaintext
    package iterator, O(package) memory.

    chunks must start at the nonce base (first_pkg == 0) or exactly at
    package first_pkg's boundary WITH the 8-byte base prepended by the
    caller. total_ct is the object's full stored size (final-package
    flag needs the package count). last_pkg bounds a ranged read: the
    iterator stops after it instead of expecting ciphertext through
    the final package."""
    from ..utils.streams import IterReader, read_exactly
    full = PKG_SIZE + PKG_OVERHEAD
    npkg = max(1, -(-(total_ct - 8) // full))
    stop = npkg if last_pkg is None else min(last_pkg + 1, npkg)
    r = IterReader(chunks)
    base = read_exactly(r, 8)
    if len(base) < 8:
        raise SSEError("truncated ciphertext stream")
    aead = AESGCM(object_key)
    i = first_pkg
    while i < stop:
        final = i == npkg - 1
        pkg = read_exactly(r, full)
        if not pkg and not final:
            raise SSEError("truncated ciphertext stream")
        try:
            yield aead.decrypt(_package_nonce(base, i, final), pkg,
                               None)
        except Exception:
            raise SSEError(f"package {i}: authentication failed")
        i += 1
        if len(pkg) < full:
            break
    if i < stop:
        raise SSEError("truncated ciphertext stream")
