"""Erasure-coded object engine: codec orchestration, bitrot protection,
metadata quorum, parallel shard I/O, healing."""
