"""Bitrot protection: per-shard checksums in the reference's formats.

Two modes (ref cmd/bitrot.go:99-111):
- streaming (default, HighwayHash256S): the shard file interleaves
  [32B hash][shard-block] for every shard_size sub-block
  (ref cmd/bitrot-streaming.go:46-57 write, :115-158 verify-on-read).
- whole-file (legacy): one checksum over the whole shard, stored in
  metadata (ref cmd/bitrot-whole.go).

Algorithms (ref cmd/bitrot.go:33-38): highwayhash256/highwayhash256S
(magic-keyed, byte-identical — ops/hh256 + native C++), blake2b-512,
sha256 (hashlib).
"""

from __future__ import annotations

import hashlib

from ..native import hh256_chunks_native, hh256_native
from ..ops.hh256 import MAGIC_KEY, HighwayHash256
from ..utils import ceil_frac

# Algorithm names as stored in metadata (ref cmd/bitrot.go:33-38).
SHA256 = "sha256"
BLAKE2B = "blake2b"
HIGHWAYHASH256 = "highwayhash256"
HIGHWAYHASH256S = "highwayhash256S"  # streaming mode

DEFAULT_ALGORITHM = HIGHWAYHASH256S

_ALGORITHMS = (SHA256, BLAKE2B, HIGHWAYHASH256, HIGHWAYHASH256S)


def is_streaming(algo: str) -> bool:
    return algo == HIGHWAYHASH256S


def hash_size(algo: str) -> int:
    return {SHA256: 32, BLAKE2B: 64,
            HIGHWAYHASH256: 32, HIGHWAYHASH256S: 32}[algo]


def digest(algo: str, data: bytes) -> bytes:
    if algo in (HIGHWAYHASH256, HIGHWAYHASH256S):
        native = hh256_native(data, MAGIC_KEY)
        if native is not None:
            return native
        return HighwayHash256(MAGIC_KEY).update(data).digest()
    if algo == SHA256:
        return hashlib.sha256(data).digest()
    if algo == BLAKE2B:
        return hashlib.blake2b(data, digest_size=64).digest()
    raise ValueError(f"unsupported bitrot algorithm: {algo}")


def digest_chunks(algo: str, data: bytes, chunk_size: int) -> list[bytes]:
    """Hash consecutive chunk_size chunks (the streaming-bitrot pattern)."""
    if len(data) == 0:
        return []
    if algo in (HIGHWAYHASH256, HIGHWAYHASH256S):
        native = hh256_chunks_native(data, chunk_size, MAGIC_KEY)
        if native is not None:
            return native
    n = ceil_frac(len(data), chunk_size)
    return [digest(algo, data[i * chunk_size:(i + 1) * chunk_size])
            for i in range(n)]


def bitrot_shard_file_size(size: int, shard_size: int, algo: str) -> int:
    """On-disk size of a shard file including interleaved hashes
    (ref cmd/bitrot.go:140)."""
    if not is_streaming(algo):
        return size
    if size < 0:
        return -1
    return ceil_frac(size, shard_size) * hash_size(algo) + size


def encode_stream(data: bytes, shard_size: int,
                  algo: str = DEFAULT_ALGORITHM) -> bytes:
    """Wrap raw shard bytes in the streaming format:
    [hash][block][hash][block]... (ref cmd/bitrot-streaming.go:46)."""
    if not is_streaming(algo):
        return data
    hs = digest_chunks(algo, data, shard_size)
    out = bytearray()
    for i, h in enumerate(hs):
        out += h
        out += data[i * shard_size:(i + 1) * shard_size]
    return bytes(out)


class BitrotMismatch(Exception):
    """Shard sub-block hash mismatch (ref errHashMismatch,
    cmd/bitrot-streaming.go:30)."""


def extract_block(buf: bytes, block_idx: int, chunk: int, shard_size: int,
                  algo: str = DEFAULT_ALGORITHM) -> bytes:
    """Extract + verify one [hash][block] frame from a streaming shard
    buffer whose frame 0 starts at byte 0 (a whole file or a ranged
    window). `chunk` is the expected block payload length."""
    if not is_streaming(algo):
        return buf[block_idx * shard_size:block_idx * shard_size + chunk]
    hsz = hash_size(algo)
    base = block_idx * (hsz + shard_size)
    want = buf[base:base + hsz]
    data = buf[base + hsz:base + hsz + chunk]
    if len(want) < hsz or len(data) < chunk:
        raise BitrotMismatch("truncated shard stream")
    if digest(algo, data) != want:
        raise BitrotMismatch(f"content hash mismatch at block {block_idx}")
    return data


def decode_stream_at(stream: bytes, offset: int, length: int,
                     shard_size: int, algo: str = DEFAULT_ALGORITHM,
                     ) -> bytes:
    """Read logical [offset, offset+length) from a streaming-format shard
    file, verifying every covered sub-block hash
    (ref streamingBitrotReader.ReadAt, cmd/bitrot-streaming.go:115).

    offset must be shard_size-aligned, like the reference.
    """
    if not is_streaming(algo):
        return stream[offset:offset + length]
    if offset % shard_size != 0:
        raise ValueError("offset must be aligned to shard_size")
    hsz = hash_size(algo)
    out = bytearray()
    block_idx = offset // shard_size
    remaining = length
    while remaining > 0:
        base = block_idx * (hsz + shard_size)
        avail = len(stream) - base - hsz
        if avail <= 0:
            raise BitrotMismatch("truncated shard stream")
        chunk = min(shard_size, avail)
        block = extract_block(stream, block_idx, chunk, shard_size, algo)
        take = min(remaining, len(block))
        out += block[:take]
        remaining -= take
        if len(block) < shard_size:
            break  # last (short) block
        block_idx += 1
    if remaining > 0:
        raise BitrotMismatch("short read from shard stream")
    return bytes(out)


def verify_stream(stream: bytes, shard_size: int,
                  algo: str = DEFAULT_ALGORITHM) -> bool:
    """Deep-scan a whole streaming shard file (VerifyFile equivalent,
    ref cmd/xl-storage.go:2312)."""
    if not is_streaming(algo):
        return True
    hsz = hash_size(algo)
    off = 0
    while off < len(stream):
        want = stream[off:off + hsz]
        block = stream[off + hsz:off + hsz + shard_size]
        if len(want) < hsz or len(block) == 0:
            return False
        if digest(algo, block) != want:
            return False
        off += hsz + len(block)
    return True
