"""Bitrot protection: per-shard checksums in the reference's formats.

Two modes (ref cmd/bitrot.go:99-111):
- streaming (default, HighwayHash256S): the shard file interleaves
  [32B hash][shard-block] for every shard_size sub-block
  (ref cmd/bitrot-streaming.go:46-57 write, :115-158 verify-on-read).
- whole-file (legacy): one checksum over the whole shard, stored in
  metadata (ref cmd/bitrot-whole.go).

Algorithms (ref cmd/bitrot.go:33-38): highwayhash256/highwayhash256S
(magic-keyed, byte-identical — ops/hh256 + native C++), blake2b-512,
sha256 (hashlib).
"""

from __future__ import annotations

import hashlib

from ..native import hh256_chunks_native, hh256_native
from ..ops.hh256 import MAGIC_KEY, HighwayHash256
from ..utils import ceil_frac

# Algorithm names as stored in metadata (ref cmd/bitrot.go:33-38).
SHA256 = "sha256"
BLAKE2B = "blake2b"
HIGHWAYHASH256 = "highwayhash256"
HIGHWAYHASH256S = "highwayhash256S"  # streaming mode

DEFAULT_ALGORITHM = HIGHWAYHASH256S

_ALGORITHMS = (SHA256, BLAKE2B, HIGHWAYHASH256, HIGHWAYHASH256S)


def is_streaming(algo: str) -> bool:
    return algo == HIGHWAYHASH256S


def hash_size(algo: str) -> int:
    return {SHA256: 32, BLAKE2B: 64,
            HIGHWAYHASH256: 32, HIGHWAYHASH256S: 32}[algo]


def digest(algo: str, data: bytes) -> bytes:
    if algo in (HIGHWAYHASH256, HIGHWAYHASH256S):
        native = hh256_native(data, MAGIC_KEY)
        if native is not None:
            return native
        return HighwayHash256(MAGIC_KEY).update(data).digest()
    if algo == SHA256:
        return hashlib.sha256(data).digest()
    if algo == BLAKE2B:
        return hashlib.blake2b(data, digest_size=64).digest()
    raise ValueError(f"unsupported bitrot algorithm: {algo}")


def digest_chunks(algo: str, data: bytes, chunk_size: int) -> list[bytes]:
    """Hash consecutive chunk_size chunks (the streaming-bitrot pattern)."""
    if len(data) == 0:
        return []
    if algo in (HIGHWAYHASH256, HIGHWAYHASH256S):
        from ..obs.kernel_stats import HH256, KERNEL, timed
        from ..obs.kernprof import NATIVE
        with timed() as t:
            native = hh256_chunks_native(data, chunk_size, MAGIC_KEY)
        if native is not None:
            KERNEL.record(HH256, False, len(data), t.s,
                          blocks=len(native), backend=NATIVE)
            return native
    n = ceil_frac(len(data), chunk_size)
    return [digest(algo, data[i * chunk_size:(i + 1) * chunk_size])
            for i in range(n)]


# --- batched (device) hashing -------------------------------------------------

# Coalesced full-chunk bytes at or above this go to the TPU kernel
# (ops/hh256_tpu.py); below it, host hashing (C++ native) wins because of
# the ~80ms relay dispatch latency — same policy shape as the RS codec's
# TPU_MIN_BYTES (erasure/codec.py).
HH_TPU_MIN_BYTES = 4 * 1024 * 1024

# Below this many coalesced bytes, host hashing stays on the calling
# thread: a multi-thread fan-out of sub-millisecond native hash calls
# costs more in scheduling than it saves in parallelism.
HOST_HASH_FANOUT_MIN = 8 * 1024 * 1024


def _device_hash_ok(algo: str, chunk_size: int, total_full_bytes: int,
                    ) -> bool:
    if algo not in (HIGHWAYHASH256, HIGHWAYHASH256S):
        return False
    if chunk_size <= 0 or total_full_bytes < HH_TPU_MIN_BYTES:
        return False
    from ..ops import batching
    return batching.device_present()


def _hash_rows_device(stacked, total_bytes: int, n_requests: int):
    """One device dispatch over (B, L) uint8 rows -> (B, 32) digests or
    None on device failure (callers fall back to the host). The batch
    dim pads to the next power of two so jit shapes stay few; padded
    rows' digests are discarded. HH_STATS counts the outcome either
    way."""
    import numpy as np

    from ..ops import batching
    try:
        from ..ops import hh256_tpu
        B = stacked.shape[0]
        cap = 1 << max(B - 1, 0).bit_length()
        if cap != B:
            stacked = np.concatenate(
                [stacked,
                 np.zeros((cap - B, stacked.shape[1]), np.uint8)])
        digs = hh256_tpu.hash_chunks(stacked)[:B]
        batching.HH_STATS.add(True, total_bytes, n_requests)
        return digs
    except Exception as exc:  # noqa: BLE001 - degrade loudly, don't fail IO
        batching.device_dispatch_failed(exc)
        batching.HH_STATS.add(False, total_bytes, n_requests)
        return None


def digest_rows(algo: str, arr):
    """(B, chunk) contiguous uint8 -> (B, hash_size) digests, zero
    input copies on the native/device paths. Byte-identical to
    digest_chunks over arr.tobytes()."""
    import numpy as np
    B = arr.shape[0]
    if B and _device_hash_ok(algo, arr.shape[1], arr.size):
        digs = _hash_rows_device(arr, arr.size, 1)
        if digs is not None:
            return np.asarray(digs, dtype=np.uint8)
    if algo in (HIGHWAYHASH256, HIGHWAYHASH256S):
        from ..native import hh256_rows_native
        from ..obs.kernel_stats import HH256, KERNEL, timed
        from ..obs.kernprof import NATIVE
        with timed() as t:
            out = hh256_rows_native(arr, MAGIC_KEY)
        if out is not None:
            from ..ops import batching
            batching.HH_STATS.add(False, arr.size)
            KERNEL.record(HH256, False, arr.size, t.s, blocks=B,
                          backend=NATIVE)
            return out
    out = np.empty((B, hash_size(algo)), dtype=np.uint8)
    for i in range(B):
        out[i] = np.frombuffer(digest(algo, arr[i].tobytes()),
                               dtype=np.uint8)
    return out


def encode_stream_arrays(arrs, algo: str = DEFAULT_ALGORITHM):
    """Frame per-shard sub-block ARRAYS into streaming-bitrot shard
    chunks with minimal copying — the batched write path's fast lane.

    arrs: one (n_blocks, chunk) contiguous uint8 array per shard (each
    row is one bitrot sub-block). Returns one flat uint8 array per
    shard laid out [hash][block][hash][block]..., byte-identical to
    ``encode_streams`` over the equivalent bytes (pinned by
    tests/test_golden.py) but with ONE data copy (into the frame)
    instead of four (ref cmd/bitrot-streaming.go:46 framing)."""
    import numpy as np
    if not is_streaming(algo):
        return [np.ascontiguousarray(a).reshape(-1) for a in arrs]
    hsize = hash_size(algo)
    # Device path: ONE dispatch over every shard's sub-blocks (they
    # all share the chunk size), mirroring digest_chunks_many.
    per_shard_digs = None
    total = sum(a.size for a in arrs)
    if arrs and _device_hash_ok(algo, arrs[0].shape[1], total):
        stacked = (np.concatenate(arrs, axis=0) if len(arrs) > 1
                   else arrs[0])
        digs = _hash_rows_device(stacked, total, len(arrs))
        if digs is not None:
            digs = np.asarray(digs, dtype=np.uint8)
            per_shard_digs, row = [], 0
            for a in arrs:
                per_shard_digs.append(digs[row:row + a.shape[0]])
                row += a.shape[0]
    if per_shard_digs is None:
        # Host hashing: shards fan out on multicore (the native kernel
        # releases the GIL), sequential where a second core doesn't
        # exist — same policy as _host_digest_many. Small batches stay
        # sequential even on multicore: dispatching k+m sub-millisecond
        # hash jobs costs more in thread wakeups than the hashing
        # itself (measured 3-20ms of scheduling noise for a 1MiB PUT
        # batch vs 0.5ms hashed inline).
        from ..parallel.quorum import MULTICORE, parallel_map
        if len(arrs) > 1 and MULTICORE and total >= HOST_HASH_FANOUT_MIN:
            per_shard_digs, errs = parallel_map(
                [lambda a=a: digest_rows(algo, a) for a in arrs])
            if any(e is not None for e in errs):
                per_shard_digs = None
        if per_shard_digs is None:
            per_shard_digs = [digest_rows(algo, a) for a in arrs]
    out = []
    for a, hs in zip(arrs, per_shard_digs):
        B, S = a.shape
        frame = np.empty((B, hsize + S), dtype=np.uint8)
        frame[:, :hsize] = hs
        frame[:, hsize:] = a
        out.append(frame.reshape(-1))
    return out


def frame_shard(full_rows, tail: bytes | None,
                algo: str = DEFAULT_ALGORITHM) -> bytes:
    """Frame ONE shard's batch contribution: `full_rows` is a
    (n_blocks, shard_size) contiguous uint8 array (or None) of
    full-block sub-blocks, `tail` the final short block's bytes (or
    None). Byte-identical to this shard's slice of
    ``encode_stream_arrays`` + the tail frame of ``encode_streams``
    (pinned by tests/test_pipeline.py golden compare) — but callable
    per shard from the writer fan-out, so the hash of shard j overlaps
    the disk write of shard i on the pipelined PUT path."""
    import numpy as np
    if not is_streaming(algo):
        parts = []
        if full_rows is not None and full_rows.size:
            parts.append(np.ascontiguousarray(full_rows)
                         .reshape(-1).tobytes())
        if tail:
            parts.append(bytes(tail))
        return b"".join(parts)
    hsize = hash_size(algo)
    parts = []
    if full_rows is not None and full_rows.size:
        B, S = full_rows.shape
        frame = np.empty((B, hsize + S), dtype=np.uint8)
        frame[:, :hsize] = digest_rows(algo, full_rows)
        frame[:, hsize:] = full_rows
        parts.append(frame.reshape(-1).tobytes())
    if tail:
        parts.append(digest(algo, tail) + tail)
    return b"".join(parts)


def _host_digest_many(algo: str, streams: list[bytes],
                      chunk_size: int) -> list[list[bytes]]:
    """Host path of digest_chunks_many: on multicore hosts the k+m
    shards hash in parallel — the native HighwayHash kernel releases
    the GIL, so the fan-out is real concurrency."""
    from ..parallel.quorum import MULTICORE, parallel_map
    if len(streams) > 1 and MULTICORE and \
            sum(len(s) for s in streams) >= HOST_HASH_FANOUT_MIN:
        results, errs = parallel_map(
            [lambda s=s: digest_chunks(algo, s, chunk_size)
             for s in streams])
        if not any(e is not None for e in errs):
            return results
    return [digest_chunks(algo, s, chunk_size) for s in streams]


def digest_chunks_many(algo: str, streams: list[bytes], chunk_size: int,
                       ) -> list[list[bytes]]:
    """Per-stream chunk digests, with all full chunks of all streams
    hashed in ONE device dispatch when the coalesced bytes clear the
    policy threshold (the bitrot half of the TPU data plane; north star
    per BASELINE.json — ref cmd/bitrot-streaming.go hashes chunk-by-
    chunk on the CPU, per shard, per block).

    Ragged tail chunks (len % chunk_size) hash on the host: the device
    kernel handles equal-length chunks only.
    """
    full_counts = [len(s) // chunk_size for s in streams]
    total_full = sum(full_counts) * chunk_size
    if not _device_hash_ok(algo, chunk_size, total_full):
        return _host_digest_many(algo, streams, chunk_size)

    import numpy as np
    stacked = np.empty((sum(full_counts), chunk_size), dtype=np.uint8)
    row = 0
    for s, nf in zip(streams, full_counts):
        if nf:
            stacked[row:row + nf] = np.frombuffer(
                s, dtype=np.uint8, count=nf * chunk_size).reshape(
                    nf, chunk_size)
            row += nf
    digs = _hash_rows_device(stacked, total_full, len(streams))
    if digs is None:
        return _host_digest_many(algo, streams, chunk_size)

    out: list[list[bytes]] = []
    row = 0
    for s, nf in zip(streams, full_counts):
        hs = [digs[row + i].tobytes() for i in range(nf)]
        row += nf
        tail = s[nf * chunk_size:]
        if tail:
            hs.append(digest(algo, tail))
        out.append(hs)
    return out


def bitrot_shard_file_size(size: int, shard_size: int, algo: str) -> int:
    """On-disk size of a shard file including interleaved hashes
    (ref cmd/bitrot.go:140)."""
    if not is_streaming(algo):
        return size
    if size < 0:
        return -1
    return ceil_frac(size, shard_size) * hash_size(algo) + size


def encode_stream(data: bytes, shard_size: int,
                  algo: str = DEFAULT_ALGORITHM) -> bytes:
    """Wrap raw shard bytes in the streaming format:
    [hash][block][hash][block]... (ref cmd/bitrot-streaming.go:46)."""
    if not is_streaming(algo):
        return data
    hs = digest_chunks(algo, data, shard_size)
    out = bytearray()
    for i, h in enumerate(hs):
        out += h
        out += data[i * shard_size:(i + 1) * shard_size]
    return bytes(out)


def encode_streams(streams: list[bytes], shard_size: int,
                   algo: str = DEFAULT_ALGORITHM) -> list[bytes]:
    """Batched encode_stream: frame many shards' bytes, hashing ALL
    their sub-blocks in one (device-eligible) digest_chunks_many call —
    the write-path entry for TPU bitrot (engine._encode_batch hands the
    k+m shards of a whole PUT batch here at once)."""
    if not is_streaming(algo):
        return list(streams)
    all_hashes = digest_chunks_many(algo, streams, shard_size)
    out: list[bytes] = []
    for data, hs in zip(streams, all_hashes):
        buf = bytearray()
        for i, h in enumerate(hs):
            buf += h
            buf += data[i * shard_size:(i + 1) * shard_size]
        out.append(bytes(buf))
    return out


def verify_frames(datas: list, wants: list[bytes],
                  algo: str = DEFAULT_ALGORITHM) -> list[bool]:
    """Batch-verify many [hash][block] frames: datas[i] (bytes or uint8
    view) must hash to wants[i]. Equal-length frames coalesce into one
    device dispatch when the policy allows (the read-path entry for TPU
    bitrot — ref streamingBitrotReader verify-per-chunk,
    cmd/bitrot-streaming.go:115, lifted to a batch)."""
    import numpy as np

    def stack_group(idxs: list[int]):
        return np.stack([
            np.frombuffer(datas[i], dtype=np.uint8)
            if not isinstance(datas[i], np.ndarray) else datas[i]
            for i in idxs])

    # Worth one (B, L) stack copy: enough same-length frames that a
    # single rows dispatch beats a Python loop of per-frame calls
    # (~2x on a degraded-GET read window's verify pass).
    HOST_ROWS_MIN_FRAMES = 5
    by_len: dict[int, list[int]] = {}
    for i, d in enumerate(datas):
        by_len.setdefault(len(d), []).append(i)
    ok = [False] * len(datas)
    for length, idxs in by_len.items():
        total = length * len(idxs)
        if length and _device_hash_ok(algo, length, total):
            digs = _hash_rows_device(stack_group(idxs), total,
                                     len(idxs))
            if digs is not None:
                for row, i in enumerate(idxs):
                    ok[i] = digs[row].tobytes() == wants[i]
                continue
        if length and len(idxs) >= HOST_ROWS_MIN_FRAMES and \
                algo in (HIGHWAYHASH256, HIGHWAYHASH256S):
            digs = digest_rows(algo, stack_group(idxs))
            for row, i in enumerate(idxs):
                ok[i] = digs[row].tobytes() == wants[i]
            continue
        for i in idxs:
            d = datas[i]
            if not isinstance(d, (bytes, bytearray)):
                d = bytes(d)
            ok[i] = digest(algo, d) == wants[i]
    return ok


class BitrotMismatch(Exception):
    """Shard sub-block hash mismatch (ref errHashMismatch,
    cmd/bitrot-streaming.go:30)."""


def extract_block(buf: bytes, block_idx: int, chunk: int, shard_size: int,
                  algo: str = DEFAULT_ALGORITHM) -> bytes:
    """Extract + verify one [hash][block] frame from a streaming shard
    buffer whose frame 0 starts at byte 0 (a whole file or a ranged
    window). `chunk` is the expected block payload length."""
    if not is_streaming(algo):
        return buf[block_idx * shard_size:block_idx * shard_size + chunk]
    hsz = hash_size(algo)
    base = block_idx * (hsz + shard_size)
    want = buf[base:base + hsz]
    data = buf[base + hsz:base + hsz + chunk]
    if len(want) < hsz or len(data) < chunk:
        raise BitrotMismatch("truncated shard stream")
    if digest(algo, data) != want:
        raise BitrotMismatch(f"content hash mismatch at block {block_idx}")
    return data


def decode_stream_at(stream: bytes, offset: int, length: int,
                     shard_size: int, algo: str = DEFAULT_ALGORITHM,
                     ) -> bytes:
    """Read logical [offset, offset+length) from a streaming-format shard
    file, verifying every covered sub-block hash
    (ref streamingBitrotReader.ReadAt, cmd/bitrot-streaming.go:115).

    offset must be shard_size-aligned, like the reference.
    """
    if not is_streaming(algo):
        return stream[offset:offset + length]
    if offset % shard_size != 0:
        raise ValueError("offset must be aligned to shard_size")
    hsz = hash_size(algo)
    out = bytearray()
    block_idx = offset // shard_size
    remaining = length
    while remaining > 0:
        base = block_idx * (hsz + shard_size)
        avail = len(stream) - base - hsz
        if avail <= 0:
            raise BitrotMismatch("truncated shard stream")
        chunk = min(shard_size, avail)
        block = extract_block(stream, block_idx, chunk, shard_size, algo)
        take = min(remaining, len(block))
        out += block[:take]
        remaining -= take
        if len(block) < shard_size:
            break  # last (short) block
        block_idx += 1
    if remaining > 0:
        raise BitrotMismatch("short read from shard stream")
    return bytes(out)


def verify_stream(stream: bytes, shard_size: int,
                  algo: str = DEFAULT_ALGORITHM) -> bool:
    """Deep-scan a whole streaming shard file (VerifyFile equivalent,
    ref cmd/xl-storage.go:2312)."""
    if not is_streaming(algo):
        return True
    hsz = hash_size(algo)
    off = 0
    while off < len(stream):
        want = stream[off:off + hsz]
        block = stream[off + hsz:off + hsz + shard_size]
        if len(want) < hsz or len(block) == 0:
            return False
        if digest(algo, block) != want:
            return False
        off += hsz + len(block)
    return True
