"""Erasure codec orchestration: the reference's `Erasure` struct rebuilt
around batched TPU dispatch.

Size semantics are byte-compatible with the reference (ref
cmd/erasure-coding.go:115-143 ShardSize/ShardFileSize/ShardFileOffset and
the Split padding of its codec dependency): objects are striped into
`block_size` blocks; each block splits into k shards of ceil(block/k)
bytes (zero-padded) plus m parity shards.

Backend selection (SURVEY §7 hard part c): the TPU sits behind an ~80ms
relay RPC, so small batches must not pay a device round-trip.  The
crossover is MEASURED, not hardwired: ``ops/autotune.py`` probes every
dispatch lane at boot and refines per-(kernel, batch-size-bucket)
throughput from live dispatches; this module only consults the plan
(pinned ``backend="tpu"|"cpu"`` bypasses it).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..ops import batching, rs_cpu, rs_tpu
from ..ops.autotune import (AUTOTUNE, DEFAULT_DEVICE_MIN_BYTES,
                            RS_DECODE, RS_ENCODE)
from ..utils import ceil_frac

# Default stripe block: 10 MiB (ref cmd/object-api-common.go:32).
BLOCK_SIZE = 10 * 1024 * 1024

# Back-compat alias: the static pre-measurement crossover now lives in
# ops/autotune.py (the one sanctioned hardwired threshold, R9); no
# dispatch decision compares against it here anymore.
TPU_MIN_BYTES = DEFAULT_DEVICE_MIN_BYTES


@dataclass
class Erasure:
    data_blocks: int
    parity_blocks: int
    block_size: int = BLOCK_SIZE
    backend: str = "auto"  # "auto" | "cpu" | "tpu"
    # Home device of the owning erasure set (parallel/mesh.py
    # DeviceAffinity, assigned by ErasureObjects): concurrent sets'
    # dispatches spread across the mesh instead of queueing on chip 0.
    affinity: int | None = field(default=None, repr=False)

    def __post_init__(self):
        if self.data_blocks <= 0 or self.parity_blocks <= 0:
            raise ValueError("data and parity block counts must be positive")
        if self.data_blocks + self.parity_blocks > 256:
            raise ValueError("too many shards (k+m > 256)")

    # --- sizes (byte-compatible with the reference) ---

    @property
    def total_shards(self) -> int:
        return self.data_blocks + self.parity_blocks

    def shard_size(self) -> int:
        """Per-shard size of a full block (ref cmd/erasure-coding.go:115)."""
        return ceil_frac(self.block_size, self.data_blocks)

    def chunk_size(self, block_len: int) -> int:
        """Per-shard stored bytes for a block of block_len bytes (the
        codec-agnostic form the read/heal paths size their frames with
        — RegenErasure's differs from this k-way split)."""
        return ceil_frac(block_len, self.data_blocks)

    def shard_file_size(self, total_length: int) -> int:
        """On-disk per-shard data size for an object of total_length bytes
        (ref cmd/erasure-coding.go:120)."""
        if total_length == 0:
            return 0
        if total_length < 0:
            return -1
        num_shards = total_length // self.block_size
        last_block_size = total_length % self.block_size
        last_shard_size = ceil_frac(last_block_size, self.data_blocks)
        return num_shards * self.shard_size() + last_shard_size

    def shard_file_offset(self, start_offset: int, length: int,
                          total_length: int) -> int:
        """Until-offset for shard reads covering [start, start+length)
        (ref cmd/erasure-coding.go:134)."""
        shard_size = self.shard_size()
        shard_file_size = self.shard_file_size(total_length)
        end_shard = (start_offset + length) // self.block_size
        till = end_shard * shard_size + shard_size
        return min(till, shard_file_size)

    # --- encode / decode ---

    def _use_tpu(self, nbytes: int, kernel: str = RS_ENCODE) -> bool:
        """Route this batch through the jitted rs_tpu path?  Pins win
        ("cpu" never, "tpu" always — the operator asked for errors,
        not silent rerouting); "auto" asks the measured plan
        (ops/autotune.py), which never picks a kernprof-DOWN lane."""
        if self.backend == "cpu":
            return False
        if self.backend == "tpu":
            return True
        return AUTOTUNE.use_jit_lane(kernel, nbytes)

    def _use_tpu_decode(self, nbytes: int) -> bool:
        return self._use_tpu(nbytes, RS_DECODE)

    # Note: the host branches below consult the planner a second time
    # (AUTOTUNE.host_lane) after _use_tpu said "not jit".  Deliberate:
    # _use_tpu is the test-override seam (monkeypatched to force the
    # jit path), so the decision can't be collapsed into one call
    # without breaking it; the second consult is a dict lookup per
    # DISPATCH, and a plan flip between the two calls just falls back
    # to the native-first default — benign and self-correcting.

    def _coalesce_ok(self) -> bool:
        """Route encodes through the cross-request coalescer? Only
        when the backend isn't pinned and the plan still sends encode
        work to a real device — the window buys nothing (and costs its
        latency) in front of host encodes."""
        return (self.backend == "auto"
                and AUTOTUNE.coalesce_worthwhile())

    def encode_data(self, data: bytes | np.ndarray) -> np.ndarray:
        """Encode one block: returns (k+m, shard_len) uint8
        (ref EncodeData, cmd/erasure-coding.go:70)."""
        buf = np.frombuffer(data, dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)) else data
        if buf.size == 0:
            return np.zeros((self.total_shards, 0), dtype=np.uint8)
        shards = rs_cpu.split(buf, self.data_blocks, self.parity_blocks)
        if self.backend == "tpu":
            out = rs_tpu.encode_batch(
                shards[None, :self.data_blocks, :],
                self.data_blocks, self.parity_blocks,
                affinity=self.affinity)[0]
            batching.STATS.add(True, shards[:self.data_blocks].nbytes)
            return out
        data_bytes = shards[:self.data_blocks].nbytes
        if self._coalesce_ok():
            return batching.get_coalescer().encode(
                shards[None, :self.data_blocks, :],
                self.data_blocks, self.parity_blocks,
                affinity=self.affinity)[0]
        if self._use_tpu(data_bytes):
            # Plan picked the jit lane while the coalescer window is
            # off (e.g. XLA-CPU measured fastest with no device): one
            # direct dispatch.
            out = rs_tpu.encode_batch(
                shards[None, :self.data_blocks, :],
                self.data_blocks, self.parity_blocks,
                affinity=self.affinity)[0]
            batching.STATS.add(True, data_bytes)
            return out
        from ..obs.kernel_stats import KERNEL, timed
        from ..ops.rs_matrix import parity_matrix
        with timed() as t:
            parity, host_backend = batching.host_apply_tagged(
                parity_matrix(self.data_blocks, self.parity_blocks),
                shards[:self.data_blocks],
                AUTOTUNE.host_lane(RS_ENCODE, data_bytes))
            shards[self.data_blocks:] = parity
        batching.STATS.add(False, data_bytes)
        KERNEL.record(RS_ENCODE, False, data_bytes, t.s, blocks=1,
                      backend=host_backend)
        return shards

    def encode_blocks_batch(self, blocks: np.ndarray) -> np.ndarray:
        """Batched encode of (B, k, S) pre-split blocks -> (B, k+m, S).
        The heal/multipart fast path: one device dispatch for many blocks
        (and still coalescable with concurrent requests)."""
        if self._use_tpu(blocks.nbytes):
            out = rs_tpu.encode_batch(blocks, self.data_blocks,
                                      self.parity_blocks,
                                      affinity=self.affinity)
            batching.STATS.add(True, blocks.nbytes)
            return out
        if self._coalesce_ok():
            return batching.get_coalescer().encode(
                blocks, self.data_blocks, self.parity_blocks,
                affinity=self.affinity)
        return batching.host_encode(
            blocks, self.data_blocks, self.parity_blocks,
            lane=AUTOTUNE.host_lane(RS_ENCODE, blocks.nbytes))

    def encode_blocks_batch_shardmajor(self, blocks: np.ndarray,
                                       ) -> np.ndarray:
        """Batched encode returning SHARD-MAJOR (k+m, B, S) contiguous —
        the layout the bitrot framer wants. The pure-host path encodes
        straight into that layout (two full-batch copies cheaper); the
        device/coalescer path reuses encode_blocks_batch and pays one
        transpose copy."""
        if self._use_tpu(blocks.nbytes) or self._coalesce_ok():
            encoded = self.encode_blocks_batch(blocks)
            return np.ascontiguousarray(encoded.transpose(1, 0, 2))
        return batching.host_encode_shardmajor(
            blocks, self.data_blocks, self.parity_blocks,
            lane=AUTOTUNE.host_lane(RS_ENCODE, blocks.nbytes))

    def decode_data_blocks(self, shards: list[np.ndarray | None],
                           ) -> list[np.ndarray]:
        """Reconstruct missing DATA shards in place of Nones
        (ref DecodeDataBlocks, cmd/erasure-coding.go:89)."""
        return self.decode_data_blocks_batch([shards])[0]

    def decode_all_blocks(self, shards: list[np.ndarray | None],
                          ) -> list[np.ndarray]:
        """Reconstruct ALL missing shards (heal path; ref
        DecodeDataAndParityBlocks, cmd/erasure-coding.go:106)."""
        return self.decode_all_blocks_batch([shards])[0]

    def decode_data_blocks_batch(self, blocks: list,
                                 ) -> list[list[np.ndarray]]:
        """Mask-grouped batched data reconstruct: blocks sharing an
        erasure signature collapse into one device dispatch
        (ops/batching.py; the TPU-native replacement for the reference's
        per-call ReconstructData, cmd/erasure-decode.go:214)."""
        return batching.reconstruct_blocks(
            blocks, self.data_blocks, self.parity_blocks,
            want_all=False, use_device=self._use_tpu_decode,
            device_fallback=self.backend != "tpu",
            affinity=self.affinity)

    def decode_all_blocks_batch(self, blocks: list,
                                ) -> list[list[np.ndarray]]:
        """Mask-grouped batched full reconstruct (heal): data and parity
        rebuilt by a single combined matrix per mask group."""
        return batching.reconstruct_blocks(
            blocks, self.data_blocks, self.parity_blocks,
            want_all=True, use_device=self._use_tpu_decode,
            device_fallback=self.backend != "tpu",
            affinity=self.affinity)


def codec_for_algorithm(algorithm: str | None, data_blocks: int,
                        parity_blocks: int,
                        block_size: int = BLOCK_SIZE,
                        backend: str = "auto",
                        affinity: int | None = None):
    """The codec for an xl.meta erasure algorithm stamp: plain RS
    (`rs-vandermonde`, the default and the value every pre-REGEN object
    carries) or the regenerating-code class (`pm-mbr-rbt`).  Lazy
    imports keep codec.py free of the regen subsystem for the common
    path and avoid the metadata<->ops cycle."""
    from ..storage.metadata import REGEN_ALGORITHM
    if algorithm == REGEN_ALGORITHM:
        from .regen import RegenErasure
        return RegenErasure(data_blocks, parity_blocks, block_size,
                            backend=backend, affinity=affinity)
    return Erasure(data_blocks, parity_blocks, block_size,
                   backend=backend, affinity=affinity)
