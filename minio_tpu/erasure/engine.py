"""ErasureObjects — one erasure set's object engine.

The analog of the reference's erasureObjects (ref cmd/erasure.go:48,
cmd/erasure-object.go): quorum metadata read/write, shard I/O
orchestration over StorageAPI disks, encode via the TPU codec, bitrot
wrap/verify, degraded reads with reconstruction.

Write path (ref putObject, cmd/erasure-object.go:582 / call stack §3.2):
    split blocks -> batched encode (TPU) -> bitrot-wrap shard streams ->
    parallel tmp write on all disks (write-quorum tolerant) ->
    rename_data commit (atomic per disk, quorum again).

Read path (ref getObjectWithFileInfo, cmd/erasure-object.go:240):
    read xl.meta all disks -> FileInfo quorum -> read k shards
    (first-k-wins with fallback to parity disks) -> reconstruct missing ->
    join + trim.
"""

from __future__ import annotations

import hashlib
import time
import uuid
from dataclasses import dataclass, field

import numpy as np

from ..faultinject import FAULTS
from ..parallel.quorum import (MULTICORE, QuorumError, hash_order,
                               parallel_map, read_quorum,
                               reduce_quorum_errs, submit, write_quorum)
from ..storage import errors as serr
from ..storage.interface import StorageAPI
from ..storage.metadata import (ERASURE_ALGORITHM, ErasureInfo, FileInfo,
                                ObjectPartInfo, new_data_dir,
                                new_version_id, now)
from ..storage.xl import INTENT_FILE, MINIO_META_BUCKET, TMP_PATH
from ..utils import ceil_frac
from . import bitrot
from .codec import BLOCK_SIZE, Erasure

from ..storage.interface import DATA_DIR_RE


def _looks_like_data_dir(name: str) -> bool:
    """Data dirs are uuid4 names (metadata.new_data_dir)."""
    return bool(DATA_DIR_RE.match(name))


# Crash points on the engine-level PUT commit (the per-disk windows
# live in storage/xl.py rename_data): staged-but-uncommitted, and
# quorum-committed-but-ungarbage-collected. Armed via the fault plan
# (kind "crash"); tests/test_crash_consistency.py asserts the restart
# invariants for each.
CRASH_PUT_STAGED = FAULTS.register_crash_point("engine.put.post_stage")
CRASH_PUT_COMMITTED = FAULTS.register_crash_point(
    "engine.put.post_commit")


def _stage_intent_blob(bucket: str, object_name: str, version_id: str,
                       data_dir: str) -> bytes:
    """The recovery breadcrumb dropped into every staging dir
    (storage/recovery.py reads it at boot to requeue the object for
    heal before GC-ing the orphaned stage)."""
    import json
    return json.dumps({"bucket": bucket, "object": object_name,
                       "versionId": version_id,
                       "dataDir": data_dir}).encode()


class ObjectNotFound(Exception):
    pass


class MethodNotAllowed(Exception):
    """GET/HEAD of a delete marker addressed by explicit versionId
    (S3 returns 405; ref toAPIErrorCode MethodNotAllowed mapping)."""
    pass


class BucketNotFound(Exception):
    pass


class BucketExists(Exception):
    pass


@dataclass
class ObjectInfo:
    bucket: str
    name: str
    size: int = 0
    etag: str = ""
    mod_time: float = 0.0
    version_id: str = ""
    delete_marker: bool = False
    metadata: dict = field(default_factory=dict)
    parts: list[ObjectPartInfo] = field(default_factory=list)

    @classmethod
    def from_file_info(cls, fi: FileInfo) -> "ObjectInfo":
        return cls(bucket=fi.volume, name=fi.name, size=fi.size,
                   etag=fi.metadata.get("etag", ""), mod_time=fi.mod_time,
                   version_id=fi.version_id, delete_marker=fi.deleted,
                   metadata=dict(fi.metadata), parts=list(fi.parts))


class _LockedStream:
    """Chunk iterator that owns a namespace read lock: released on
    exhaustion, close(), error, or GC — so an abandoned streaming GET
    can't pin the object's lock."""

    def __init__(self, lock_ctx, gen):
        self._ctx = lock_ctx  # already entered
        self._gen = gen
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        if self._closed:
            raise StopIteration
        try:
            return next(self._gen)
        except BaseException:
            self.close()
            raise

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._gen.close()
        finally:
            self._ctx.__exit__(None, None, None)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ErasureObjects:
    """Object engine over one erasure set of k+m disks."""

    # PUT accepts chunk readers (O(batch) streaming pipeline).
    supports_streaming_put = True

    def __init__(self, disks: list[StorageAPI],
                 data_shards: int | None = None,
                 parity_shards: int | None = None,
                 block_size: int = BLOCK_SIZE):
        n = len(disks)
        if n < 2:
            raise ValueError("an erasure set needs >= 2 disks")
        if data_shards is None:
            # Default split: half data, half parity (ref default
            # storage-class N/2:N/2, cmd/config/storageclass).
            parity_shards = n // 2
            data_shards = n - parity_shards
        elif parity_shards is None:
            parity_shards = n - data_shards
        if data_shards + parity_shards != n:
            raise ValueError("k + m must equal the number of disks")
        self.disks = list(disks)
        self.k = data_shards
        self.m = parity_shards
        self.block_size = block_size
        # Drive-health peer group: this set's disks score each other's
        # latency EWMAs relative to the set median (obs/drivemon.py) —
        # a laggard drive is only an outlier against its own quorum
        # peers, never against unrelated pools.
        from ..obs.drivemon import DRIVEMON, drive_key

        # Per-disk health identity, index-aligned with self.disks: the
        # read-selection, hedging, and quarantine paths all key the
        # monitor by it.
        self.endpoints = [drive_key(d) for d in self.disks]
        DRIVEMON.register_set(self.endpoints)
        # Hedged shard reads (the reaction half of drive health): when
        # a shard read straggles past the adaptive budget — multiplier
        # x rolling p75 of healthy shard reads (utils/dyntimeout.py
        # PercentileBudget) — a backup read of a spare shard fires on
        # the background QoS lane; first response wins, the loser is
        # discarded. This bounds GET tail latency by the budget, not
        # the straggler (arXiv:1709.05365's regime; any-k-of-n reads
        # per arXiv:1504.07038).
        from ..utils.dyntimeout import PercentileBudget
        self.hedge_enabled = True
        # Floor sits above OS-scheduler jitter (tens of ms under
        # contention): a stall the scheduler alone can cause must not
        # fire backup I/O, or a busy box hedges every read.
        self.hedge_budget = PercentileBudget(
            multiplier=4.0, floor=0.050, ceiling=2.0)
        # Streaming-pipeline knobs: how many bytes one encode dispatch /
        # one read window group covers, and how many batches/groups may
        # be in flight at once (utils/pipeline.py). Peak data-plane
        # memory is O(pipeline_depth × batch), independent of object
        # size. Batches are sized so a multi-batch stream actually
        # pipelines (several batches per large part) while one encode
        # dispatch still clears the device-batching threshold
        # (codec.TPU_MIN_BYTES).
        from ..utils.pipeline import DEFAULT_DEPTH
        from ..utils.streams import DEFAULT_BATCH_BYTES, PUT_BATCH_BYTES
        self.put_batch_bytes = PUT_BATCH_BYTES
        self.read_group_bytes = DEFAULT_BATCH_BYTES // 2
        self.pipeline_depth = DEFAULT_DEPTH
        self.codec = Erasure(data_shards, parity_shards, block_size)
        self._codec_cache: dict[tuple[int, int], Erasure] = {}
        from ..parallel.nslock import LocalNSLock
        from .heal import Healer, MRFQueue, NewDiskMonitor
        from .multipart import MultipartUploads
        from .heal import QuarantineProber
        self.healer = Healer(self)
        self.mrf = MRFQueue(self.healer)
        # Not started by default; the server boot starts it (tests and
        # library users drive tick() directly).
        self.new_disk_monitor = NewDiskMonitor(self.healer)
        # Probation probes for quarantined drives (same start contract
        # as the new-disk monitor: server boot starts it, tests drive
        # tick() directly).
        self.quarantine_prober = QuarantineProber(self)
        self.multipart = MultipartUploads(self)
        # Namespace locks: in-process by default; distributed deployments
        # inject a dsync-backed provider (ref ObjectLayer.NewNSLock).
        self.ns_lock = LocalNSLock()
        # Listing engine + change tracking (ref metacache + bloom
        # dataUpdateTracker; cmd/metacache-server-pool.go:38).
        from ..listing.metacache import MetacacheManager
        from ..scanner.tracker import DataUpdateTracker
        self.update_tracker = DataUpdateTracker()
        self.metacache = MetacacheManager(self)
        # Hot-object serving tier namespace (cache/hotcache.py): GETs
        # consult the process-wide HOTCACHE under this engine-unique
        # prefix, so two unrelated engines in one process (test
        # fixtures, multi-pool layouts) can never serve each other's
        # bytes; invalidation addresses (bucket, key) and clears every
        # namespace.
        self.cache_ns = uuid.uuid4().hex[:16]
        # Per-set device affinity (parallel/mesh.py DeviceAffinity):
        # on a multi-chip mesh each erasure set gets a home device, so
        # concurrent sets' codec dispatches spread across chips
        # instead of all queueing on device 0 (None off-mesh; jax
        # failures must never block engine construction).
        try:
            from ..parallel.mesh import MESH_AFFINITY
            self.device_affinity = MESH_AFFINITY.assign(self.cache_ns)
        except Exception:
            self.device_affinity = None
        self.codec.affinity = self.device_affinity

    def shutdown(self) -> None:
        """Stop this engine's background daemons — the MRF heal queue
        worker, the new-disk monitor, and the quarantine prober. A
        stopped deployment's daemons must not keep healing into the
        void: a test or embedder that drops the engine otherwise leaks
        threads that churn dead disks (and steal CPU from whatever
        runs next in the process). Server shutdown calls this; safe to
        call twice."""
        self.healer.shutdown()
        self.mrf.stop()
        self.new_disk_monitor.stop()
        self.quarantine_prober.stop()
        if getattr(self, "device_affinity", None) is not None:
            try:
                from ..parallel.mesh import MESH_AFFINITY
                MESH_AFFINITY.release(self.cache_ns)
            except Exception:
                pass

    def _mark_update(self, bucket: str, object_name: str = "") -> None:
        self.update_tracker.mark(bucket, object_name)

    # ------------------------------------------------------------------
    # buckets

    # Bucket create/delete serialize on a meta lock (ref MakeBucket /
    # DeleteBucket taking the bucket's lock, cmd/erasure-server-pool.go):
    # two racing, per-disk-parallel ops could otherwise BOTH "succeed"
    # while leaving the volume on half the disks.
    def _bucket_meta_lock(self, bucket: str):
        return self.ns_lock.write_locked(MINIO_META_BUCKET,
                                         f"buckets/{bucket}")

    def make_bucket(self, bucket: str) -> None:
        self._check_not_reserved(bucket)
        with self._bucket_meta_lock(bucket):
            self._make_bucket_locked(bucket)

    def _make_bucket_locked(self, bucket: str) -> None:
        _, errs = parallel_map(
            [lambda d=d: d.make_volume(bucket) for d in self.disks])
        exists = [isinstance(e, serr.VolumeExists) for e in errs]
        if any(exists) and not any(e is None for e in errs):
            # No disk actually created it -> it already exists (faulty
            # disks tolerated; heal converges stragglers later).
            raise BucketExists(bucket)
        # A disk where the volume already exists counts as success.
        eff = [None if ex else e for e, ex in zip(errs, exists)]
        try:
            reduce_quorum_errs(eff, len(self.disks) // 2 + 1, "make_bucket")
        except QuorumError:
            # Roll back partial creates.
            parallel_map([lambda d=d: d.delete_volume(bucket, force=True)
                          for d, e in zip(self.disks, errs) if e is None])
            raise

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self._check_not_reserved(bucket)
        with self._bucket_meta_lock(bucket):
            self._delete_bucket_locked(bucket, force)

    def _delete_bucket_locked(self, bucket: str, force: bool) -> None:
        _, errs = parallel_map(
            [lambda d=d: d.delete_volume(bucket, force=force)
             for d in self.disks])
        def undo_removals():
            # Restore volumes on disks where OUR delete succeeded (ref
            # undoDeleteBucketSets, cmd/erasure-sets.go:723) — the
            # bucket must stay fully present, not on a random subset.
            parallel_map([lambda d=d: d.make_volume(bucket)
                          for d, e in zip(self.disks, errs) if e is None])

        if any(isinstance(e, serr.VolumeExists) for e in errs):
            # Non-empty somewhere (e.g. a racing PUT committed there).
            undo_removals()
            raise BucketExists(f"{bucket} not empty")
        if all(isinstance(e, serr.VolumeNotFound) for e in errs):
            raise BucketNotFound(bucket)
        # A disk where the volume is already absent counts as success:
        # deletion is idempotent, and a concurrent delete_bucket racing
        # this one may have removed some volumes first — the combined
        # outcome (bucket gone) is what both callers asked for.
        eff = [None if isinstance(e, serr.VolumeNotFound) else e
               for e in errs]
        try:
            reduce_quorum_errs(eff, len(self.disks) // 2 + 1,
                               "delete_bucket")
        except QuorumError:
            # Below quorum (real disk errors): undo what we removed.
            undo_removals()
            raise
        self.metacache.drop_bucket(bucket)
        from ..cache.hotcache import HOTCACHE
        HOTCACHE.invalidate_bucket(bucket)
        self._mark_update(bucket)

    def list_buckets(self) -> list[dict]:
        """Volumes held by a MAJORITY of responding disks.

        First-healthy-disk semantics (ref cmd/erasure-bucket.go) break
        when a wiped replacement disk answers with an empty listing;
        a plain union breaks the other way, resurrecting buckets that
        were deleted at write quorum while one disk was offline (the
        stale minority copy would reappear). Majority-of-responding
        matches both: a fresh disk is a minority of absences, a stale
        survivor is a minority of presences."""
        def one(disk):
            return [disk.stat_volume(v) for v in disk.list_volumes()]

        results, errs = parallel_map(
            [lambda d=d: one(d) for d in self.disks])
        responding = sum(1 for e in errs if e is None)
        seen: dict[str, dict] = {}
        counts: dict[str, int] = {}
        for stats, e in zip(results, errs):
            if e is not None:
                continue
            for st in stats or []:
                counts[st["name"]] = counts.get(st["name"], 0) + 1
                cur = seen.get(st["name"])
                if cur is None or st.get("created", 0) < cur.get(
                        "created", 0):
                    seen[st["name"]] = st
        return sorted(
            (st for name, st in seen.items()
             if counts[name] * 2 > responding),
            key=lambda s: s["name"])

    def bucket_exists(self, bucket: str) -> bool:
        """True if any reachable disk has the bucket and no not-found
        majority exists (reads tolerate offline disks; ref getBucketInfo
        first-healthy-disk semantics, cmd/erasure-bucket.go)."""
        _, errs = parallel_map(
            [lambda d=d: d.stat_volume(bucket) for d in self.disks])
        ok = sum(1 for e in errs if e is None)
        not_found = sum(1 for e in errs
                        if isinstance(e, serr.VolumeNotFound))
        return ok >= 1 and not_found <= len(self.disks) // 2

    @staticmethod
    def _check_not_reserved(bucket: str) -> None:
        """The system namespace is never reachable through the object API
        (ref isReservedOrInvalidBucket checks on every handler)."""
        if bucket == MINIO_META_BUCKET or bucket.startswith(
                MINIO_META_BUCKET + "/"):
            raise BucketNotFound(bucket)

    def _raise_if_bucket_gone(self, errs, bucket: str, *,
                              for_write: bool = False,
                              wq: int | None = None) -> None:
        """Map VolumeNotFound evidence to NoSuchBucket instead of a
        quorum 5xx (ref toObjectErr mapping errVolumeNotFound ->
        BucketNotFound, cmd/typed-errors.go).

        Reads require a MAJORITY of missing volumes — agreeing with
        bucket_exists and the make/delete-bucket quorum, so a settled
        bucket never reads as both present and gone. Writes map a
        write-quorum of VolumeNotFound to NoSuchBucket (the reference's
        reduceWriteQuorumErrs bar); BELOW that bar a partial
        VolumeNotFound is ambiguous — freshly wiped disks awaiting heal
        (bucket exists; the quorum error is retryable) vs a racing
        delete_bucket mid-flight (will finish or roll back within
        moments) — so the write path lets the race settle and takes the
        majority vote before deciding."""
        vnf = sum(1 for e in errs if isinstance(e, serr.VolumeNotFound))
        if vnf == 0:
            return
        n = len(self.disks)
        if not for_write:
            if vnf >= n // 2 + 1:
                raise BucketNotFound(bucket)
            return
        if wq is None:
            wq = write_quorum(self.k, self.m)
        ok = sum(1 for e in errs if e is None)
        if ok >= wq:
            # The write LANDED despite stray VolumeNotFound disks (e.g.
            # a wiped replacement awaiting heal): no settle, no stall —
            # the per-write cost of this helper must be zero in the
            # steady degraded state.
            return
        if vnf >= wq:
            raise BucketNotFound(bucket)
        time.sleep(0.05)
        # Decisive only on a RESPONDING majority saying the volume is
        # absent; zero responders is an outage (retryable 5xx), not 404.
        _, st = parallel_map(
            [lambda d=d: d.stat_volume(bucket) for d in self.disks])
        absent = sum(1 for e in st if isinstance(e, serr.VolumeNotFound))
        if absent >= n // 2 + 1:
            raise BucketNotFound(bucket)

    def guard_commit_bucket_gone(self, errs, bucket: str,
                                 object_name: str, version_id: str, *,
                                 wq: int | None = None) -> None:
        """Commit-path wrapper over _raise_if_bucket_gone: when the
        bucket vanished mid-commit, UNDO the copies that landed (disks
        where errs[i] is None) before re-raising — 1-copy danglers
        would otherwise block the racing delete_bucket with a phantom
        "not empty". Shared by put_object, the delete-marker write and
        complete_multipart_upload."""
        try:
            self._raise_if_bucket_gone(errs, bucket, for_write=True,
                                       wq=wq)
        except BucketNotFound:
            undo_fi = FileInfo(volume=bucket, name=object_name,
                               version_id=version_id)
            parallel_map(
                [lambda d=d: d.delete_version(bucket, object_name,
                                              undo_fi)
                 for d, e in zip(self.disks, errs) if e is None])
            raise

    def _check_bucket(self, bucket: str) -> None:
        self._check_not_reserved(bucket)
        if not self.bucket_exists(bucket):
            raise BucketNotFound(bucket)

    # ------------------------------------------------------------------
    # write path

    def codec_for(self, k: int, m: int, block_size: int | None = None,
                  algorithm: str | None = None):
        """Codec for a per-object geometry (storage class may override
        the set default parity, ref GetParityForSC,
        cmd/config/storageclass/storage-class.go; old objects may also
        carry a different block size) and erasure algorithm (the REGEN
        storage class stamps pm-mbr-rbt in xl.meta; absent/rs means
        plain RS, so every pre-REGEN object resolves unchanged)."""
        algo = algorithm or ERASURE_ALGORITHM
        bs = self.block_size if block_size is None else block_size
        if (k, m, bs, algo) == (self.k, self.m, self.block_size,
                                ERASURE_ALGORITHM):
            return self.codec
        key = (k, m, bs, algo)
        codec = self._codec_cache.get(key)
        if codec is None:
            from .codec import codec_for_algorithm
            codec = codec_for_algorithm(
                algo, k, m, bs,
                # Per-object geometries still dispatch from THIS set:
                # they share its home device.
                affinity=getattr(self, "device_affinity", None))
            self._codec_cache[key] = codec
        return codec

    def put_object(self, bucket: str, object_name: str, data,
                   metadata: dict | None = None,
                   versioned: bool = False,
                   parity_shards: int | None = None,
                   algorithm: str | None = None) -> ObjectInfo:
        """Streaming block pipeline (ref Erasure.Encode block loop,
        cmd/erasure-encode.go:73-109 + parallelWriter :36-70): `data` is
        bytes OR a chunk reader/iterable. The stream is consumed in
        multiples of block_size, each batch erasure-encoded in one
        (TPU-batched) dispatch, bitrot-wrapped, and appended to the k+m
        staged shard files under write-quorum tolerance — peak memory is
        O(batch), never O(object)."""
        from ..utils import streams
        self._check_bucket(bucket)
        n = len(self.disks)
        m = self.m if parity_shards is None else parity_shards
        if not (0 < m <= n // 2):
            raise ValueError(f"parity {m} out of range for {n} disks")
        k = n - m
        codec = self.codec_for(k, m, algorithm=algorithm)
        distribution = hash_order(f"{bucket}/{object_name}", n)
        wq = write_quorum(k, m)
        reader = streams.ensure_reader(data)

        version_id = new_version_id() if versioned else ""
        data_dir = new_data_dir()
        tmp_id = str(uuid.uuid4())
        tmp_path = f"{TMP_PATH}/{tmp_id}"
        shard_rel = f"{tmp_path}/{data_dir}/part.1"
        mod_time = now()

        # Reuse the hash a verifying reader already computes over the
        # consumed stream; otherwise tee our own (etag = md5 of stored
        # bytes).
        md5 = None if hasattr(reader, "etag") else hashlib.md5()
        total = 0
        # Failed writers are nilled out and skipped for the rest of the
        # stream; quorum is re-checked per batch (ref parallelWriter
        # degradation + reduceWriteQuorumErrs, cmd/erasure-encode.go:56-70).
        alive = [True] * n
        disk_errs: list = [None] * n
        # Quarantined drives are skipped up front (degraded write):
        # their shards ride the same dead-disk path below — tmp
        # cleanup + MRF heal requeue — so the object converges back to
        # full redundancy once the drive is reinstated.
        self._quarantine_skip(alive, disk_errs, wq)

        # Recovery breadcrumb: the first shard append per disk drops
        # intent.json into the staging dir (riding the existing write
        # fan-out — no extra parallel round on the PUT hot path; the
        # 6-thunk parallel_map scheduler cost alone measured 3-20ms on
        # this box). Best-effort: a disk that can't take the intent
        # will fail its shard append right after and ride the normal
        # dead-disk path.
        intent_blob = _stage_intent_blob(bucket, object_name,
                                         version_id, data_dir)
        intent_rel = f"{tmp_path}/{INTENT_FILE}"
        wrote_intent = [False] * n

        def _intent_first(i: int) -> None:
            if wrote_intent[i]:
                return
            wrote_intent[i] = True
            try:
                self.disks[i].append_file(MINIO_META_BUCKET,
                                          intent_rel, intent_blob)
            except Exception:
                pass

        def append_one(i: int, payload: bytes, parent=None):
            _intent_first(i)
            if parent is None:  # untraced fast path
                self.disks[i].append_file(MINIO_META_BUCKET, shard_rel,
                                          payload)
                return
            # Explicit parent: parallel_map workers don't inherit the
            # request thread's contextvar; entering this span seeds it
            # so nested disk/RPC spans stitch under the right write.
            from ..obs.span import TRACER
            with TRACER.span("ec.shard_write", parent=parent, disk=i,
                             endpoint=str(self.disks[i]),
                             bytes=len(payload)):
                self.disks[i].append_file(MINIO_META_BUCKET, shard_rel,
                                          payload)

        def cleanup_tmp(indices):
            parallel_map([
                lambda i=i: self.disks[i].delete(
                    MINIO_META_BUCKET, tmp_path, recursive=True)
                for i in indices])

        from ..obs.span import TRACER
        from ..utils.phasetimer import PUT as _PUT

        def quorum_msg() -> str:
            causes = "; ".join(
                f"disk{i}: {type(e).__name__}: {e}"
                for i, e in enumerate(disk_errs)
                if e is not None)
            return ("write quorum lost mid-stream "
                    f"({sum(alive)}/{n}, need {wq}): {causes}")

        try:
            # Staging happens OUTSIDE the namespace lock: a slow
            # client-paced stream must not block readers of the key.
            # Only the commit below takes the write lock (ref NSLock
            # placement just before the metadata write + rename,
            # cmd/erasure-object.go:694-700).
            total, _t_enc, _t_wr = self._stream_shard_writes(
                reader, k, m, codec, distribution, append_one,
                alive, disk_errs, wq, quorum_msg, md5)
            # A hash-verifying reader raises here when the declared
            # md5/sha256/size doesn't match what streamed through —
            # the staged shards are discarded, nothing committed
            # (ref pkg/hash/reader.go verification at EOF).
            if hasattr(reader, "verify"):
                reader.verify()
            # Crash window: every shard staged, nothing committed — a
            # death here must leave the old version (or 404) intact
            # and the stages for the boot sweep.
            FAULTS.crash_point(CRASH_PUT_STAGED)

            etag = reader.etag() if md5 is None else md5.hexdigest()
            meta = dict(metadata or {})
            meta["etag"] = etag
            part = ObjectPartInfo(number=1, size=total,
                                  actual_size=total, etag=etag)

            def commit_one(i: int, parent=None):
                if not alive[i]:
                    raise disk_errs[i]
                if parent is not None:
                    from ..obs.span import TRACER as _TR
                    with _TR.span("ec.shard_commit", parent=parent,
                                  disk=i, endpoint=str(self.disks[i])):
                        return _commit_inner(i)
                return _commit_inner(i)

            def _commit_inner(i: int):
                fi = FileInfo(
                    volume=bucket, name=object_name,
                    version_id=version_id,
                    data_dir=data_dir if total > 0 else "",
                    size=total, mod_time=mod_time, metadata=meta,
                    parts=[part],
                    erasure=ErasureInfo(
                        algorithm=algorithm or ERASURE_ALGORITHM,
                        data_blocks=k, parity_blocks=m,
                        block_size=self.block_size,
                        index=distribution[i],
                        distribution=list(distribution),
                        checksums=[{
                            "part": 1,
                            "algorithm": bitrot.DEFAULT_ALGORITHM,
                            "hash": ""}],
                    ),
                )
                try:
                    self.disks[i].rename_data(
                        MINIO_META_BUCKET, tmp_path, fi,
                        bucket, object_name)
                except BaseException:
                    try:
                        self.disks[i].delete(MINIO_META_BUCKET,
                                             tmp_path, recursive=True)
                    except Exception:
                        pass
                    raise
                return fi

            # Exclusive commit: the lock covers only metadata write +
            # rename, not the body transfer.
            _t2 = time.perf_counter()
            with self.ns_lock.write_locked(bucket, object_name):
                with TRACER.span("ec.commit") as _cs:
                    _, errs = parallel_map(
                        [lambda i=i: commit_one(i, _cs)
                         for i in range(n)])
                self.guard_commit_bucket_gone(errs, bucket,
                                              object_name, version_id,
                                              wq=wq)
                reduce_quorum_errs(errs, wq, "put_object")
                # Crash window: quorum-committed, but dead-disk stage
                # cleanup + MRF requeue haven't run — a death here
                # must serve the NEW version on restart, with the boot
                # sweep GC-ing the leftovers and requeueing the heal.
                FAULTS.crash_point(CRASH_PUT_COMMITTED)
            _PUT.record("engine_commit",
                        (time.perf_counter() - _t2) * 1e3)
            _PUT.record("engine_encode", _t_enc * 1e3)
            _PUT.record("engine_write", _t_wr * 1e3)
        except BaseException:
            # Don't leak staged shards (the reference deletes the
            # tmp prefix on every error path).
            cleanup_tmp(range(n))
            raise
        # Failed disks keep no stage and feed the MRF heal queue
        # (ref addPartial, cmd/erasure-object.go:1082).
        dead = [i for i in range(n) if errs[i] is not None]
        if dead:
            cleanup_tmp(dead)
            self.mrf.add(bucket, object_name)
        self._mark_update(bucket, object_name)
        # Write-through invalidation: drop every cached decoded copy
        # of the old version, locally and (async) on every peer.
        from ..cache.hotcache import HOTCACHE
        HOTCACHE.invalidate(bucket, object_name)
        return ObjectInfo(bucket=bucket, name=object_name, size=total,
                          etag=etag, mod_time=mod_time,
                          version_id=version_id, metadata=meta,
                          parts=[part])

    def _stream_shard_writes(self, reader, k: int, m: int, codec,
                             distribution, append_shard, alive,
                             disk_errs, wq: int, quorum_msg, md5,
                             name: str = "put",
                             ) -> tuple[int, float, float]:
        """The pipelined PUT/part data plane (shared by put_object and
        multipart.put_object_part): consume `reader` in encode batches;
        while batch N's k+m shards fan out to disks, batch N+1 is
        already being read from the client and erasure-encoded on the
        pipeline's worker thread (utils/pipeline.py, bounded depth —
        at most depth+1 encoded batches alive). Write quorum is
        re-checked per batch at the join point, exactly as the serial
        loop did. A single-batch stream (object <= put_batch_bytes)
        never starts the worker: small PUTs stay thread-free.

        append_shard(disk_index, payload, parent_span) performs one
        shard append; alive/disk_errs are the caller's per-disk
        degradation state (mutated in place); quorum_msg() renders the
        caller's quorum-loss error text.

        Returns (total_bytes, encode_seconds, write_seconds) — the two
        phase sums overlap under the pipeline, so their total may
        exceed wall time (that ratio is the bench's overlap factor).
        """
        from ..obs.span import TRACER
        from ..utils import streams
        from ..utils.pipeline import Prefetch
        n = k + m
        shard_size = codec.shard_size()
        root = TRACER.current()
        state = {"total": 0, "enc_s": 0.0, "wr_s": 0.0}

        def encode_one(batch: bytes):
            t0 = time.perf_counter()
            with TRACER.span("ec.encode", parent=root,
                             bytes=len(batch)):
                # The etag md5 overlaps the erasure encode on multicore
                # hosts: both walk the same batch, md5 releases the GIL
                # on big buffers, and stream order is preserved because
                # each batch joins before the next submits (~1.7ms off
                # a 1MiB PUT's critical path).
                md5_fut = (submit(md5.update, batch)
                           if md5 is not None and MULTICORE else None)
                if md5 is not None and md5_fut is None:
                    md5.update(batch)
                state["total"] += len(batch)
                full_sm, tails = self._encode_batch_split(batch, k, m,
                                                          codec)
                framed = None
                if full_sm is not None and bitrot._device_hash_ok(
                        bitrot.DEFAULT_ALGORITHM, shard_size,
                        full_sm.nbytes):
                    # Device bitrot stays one coalesced dispatch over
                    # all shards; per-shard hashing in the writer
                    # fan-out would fragment it below the threshold.
                    framed = self._frame_split(full_sm, tails, codec)
                if md5_fut is not None:
                    md5_fut.result()
            state["enc_s"] += time.perf_counter() - t0
            return len(batch), full_sm, tails, framed

        def write_batch(item) -> None:
            nbytes, full_sm, tails, framed = item
            t1 = time.perf_counter()
            live = [i for i in range(n) if alive[i]]
            with TRACER.span("ec.write", bytes=nbytes) as _ws:
                def one(i: int) -> None:
                    j = distribution[i] - 1
                    if framed is not None:
                        payload = framed[j]
                    else:
                        # Host bitrot rides the writer fan-out: the
                        # hash of shard j (GIL-released native kernel)
                        # overlaps the disk writes of the other shards.
                        payload = bitrot.frame_shard(
                            None if full_sm is None else full_sm[j],
                            None if tails is None else tails[j])
                    append_shard(i, payload, _ws)
                _, errs = parallel_map(
                    [lambda i=i: one(i) for i in live])
            state["wr_s"] += time.perf_counter() - t1
            for i, e in zip(live, errs):
                if e is not None:
                    alive[i] = False
                    disk_errs[i] = e
            if sum(alive) < wq:
                raise QuorumError(
                    quorum_msg(),
                    [e for e in disk_errs if e is not None])

        per = streams.batch_size(self.block_size, self.put_batch_bytes)
        first = streams.read_exactly(reader, per)
        if not first:
            return 0, 0.0, 0.0
        # One-byte lookahead: a stream of EXACTLY one full batch must
        # also take the inline path — without it, an 8MiB part would
        # spin up the worker for a single item. The probe blocks no
        # longer than the next batch read would have.
        probe = b"" if len(first) < per else streams.read_exactly(
            reader, 1)
        if len(first) < per or not probe:
            # The whole stream fit in one batch: encode + write inline
            # on the request thread (no worker, no queue — a small PUT
            # must not pay a thread handoff for nothing to overlap).
            write_batch(encode_one(first))
            return state["total"], state["enc_s"], state["wr_s"]
        batches = streams.iter_batches(
            streams.PushbackReader(probe, reader), self.block_size,
            self.put_batch_bytes)

        def produce():
            yield encode_one(first)
            for batch in batches:
                yield encode_one(batch)

        with Prefetch(produce(), depth=self.pipeline_depth,
                      name=name, span=root) as pf:
            for item in pf:
                write_batch(item)
        return state["total"], state["enc_s"], state["wr_s"]

    def _encode_batch_split(self, data: bytes, k: int, m: int, codec,
                            ) -> tuple:
        """RS-encode one batch WITHOUT bitrot framing: returns
        (full_sm, tails) where full_sm is a shard-major
        (k+m, n_blocks, shard_size) uint8 array of the full blocks'
        shards (None when the batch is shorter than one block) and
        tails the k+m per-shard byte strings of the final short block
        (None when the batch is block-aligned). Framing happens either
        centrally (_frame_split — the device-hash path) or per shard
        in the writer fan-out (bitrot.frame_shard)."""
        n = k + m
        if len(data) == 0:
            return None, None
        from ..obs.span import TRACER
        if getattr(codec, "is_regen", False):
            # REGEN encode: no k-way pre-split — the product-matrix
            # code consumes raw block bytes (pack_blocks_batch stripes
            # them B-wide) and emits n equal non-systematic chunks.
            # Same (full_sm, tails) contract, so framing and the
            # writer fan-out are untouched.
            with TRACER.span("kernel.regen_encode", bytes=len(data),
                             k=k, m=m):
                full_sm = None
                nfull = len(data) // self.block_size
                if nfull:
                    full = np.frombuffer(
                        data[:nfull * self.block_size], dtype=np.uint8,
                    ).reshape(nfull, self.block_size)
                    full_sm = codec.encode_blocks_batch_bytes(full)
                rest = data[nfull * self.block_size:]
                tails = None
                if rest:
                    shards = codec.encode_data(rest)
                    tails = [shards[j].tobytes()
                             for j in range(codec.total_shards)]
                return full_sm, tails
        with TRACER.span("kernel.rs_encode", bytes=len(data),
                         k=k, m=m):
            shard_size = codec.shard_size()
            full_sm = None
            nfull = len(data) // self.block_size
            if nfull:
                # Each block is zero-padded to k*shard_size (split
                # padding semantics, ref dependency Split of
                # cmd/erasure-coding.go:74).
                full = np.frombuffer(
                    data[:nfull * self.block_size], dtype=np.uint8,
                ).reshape(nfull, self.block_size)
                if self.block_size != k * shard_size:
                    padded = np.zeros((nfull, k * shard_size),
                                      dtype=np.uint8)
                    padded[:, :self.block_size] = full
                    full = padded
                full = full.reshape(nfull, k, shard_size)
                # Shard-major framing: each full block is exactly one
                # bitrot sub-block, so (n_blocks, S) rows frame
                # directly — no per-shard byte reassembly.
                full_sm = codec.encode_blocks_batch_shardmajor(full)
            rest = data[nfull * self.block_size:]
            tails = None
            if rest:
                shards = codec.encode_data(rest)
                tails = [shards[j].tobytes() for j in range(n)]
            return full_sm, tails

    def _frame_split(self, full_sm, tails, codec) -> list:
        """Bitrot-frame a split-encoded batch into per-shard chunks —
        byte-identical to the pre-split _encode_batch output (golden
        tests): consecutive batches concatenate into a valid
        streaming-bitrot shard file (ref cmd/bitrot-streaming.go:46)."""
        shard_size = codec.shard_size()
        full_frames = None
        if full_sm is not None:
            full_frames = bitrot.encode_stream_arrays(list(full_sm))
        if tails is None:
            return full_frames
        tail_frames = bitrot.encode_streams(tails, shard_size)
        if full_frames is None:
            return tail_frames
        return [np.concatenate([ff, np.frombuffer(tf, np.uint8)])
                for ff, tf in zip(full_frames, tail_frames)]

    def _encode_batch(self, data: bytes, k: int | None = None,
                      m: int | None = None,
                      codec=None) -> list[bytes]:
        """Encode one batch (a multiple of block_size, except a final
        short tail) into k+m bitrot-wrapped shard chunks: one batched
        device dispatch for the full blocks (ref EncodeData per block,
        cmd/erasure-encode.go:80 — here many blocks per dispatch), host
        encode for the tail. Chunk framing aligns with shard_size
        sub-blocks, so consecutive batches concatenate into a valid
        streaming-bitrot shard file (ref cmd/bitrot-streaming.go:46)."""
        k = self.k if k is None else k
        m = self.m if m is None else m
        codec = self.codec if codec is None else codec
        n = k + m
        if len(data) == 0:
            return [b""] * n
        # The kernel child span (RS math + any coalescer window wait)
        # opens inside _encode_batch_split; which device actually ran
        # it is in the kernel counters (obs/kernel_stats.py).
        full_sm, tails = self._encode_batch_split(data, k, m, codec)
        return self._frame_split(full_sm, tails, codec)

    def _encode_object(self, data: bytes, k: int | None = None,
                       m: int | None = None,
                       codec=None) -> list[bytes]:
        """Whole-object encode -> k+m bitrot-wrapped shard streams
        (multipart parts and heal re-encode, which already hold the
        part in memory)."""
        return self._encode_batch(data, k, m, codec)

    # ------------------------------------------------------------------
    # read path

    def _read_file_infos(self, bucket: str, object_name: str,
                         version_id: str = "",
                         ) -> tuple[list[FileInfo | None], list]:
        # Quarantined drives serve NO data-plane reads — the metadata
        # fan-out included (parallel_map joins every thunk, so one
        # quarantined-and-stalling drive would drag every stat/GET).
        # They answer as pre-failed; the quorum math treats that like
        # any other down disk.
        from ..obs.drivemon import DRIVEMON

        def one(i: int):
            if DRIVEMON.is_quarantined(self.endpoints[i]):
                raise serr.DriveQuarantined(self.endpoints[i])
            return self.disks[i].read_version(bucket, object_name,
                                              version_id)

        results, errs = parallel_map(
            [lambda i=i: one(i) for i in range(len(self.disks))])
        fis = [r if e is None else None for r, e in zip(results, errs)]
        # Availability over hygiene: when the healthy drives alone
        # can't produce k readable shards (quarantine plus a real
        # failure), the quarantined drives ARE the remaining copies —
        # probe them after all, serially (they may stall; never let
        # them drag the healthy fan-out's join). Without this second
        # pass the shard map never includes a quarantined drive and
        # _read_order's last-resort re-entry has nothing to extend
        # with — m+1 quarantined drives would fail every GET in the
        # set despite byte-exact data. A healthy disk answering a
        # namespace miss is DEFINITIVE (the object simply isn't
        # there) — without that guard every 404-path request would
        # block on a possibly-hung quarantined drive, the exact stall
        # the pre-fail above exists to avoid (same policy as
        # iam.ConfigStore).
        definitive = (serr.FileNotFound, serr.VersionNotFound,
                      serr.VolumeNotFound)
        if (sum(f is not None for f in fis) < self.k
                and not any(isinstance(e, definitive) for e in errs)):
            for i, e in enumerate(errs):
                if not isinstance(e, serr.DriveQuarantined):
                    continue
                try:
                    fis[i] = self.disks[i].read_version(
                        bucket, object_name, version_id)
                    errs[i] = None
                except Exception as e2:  # keep the quorum math exact
                    errs[i] = e2
        return fis, errs

    def _quorum_file_info(self, bucket: str, object_name: str,
                          version_id: str = "", *,
                          reduce_notfound: bool = True,
                          ) -> tuple[FileInfo, list[FileInfo | None]]:
        """FileInfo agreed by >= read-quorum disks (ref
        findFileInfoInQuorum, cmd/erasure-metadata.go).

        reduce_notfound: serving paths map a not-found majority to
        ObjectNotFound (ref reduceReadQuorumErrs + errFileNotFound,
        cmd/erasure-object.go:388-391); the HEALER passes False so a
        below-quorum straggler copy surfaces as QuorumError and gets
        classified dangling instead of skipped."""
        fis, errs = self._read_file_infos(bucket, object_name, version_id)
        nf = sum(1 for e in errs if isinstance(
            e, (serr.FileNotFound, serr.VersionNotFound)))
        if all(f is None for f in fis):
            if nf < read_quorum(self.k):
                # Disks failed with REAL errors (IO, unmounted) and
                # fewer than a read quorum said not-found: a backend
                # outage is unavailability, not a 404 — unless the
                # BUCKET itself is gone (racing delete-bucket).
                self._raise_if_bucket_gone(errs, bucket)
                raise QuorumError(
                    f"all disks failed reading {bucket}/{object_name}",
                    list(errs))
            if any(isinstance(e, serr.VersionNotFound) for e in errs):
                raise ObjectNotFound(f"{bucket}/{object_name}@{version_id}")
            raise ObjectNotFound(f"{bucket}/{object_name}")
        groups: dict[tuple, list[int]] = {}
        for i, fi in enumerate(fis):
            if fi is not None:
                groups.setdefault(fi.quorum_key(), []).append(i)
        key, members = max(groups.items(), key=lambda kv: len(kv[1]))
        fi = fis[members[0]]
        rq = read_quorum(fi.erasure.data_blocks or self.k)
        if len(members) < rq:
            # Reduce read errors before quorum-failing (ref
            # reduceReadQuorumErrs + the errFileNotFound mapping,
            # cmd/erasure-object.go:388-391): when enough disks agree
            # the key is ABSENT — a lock-free stat racing a delete or a
            # commit — that's not-found (404), not a 5xx. The healer
            # opts out so straggler copies classify dangling.
            if reduce_notfound and nf >= rq:
                raise ObjectNotFound(f"{bucket}/{object_name}")
            self._raise_if_bucket_gone(errs, bucket)
            raise QuorumError(
                f"metadata quorum not met for {bucket}/{object_name} "
                f"({len(members)}/{len(self.disks)}, need {rq})",
                list(errs))
        # Null out disks outside the quorum group.
        agreed = [fis[i] if i in members else None
                  for i in range(len(fis))]
        return fi, agreed

    def _uncached_info(self, bucket: str, object_name: str,
                       ) -> ObjectInfo:
        """Metadata-quorum ObjectInfo bypassing the hot-object cache —
        the cache's ETag-revalidation oracle (calling the public stat
        would recurse straight back into the cache)."""
        with self.ns_lock.read_locked(bucket, object_name):
            fi, _ = self._quorum_file_info(bucket, object_name)
        if fi.deleted:
            raise ObjectNotFound(f"{bucket}/{object_name}")
        return ObjectInfo.from_file_info(fi)

    def get_object_info(self, bucket: str, object_name: str,
                        version_id: str = "") -> ObjectInfo:
        from ..cache.hotcache import HOTCACHE
        if HOTCACHE.enabled and not version_id:
            # Memory-tier stat: a hot GET's HEAD/stat half also skips
            # the metadata fan-out (latest-only; versioned stats take
            # the quorum path below).
            info = HOTCACHE.lookup_info(
                self.cache_ns, bucket, object_name,
                lambda: self._uncached_info(bucket, object_name))
            if info is not None:
                return info
        self._check_bucket(bucket)
        # Same read lock as the data path: a stat racing a concurrent
        # commit/delete must see before-or-after state, never the
        # mid-parallel-write mixture (ref getObjectInfo taking the
        # shared ns lock, cmd/erasure-object.go:383).
        with self.ns_lock.read_locked(bucket, object_name):
            fi, _ = self._quorum_file_info(bucket, object_name,
                                           version_id)
        if fi.deleted:
            if version_id:
                raise MethodNotAllowed(f"{bucket}/{object_name}")
            raise ObjectNotFound(f"{bucket}/{object_name}")
        return ObjectInfo.from_file_info(fi)

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1, version_id: str = "",
                   ) -> tuple[bytes, ObjectInfo]:
        info, stream = self.get_object_stream(bucket, object_name,
                                              offset, length, version_id)
        return b"".join(stream), info

    def get_object_stream(self, bucket: str, object_name: str,
                          offset: int = 0, length: int = -1,
                          version_id: str = "",
                          ) -> tuple[ObjectInfo, "object"]:
        """(info, chunk iterator) — the streaming GET: blocks are
        fetched, bitrot-verified, and reconstructed group-by-group, so
        peak memory is O(group), never O(range) (ref blockwise decode,
        cmd/erasure-decode.go:248-263). The read lock is held for the
        stream's lifetime, like the reference holds its read lock across
        the response write (cmd/erasure-object.go:134); exhaust or
        close() the iterator to release it.

        The hot-object cache is consulted twice (cache/hotcache.py):
        a tier hit up front serves decoded bytes with NO disk I/O at
        all; past the metadata quorum read, a concurrent fill of the
        same key+etag is joined (coalesced wait — N cold GETs of one
        hot key perform exactly one shard fan-out + decode), and a
        full-object read registers itself as the single-flight fill."""
        from ..cache.hotcache import HOTCACHE
        if HOTCACHE.enabled and not version_id:
            served = HOTCACHE.serve(
                self.cache_ns, bucket, object_name, offset, length,
                lambda: self._uncached_info(bucket, object_name))
            if served is not None:
                return served
        self._check_bucket(bucket)
        # The read lock covers metadata + data so a concurrent overwrite
        # cannot swap the data dir between the two reads.
        ctx = self.ns_lock.read_locked(bucket, object_name)
        ctx.__enter__()
        try:
            fi, agreed = self._quorum_file_info(bucket, object_name,
                                                version_id)
            if fi.deleted:
                if version_id:
                    raise MethodNotAllowed(f"{bucket}/{object_name}")
                raise ObjectNotFound(f"{bucket}/{object_name}")
            info = ObjectInfo.from_file_info(fi)
            if offset < 0 or offset > fi.size:
                raise ValueError("invalid range")
            if length < 0:
                length = fi.size - offset
            if offset + length > fi.size:
                raise ValueError("invalid range")
            if length == 0 or fi.size == 0:
                ctx.__exit__(None, None, None)
                return info, iter(())
            if HOTCACHE.enabled and not version_id:
                cached = self._cache_fill_or_join(
                    ctx, fi, agreed, info, bucket, object_name,
                    offset, length)
                if cached is not None:
                    return cached
            gen = self._iter_ranges(fi, agreed, offset, length)
            return info, _LockedStream(ctx, gen)
        except BaseException:
            ctx.__exit__(None, None, None)
            raise

    def _cache_fill_or_join(self, ctx, fi, agreed, info, bucket: str,
                            object_name: str, offset: int, length: int):
        """Single-flight integration past the metadata read: join an
        in-flight fill of this key+etag (releasing our read lock — the
        filler's lock covers the data), or register as the fill when
        this is a cacheable full-object read. Returns (info, stream)
        or None to proceed with a plain erasure read."""
        from ..cache.hotcache import HOTCACHE

        def resume(pos: int, _off=offset, _len=length):
            # Waiter fallback when the fill dies under it: re-read the
            # remainder ourselves — but never stitch bytes of a
            # DIFFERENT object version onto what the waiter already
            # streamed.
            info2, stream = self.get_object_stream(
                bucket, object_name, offset=_off + pos,
                length=_len - pos)
            if info2.etag != fi.metadata.get("etag", ""):
                try:
                    stream.close()
                except Exception:
                    pass
                raise QuorumError(
                    f"{bucket}/{object_name} changed while a coalesced "
                    "read was streaming from a failed fill", [])
            return stream

        waiter = HOTCACHE.join_fill(
            self.cache_ns, bucket, object_name,
            fi.metadata.get("etag", ""), offset, length, resume)
        if waiter is not None:
            ctx.__exit__(None, None, None)
            return info, waiter
        if offset != 0 or length != fi.size:
            return None
        fill = HOTCACHE.begin_fill(self.cache_ns, bucket, object_name,
                                   info)
        if fill is None:
            return None
        handed = False
        try:
            rdr = fill.reader(
                self._iter_ranges(fi, agreed, 0, fi.size))
            handed = True
            return info, _LockedStream(ctx, rdr)
        finally:
            if not handed:
                fill.abort(RuntimeError("fill setup failed"))

    def _quarantine_skip(self, alive: list, disk_errs: list,
                         wq: int) -> list[int]:
        """Degraded write: pre-mark quarantined drives dead for a write
        fan-out, so their shards fall to the MRF heal queue exactly
        like a failed write would — but only while enough healthy
        drives remain for write quorum. With quorum at stake,
        availability wins and the quarantined drives are attempted
        anyway. Returns the skipped disk indices."""
        from ..obs.drivemon import DRIVEMON
        q = [i for i in range(len(self.disks))
             if alive[i] and DRIVEMON.is_quarantined(self.endpoints[i])]
        if not q or sum(alive) - len(q) < wq:
            return []
        for i in q:
            alive[i] = False
            disk_errs[i] = serr.DriveQuarantined(
                f"{self.endpoints[i]}: write skipped (quarantined)")
        return q

    def _read_order(self, by_shard: list[int | None], k: int,
                    m: int) -> list[int]:
        """Health-ranked shard read order: pick the k healthiest of
        k+m. Sort key is (health state, parity flag, read EWMA) — an
        OK data shard beats an OK parity shard (reading parity forces
        a reconstruct), and ANY healthy shard beats a suspect one (a
        reconstruct is cheaper than waiting on a dragging drive; the
        Mojette any-k-of-n argument, arXiv:1504.07038). Quarantined
        drives serve no data-plane reads at all — they re-enter only
        if exclusion would leave fewer than k readable shards
        (availability over hygiene)."""
        from ..obs.drivemon import DRIVEMON, OK, SUSPECT
        ranked: list[tuple] = []
        quarantined: list[int] = []
        for j, pos in enumerate(by_shard):
            if pos is None:
                continue
            ep = self.endpoints[pos]
            if DRIVEMON.is_quarantined(ep):
                quarantined.append(j)
                continue
            state = DRIVEMON.state_of(ep)
            srank = 0 if state == OK else (1 if state == SUSPECT else 2)
            ewma = DRIVEMON.ewma_for(ep).get("read", 0.0)
            ranked.append((srank, 0 if j < k else 1, ewma, j))
        ranked.sort()
        order = [t[3] for t in ranked]
        if len(order) < k:
            order.extend(quarantined)
        return order

    def _hedged_fetch(self, primary: list[int], spares: list[int],
                      fetch, win_off: int, n_cov: int, windows: dict,
                      k: int, parent_span) -> None:
        """Fan the k primary shard reads out, hedging stragglers: when
        the group hasn't assembled k windows within the adaptive
        budget (hedge_budget), backup reads of spare shards fire on
        the BACKGROUND QoS lane — they defer to foreground kernel
        work, so hedges can never amplify an overload. First response
        wins; straggler futures are cancelled if unstarted, otherwise
        their late results are simply discarded (the group only ever
        consumes k verified windows)."""
        from concurrent.futures import FIRST_COMPLETED
        from concurrent.futures import wait as _fwait
        from ..obs.metrics2 import METRICS2
        from ..qos.scheduler import BACKGROUND, lane_scope
        budget_s = self.hedge_budget.budget()
        METRICS2.set_gauge("minio_tpu_v2_hedge_budget_ms", None,
                           round(budget_s * 1e3, 3))
        pending = {submit(lambda j=j: fetch(j, win_off, n_cov, windows))
                   for j in primary}
        hedge_futs: dict = {}
        deadline = time.monotonic() + budget_s
        while pending:
            if len(windows) >= k:
                break
            timeout = (None if hedge_futs else
                       max(0.0, deadline - time.monotonic()))
            _done, pending = _fwait(pending, timeout=timeout,
                                    return_when=FIRST_COMPLETED)
            if (not hedge_futs and pending and spares
                    and len(windows) < k
                    and time.monotonic() >= deadline):
                need = min(len(pending), len(spares),
                           k - len(windows))
                fired = spares[:need]
                for j in fired:
                    def hedge(j=j):
                        with lane_scope(BACKGROUND):
                            return fetch(j, win_off, n_cov, windows)
                    hedge_futs[submit(hedge)] = j
                    METRICS2.inc("minio_tpu_v2_hedged_reads_total",
                                 {"result": "fired"})
                if parent_span is not None:
                    parent_span.add_event(
                        "ec.hedge", shards=list(fired),
                        budget_ms=round(budget_s * 1e3, 1))
                pending |= set(hedge_futs)
        for f in pending:
            f.cancel()
        if hedge_futs:
            # Outcome accounting for the bench's wasted-read fraction:
            # a hedge "won" when it filled a slot a straggling primary
            # never did; completed hedges beyond that were wasted I/O.
            missing = sum(1 for j in primary if j not in windows)
            won = 0
            for f, j in hedge_futs.items():
                if not f.done() or f.cancelled():
                    continue
                if j in windows and won < missing:
                    won += 1
                    METRICS2.inc("minio_tpu_v2_hedged_reads_total",
                                 {"result": "won"})
                else:
                    METRICS2.inc("minio_tpu_v2_hedged_reads_total",
                                 {"result": "wasted"})

    def _shard_readers(self, fi: FileInfo,
                       agreed: list[FileInfo | None]) -> list[int | None]:
        """Map shard index j (0-based) -> disk position, using each disk's
        own erasure.index from its metadata."""
        n = self.k + self.m
        by_shard: list[int | None] = [None] * n
        for i, f in enumerate(agreed):
            if f is not None and 1 <= f.erasure.index <= n:
                by_shard[f.erasure.index - 1] = i
        return by_shard

    def _iter_ranges(self, fi: FileInfo,
                     agreed: list[FileInfo | None],
                     offset: int, length: int):
        """Walk the object's parts, streaming the covered range from
        each (multipart objects carry one erasure-coded shard file per
        part, ref cmd/erasure-object.go:240 per-part loop)."""
        parts = fi.parts or [ObjectPartInfo(number=1, size=fi.size,
                                            actual_size=fi.size)]
        failed: set[int] = set()
        pos = 0
        for p in parts:
            part_start, part_end = pos, pos + p.size
            pos = part_end
            if part_end <= offset or part_start >= offset + length:
                continue
            local_off = max(0, offset - part_start)
            local_len = min(part_end, offset + length) - (
                part_start + local_off)
            yield from self._iter_part_range(fi, agreed, p.number,
                                             p.size, local_off,
                                             local_len, failed)

    def _read_and_decode(self, fi: FileInfo,
                         agreed: list[FileInfo | None],
                         offset: int, length: int) -> bytes:
        return b"".join(self._iter_ranges(fi, agreed, offset, length))

    def _iter_part_range(self, fi: FileInfo,
                         agreed: list[FileInfo | None],
                         part_number: int, part_size: int,
                         offset: int, length: int,
                         failed: set[int]):
        """Yield decoded plaintext of [offset, offset+length) within one
        part, group-by-group: shard windows covering a bounded group of
        blocks are fetched in parallel, verified, and reconstructed, so
        memory stays O(group) for any range (ref the per-block decode
        loop, cmd/erasure-decode.go:248-263)."""
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        shard_size = fi.erasure.shard_size()
        by_shard = self._shard_readers(fi, agreed)
        # Codec geometry AND algorithm come from the object's metadata
        # (they may differ from this engine's default — mixed-class
        # buckets hold RS and REGEN objects side by side).
        codec = self.codec_for(k, m, fi.erasure.block_size,
                               algorithm=fi.erasure.algorithm)
        is_regen = getattr(codec, "is_regen", False)

        # Block coverage of [offset, offset+length).
        start_block = offset // fi.erasure.block_size
        end_block = (offset + length - 1) // fi.erasure.block_size

        # Bitrot algorithm comes from the object's own metadata, not the
        # current default — framing stride depends on it.
        algo = bitrot.DEFAULT_ALGORITHM
        for cs in fi.erasure.checksums:
            if cs.get("part") == part_number:
                algo = cs.get("algorithm", algo)

        # Each full block contributes [hash][shard_size] to the shard
        # stream (ref streamingBitrotReader stream offset math,
        # cmd/bitrot-streaming.go:125). Whole-file (non-streaming)
        # algorithms have no interleaved hashes: stride is bare
        # shard_size and per-frame verify is skipped (their checksum
        # lives in metadata and is checked by verify_file deep scans).
        hsz = bitrot.hash_size(algo) if bitrot.is_streaming(algo) else 0
        stride = hsz + shard_size
        group = max(1, self.read_group_bytes // fi.erasure.block_size)
        # Health-ranked candidate order, computed once per part:
        # healthy data shards first, suspect/faulty drives demoted to
        # last resort, quarantined drives excluded (obs/drivemon.py).
        candidates = self._read_order(by_shard, k, m)

        want_end = offset + length

        from ..obs.span import TRACER
        # Captured ONCE on the consumer's thread: both the pipeline's
        # prefetch worker and parallel_map fetch workers attach their
        # shard-read spans to it (the contextvar doesn't cross threads).
        _read_parent = TRACER.current()

        def fetch(j: int, win_off: int, n_cov: int,
                  windows: dict) -> bool:
            """Fetch shard j's window for one group; False if
            unavailable. Successful read durations feed the hedge
            budget (the healthy-population percentile)."""
            if j in windows:
                return True
            if j in failed or by_shard[j] is None:
                return False
            disk = self.disks[by_shard[j]]
            f = agreed[by_shard[j]]
            rel = f"{fi.name}/{f.data_dir}/part.{part_number}"
            t0 = time.perf_counter()
            try:
                if _read_parent is None:
                    data = disk.read_file(fi.volume, rel, win_off,
                                          n_cov * stride)
                else:
                    with TRACER.span("ec.shard_read",
                                     parent=_read_parent, shard=j,
                                     endpoint=str(disk),
                                     bytes=n_cov * stride):
                        data = disk.read_file(fi.volume, rel, win_off,
                                              n_cov * stride)
            except Exception:
                failed.add(j)
                return False
            self.hedge_budget.observe(time.perf_counter() - t0)
            windows[j] = data
            return True

        def fetch_group(g0: int) -> tuple:
            """Stage 1 (pipeline producer): pull one group's shard
            windows — the k healthiest first (hedged against
            stragglers), then CONCURRENT fallback bursts bounded by
            how many shards are still missing, so a 2-lost read pays
            one extra read RTT instead of two sequential ones (ref
            parallelReader, cmd/erasure-decode.go:104)."""
            g1 = min(g0 + group - 1, end_block)
            n_cov = g1 - g0 + 1
            win_off = g0 * stride
            windows: dict[int, bytes] = {}
            order = [j for j in candidates if j not in failed]
            primary, spares = order[:k], order[k:]
            if self.hedge_enabled and spares and len(primary) == k:
                self._hedged_fetch(primary, spares, fetch, win_off,
                                   n_cov, windows, k, _read_parent)
            else:
                parallel_map(
                    [lambda j=j: fetch(j, win_off, n_cov, windows)
                     for j in primary])
            have = [j for j in candidates if j in windows]
            # Known-dead shards (condemned in an earlier group, or
            # with no mapped disk) would burn the first burst's slots
            # on instant-False fetches — the burst must hold real
            # parity reads.
            rest = [j for j in candidates
                    if j not in windows and j not in failed]
            while len(have) < k and rest:
                burst = rest[:k - len(have)]
                rest = rest[len(burst):]
                oks, _ = parallel_map(
                    [lambda j=j: fetch(j, win_off, n_cov, windows)
                     for j in burst])
                have.extend(j for j, ok in zip(burst, oks) if ok)
            if len(have) < k:
                raise QuorumError(
                    f"read quorum not met: only {len(have)}/{k} "
                    "shards readable", [])
            return g0, g1, n_cov, win_off, windows, have

        def decode_group(item):
            """Stage 2 (consumer): verify, reconstruct, and trim one
            fetched group; yields the plaintext chunks in range order."""
            g0, g1, n_cov, win_off, windows, have = item
            # Pass 1: gather + bitrot-verify every block's chunk in this
            # group (views into the fetched windows, no copies). All
            # frames of all fetched windows verify in ONE batched call —
            # bitrot.verify_frames coalesces equal-length frames into a
            # single device dispatch (the read half of the TPU bitrot
            # path; ref streamingBitrotReader verifies per chunk on the
            # CPU, cmd/bitrot-streaming.go:115).
            metas = []
            for b in range(g0, g1 + 1):
                blk_len = (min(fi.erasure.block_size,
                               part_size - b * fi.erasure.block_size))
                metas.append((b, blk_len, codec.chunk_size(blk_len)))

            frame_ok: dict[tuple[int, int], np.ndarray] = {}
            verified: set[int] = set()

            def verify_window(js: list[int]) -> None:
                """Batch-verify all frames of windows js; populate
                frame_ok, mark bad shards failed + heal-queued."""
                datas, wants, keys = [], [], []
                bad: set[int] = set()
                for j in js:
                    win = windows.get(j)
                    if win is None:
                        continue
                    for bi, (b, _bl, chunk) in enumerate(metas):
                        base = bi * stride
                        if len(win) < base + hsz + chunk:
                            bad.add(j)
                            continue
                        if bitrot.is_streaming(algo):
                            datas.append(np.frombuffer(
                                win, np.uint8, count=chunk,
                                offset=base + hsz))
                            wants.append(bytes(win[base:base + hsz]))
                            keys.append((j, b))
                        else:
                            frame_ok[(j, b)] = np.frombuffer(
                                win, np.uint8, count=chunk, offset=base)
                oks = bitrot.verify_frames(datas, wants, algo) \
                    if datas else []
                for (j, b), okv, raw in zip(keys, oks, datas):
                    if okv:
                        frame_ok[(j, b)] = raw
                    else:
                        bad.add(j)
                for j in js:
                    if j in bad:
                        # Drop the shard's surviving frames too: one
                        # rotten frame distrusts the whole window (the
                        # reference aborts the shard stream likewise).
                        for b, _bl, _c in metas:
                            frame_ok.pop((j, b), None)
                        failed.add(j)
                        windows.pop(j, None)
                        if j in have:
                            have.remove(j)
                        # heal required (ref errHealRequired ->
                        # deepHealObject, cmd/erasure-object.go:324)
                        self.mrf.add(fi.volume, fi.name)
                    elif j in windows:
                        verified.add(j)

            verify_window(list(have))
            # Top up: if corruption dropped us below k shards, pull in
            # spare candidates (parity first-fallback order) until k
            # verified windows exist or candidates run out.
            for j in candidates:
                if len(verified) >= k:
                    break
                if j not in verified and fetch(j, win_off, n_cov,
                                               windows):
                    verify_window([j])

            # (A vectorized group-gather fast path was tried here and
            # REVERTED: numpy's strided (n_cov, k, S) assignment
            # measured ~27% slower than the per-block tobytes+join
            # below on the host — bytes.join over contiguous views is
            # already near-memcpy speed.)
            gathered: list[tuple[int, int, list]] = []
            for b, blk_len, chunk in metas:
                shards: list[np.ndarray | None] = [None] * (k + m)
                good = 0
                for j in sorted(verified):
                    if good >= k:
                        break
                    raw = frame_ok.get((j, b))
                    if raw is not None:
                        shards[j] = raw
                        good += 1
                if good < k:
                    raise QuorumError(
                        f"block {b}: only {good}/{k} shards valid", [])
                gathered.append((b, blk_len, shards))

            if is_regen:
                # REGEN is non-systematic: EVERY read decodes the
                # message stripes from its k verified chunks — one
                # batched dispatch per (mask, stripe-count) group.
                with TRACER.span("kernel.regen_decode",
                                 parent=_read_parent,
                                 blocks=len(gathered)):
                    texts = codec.decode_blocks_batch(
                        [sh for _b, _bl, sh in gathered],
                        [bl for _b, bl, _sh in gathered])
                for (b, blk_len, _sh), block_data in zip(gathered,
                                                         texts):
                    bstart = b * fi.erasure.block_size
                    lo = max(offset, bstart) - bstart
                    hi = min(want_end, bstart + blk_len) - bstart
                    if hi > lo:
                        yield block_data[lo:hi]
                return

            # Pass 2: batch-reconstruct blocks with data loss — blocks
            # of one object share an erasure mask, so the whole group is
            # a single coalesced device dispatch (ops/batching.py).
            need = [i for i, (_, _, sh) in enumerate(gathered)
                    if any(sh[j] is None for j in range(k))]
            if need:
                # Kernel child span: without it a degraded read's
                # reconstruct math hides in root self-time and the
                # slowlog blames client-stream instead of the codec.
                with TRACER.span("kernel.rs_decode",
                                 parent=_read_parent,
                                 blocks=len(need)):
                    decoded = codec.decode_data_blocks_batch(
                        [gathered[i][2] for i in need])
                for i, dec in zip(need, decoded):
                    gathered[i] = (gathered[i][0], gathered[i][1], dec)

            for b, blk_len, shards in gathered:
                block_data = b"".join(
                    shards[j].tobytes() for j in range(k))[:blk_len]
                # Trim to the requested range within this block.
                bstart = b * fi.erasure.block_size
                lo = max(offset, bstart) - bstart
                hi = min(want_end, bstart + blk_len) - bstart
                if hi > lo:
                    yield block_data[lo:hi]

        group_starts = range(start_block, end_block + 1, group)
        if len(group_starts) <= 1:
            # Single group: no read-ahead to do — stay thread-free.
            for g0 in group_starts:
                yield from decode_group(fetch_group(g0))
            return

        # Read-ahead pipeline: group g+1's shard windows are fetched on
        # the worker while group g verifies, reconstructs, and yields to
        # the client (utils/pipeline.py; bounded depth keeps memory at
        # O(depth × group)). The shared `failed` set stays coherent: a
        # shard condemned by verification in group g is skipped by every
        # LATER fetch, and a window already in flight for it still
        # passes through the same verify pass before use. Abandoning the
        # stream (GeneratorExit at a yield) closes the pipeline, which
        # stops and joins the worker.
        from ..utils.pipeline import Prefetch

        def produce():
            for g0 in group_starts:
                yield fetch_group(g0)

        with Prefetch(produce(), depth=self.pipeline_depth, name="get",
                      span=_read_parent) as pf:
            for item in pf:
                yield from decode_group(item)

    # ------------------------------------------------------------------
    # delete / list

    def delete_object(self, bucket: str, object_name: str,
                      version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        """Delete semantics (ref DeleteObject, cmd/erasure-object.go):
        - versioned bucket + no explicit versionId -> write a delete
          marker as the new latest version (nothing is erased);
        - explicit versionId (or unversioned bucket) -> permanently
          remove that version (latest null version when unversioned).
        Returns the deleted-object descriptor (marker id when one was
        written)."""
        self._check_bucket(bucket)
        if versioned and version_id == "":
            marker = FileInfo(
                volume=bucket, name=object_name,
                version_id=new_version_id(), deleted=True,
                mod_time=now())
            with self.ns_lock.write_locked(bucket, object_name):
                _, errs = parallel_map(
                    [lambda d=d: d.write_metadata(bucket, object_name,
                                                  marker)
                     for d in self.disks])
                self.guard_commit_bucket_gone(errs, bucket,
                                              object_name,
                                              marker.version_id)
                reduce_quorum_errs(errs, write_quorum(self.k, self.m),
                                   "delete_object(marker)")
            self._mark_update(bucket, object_name)
            from ..cache.hotcache import HOTCACHE
            HOTCACHE.invalidate(bucket, object_name)
            return ObjectInfo(bucket=bucket, name=object_name,
                              version_id=marker.version_id,
                              delete_marker=True,
                              mod_time=marker.mod_time)
        fi = FileInfo(volume=bucket, name=object_name,
                      version_id=version_id)
        was_marker = False
        with self.ns_lock.write_locked(bucket, object_name):
            if version_id:
                for d in self.disks:
                    try:
                        was_marker = d.read_version(
                            bucket, object_name, version_id).deleted
                        break
                    except serr.StorageError:
                        continue
            _, errs = parallel_map(
                [lambda d=d: d.delete_version(bucket, object_name, fi)
                 for d in self.disks])
        not_found = sum(1 for e in errs if isinstance(
            e, (serr.FileNotFound, serr.VersionNotFound)))
        if not_found == len(self.disks):
            raise ObjectNotFound(f"{bucket}/{object_name}")
        # A missing key counts as success for a DELETE (idempotent), so
        # fold it to None BEFORE the bucket-gone check — a degraded set
        # (one wiped disk) deleting a nonexistent key must not pay the
        # helper's settle path. VolumeNotFound likewise: a disk without
        # the volume trivially holds no copy.
        eff = [None if isinstance(e, (serr.FileNotFound,
                                      serr.VersionNotFound)) else e
              for e in errs]
        self._raise_if_bucket_gone(eff, bucket, for_write=True)
        reduce_quorum_errs(
            [None if isinstance(e, serr.VolumeNotFound) else e
             for e in eff],
            write_quorum(self.k, self.m), "delete_object")
        self._mark_update(bucket, object_name)
        from ..cache.hotcache import HOTCACHE
        HOTCACHE.invalidate(bucket, object_name)
        return ObjectInfo(bucket=bucket, name=object_name,
                          version_id=version_id,
                          delete_marker=was_marker)

    def object_exists(self, bucket: str, object_name: str) -> bool:
        """True when ANY version (object or delete marker) of the key
        exists on any disk — the placement probe that, unlike
        get_object_info, is not blinded by a delete marker being the
        latest version."""
        self._check_not_reserved(bucket)
        results, _ = parallel_map(
            [lambda d=d: d.read_versions(bucket, object_name)
             for d in self.disks])
        return any(r for r in results
                   if r is not None and not isinstance(r, BaseException))

    def put_object_tags(self, bucket: str, object_name: str, tags: str,
                        version_id: str = "") -> None:
        """Replace the object's tag set in-place in xl.meta (ref
        PutObjectTags, cmd/erasure-object.go — a metadata-only update;
        "" clears)."""
        self.update_object_metadata(bucket, object_name,
                                    {"x-amz-tagging": tags or None},
                                    version_id)

    def update_object_metadata(self, bucket: str, object_name: str,
                               updates: dict, version_id: str = "") -> None:
        """Metadata-only in-place xl.meta update under write quorum (a
        None value deletes the key). Each disk rewrites ITS OWN FileInfo
        so per-disk erasure indices stay intact (ref the updateObjectMeta
        pattern shared by PutObjectTags and replication-status writes,
        cmd/erasure-object.go)."""
        self._check_bucket(bucket)
        with self.ns_lock.write_locked(bucket, object_name):
            fi, agreed = self._quorum_file_info(bucket, object_name,
                                                version_id)
            if fi.deleted:
                if version_id:
                    raise MethodNotAllowed(f"{bucket}/{object_name}")
                raise ObjectNotFound(f"{bucket}/{object_name}")

            def update_one(i: int):
                own = agreed[i]
                if own is None:
                    return  # out-of-quorum disk; healing repairs it
                for k, v in updates.items():
                    if v is None:
                        own.metadata.pop(k, None)
                    else:
                        own.metadata[k] = v
                self.disks[i].write_metadata(bucket, object_name, own)

            _, errs = parallel_map(
                [lambda i=i: update_one(i)
                 for i in range(len(self.disks))])
            self._raise_if_bucket_gone(errs, bucket, for_write=True)
            reduce_quorum_errs(errs, write_quorum(self.k, self.m),
                               "update_object_metadata")
        self._mark_update(bucket, object_name)
        # Metadata (tags, replication status) lives in the cached
        # ObjectInfo too: drop the entry.
        from ..cache.hotcache import HOTCACHE
        HOTCACHE.invalidate(bucket, object_name)

    def walk_object_names(self, bucket: str) -> list[str]:
        """Union-merge directory walk across disks: every object name
        present on ANY disk (partial writes within quorum still list)."""
        names: set[str] = set()

        def walk(disk: StorageAPI, path: str) -> None:
            try:
                entries = disk.list_dir(bucket, path)
            except serr.StorageError:
                return
            is_object = "xl.meta" in entries
            if is_object:
                names.add(path)
            for e in entries:
                if not e.endswith("/"):
                    continue
                # Skip an object's data dirs (uuid dirs holding part files)
                # but keep descending into real sub-prefixes: an object
                # 'a' must not hide objects under 'a/'.
                if is_object and _looks_like_data_dir(e.rstrip("/")):
                    continue
                walk(disk, f"{path}{e}" if path else e)

        for disk in self.disks:
            try:
                base_entries = disk.list_dir(bucket, "")
            except serr.StorageError:
                continue
            for e in base_entries:
                if e.endswith("/"):
                    walk(disk, e)
        return sorted(n.rstrip("/") for n in names)

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000,
                     marker: str = "") -> list[ObjectInfo]:
        """Latest live version per key, served by the metacache engine:
        cached parallel walk_dir + k-way quorum merge (ref listPath,
        cmd/metacache-server-pool.go:38)."""
        self._check_bucket(bucket)
        return [ObjectInfo.from_file_info(fi)
                for fi in self.metacache.list_path(
                    bucket, prefix=prefix, marker=marker,
                    max_keys=max_keys)]

    def list_object_versions(self, bucket: str, prefix: str = "",
                             max_keys: int = 1000,
                             marker: str = "") -> list[ObjectInfo]:
        """All versions (objects + delete markers) newest-first per key,
        quorum-resolved from the same metacache walk (ref
        ListObjectVersions through listPath)."""
        self._check_bucket(bucket)
        return [ObjectInfo.from_file_info(fi)
                for fi in self.metacache.list_versions(
                    bucket, prefix=prefix, marker=marker,
                    max_keys=max_keys)]
