"""Healing: converge damaged/missing shards back to full redundancy
(ref cmd/erasure-healing.go:224 healObject, cmd/background-heal-ops.go,
cmd/erasure-object.go:1082 MRF).

heal_object classifies each disk for the latest quorum version —
  ok        xl.meta agrees + shard passes bitrot verify
  outdated  xl.meta missing/stale (disk swapped, partial write)
  corrupt   shard fails deep bitrot scan
— then regenerates every missing shard from k good ones and rewrites the
bad disks via the same tmp→rename_data commit as a PUT. Reconstruction is
the best TPU batch source: all blocks of an object share one erasure
mask, so each part's blocks coalesce into a single batched device
dispatch via codec.decode_all_blocks_batch → ops/batching.py (SURVEY §7
stage 5; one mask group per part, tail block forming its own group).
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field

import numpy as np

from ..faultinject import FAULTS
from ..parallel.quorum import parallel_map
from ..storage import errors as serr
from ..storage.metadata import FileInfo
from ..storage.xl import INTENT_FILE, MINIO_META_BUCKET, TMP_PATH
from ..utils import ceil_frac
from . import bitrot
from .codec import codec_for_algorithm

# Cap on stacked survivor bytes per coalesced heal dispatch: large
# enough to saturate the device, small enough to bound heal memory.
HEAL_BATCH_BYTES = 64 * 1024 * 1024

# Crash points on the heal write-back commit: mid shard regeneration
# (staged frames on the bad disks, object still degraded) and just
# before the per-disk rename_data fan-out (fully staged).
CRASH_HEAL_MID = FAULTS.register_crash_point("engine.heal.mid_append")
CRASH_HEAL_PRE_COMMIT = FAULTS.register_crash_point(
    "engine.heal.pre_commit")


@dataclass
class HealResult:
    bucket: str
    object_name: str
    total_disks: int = 0
    before_ok: int = 0
    after_ok: int = 0
    healed_disks: list[int] = field(default_factory=list)
    corrupt_disks: list[int] = field(default_factory=list)
    missing_disks: list[int] = field(default_factory=list)
    dangling: bool = False
    skipped_lock: bool = False  # lock-contended: requeued via MRF

    @property
    def healthy(self) -> bool:
        """Full redundancy restored: every disk holds a valid shard."""
        return not self.dangling and self.after_ok == self.total_disks


class Healer:
    """Heal operations over an ErasureObjects engine."""

    def __init__(self, engine):
        self.engine = engine
        # Set by ErasureObjects.shutdown(): long sweeps (fresh-disk,
        # post-reinstatement) run on daemon threads that outlive their
        # trigger — they must stop at the next object boundary instead
        # of healing a dead deployment's disks forever.
        self._shutdown = threading.Event()

    def shutdown(self) -> None:
        self._shutdown.set()

    # -- classification ------------------------------------------------

    def _classify(self, bucket: str, object_name: str,
                  ) -> tuple[FileInfo, list[str]]:
        """Returns (quorum FileInfo, per-disk state list:
        'ok'|'outdated'|'corrupt')."""
        eng = self.engine
        # reduce_notfound=False: a below-quorum straggler copy must
        # surface as QuorumError so heal classifies it dangling and
        # purges it, not as ObjectNotFound (which would skip it forever).
        fi, agreed = eng._quorum_file_info(bucket, object_name,
                                           reduce_notfound=False)

        def check(i: int) -> str:
            f = agreed[i]
            if f is None:
                return "outdated"
            if fi.size == 0 or fi.deleted:
                return "ok"
            try:
                eng.disks[i].verify_file(bucket, object_name, f)
                return "ok"
            except serr.FileCorrupt:
                return "corrupt"
            except serr.StorageError:
                return "outdated"
            except Exception:
                return "outdated"

        results, _ = parallel_map(
            [lambda i=i: check(i) for i in range(len(eng.disks))])
        states = list(results)
        return fi, states

    # -- object heal ---------------------------------------------------

    def heal_object(self, bucket: str, object_name: str,
                    dry_run: bool = False,
                    lock_timeout: float = 30.0) -> HealResult:
        """Per-object heal under the namespace lock (ref healObject
        taking the object's ns lock, cmd/erasure-healing.go): classify +
        repair must not race a concurrent overwrite swapping the data
        dir between the metadata read and the shard reads/writes.

        Lock discipline: classification (metadata + deep bitrot verify)
        is read-only, so it runs under the READ lock — sweeping a mostly
        healthy namespace never stalls client traffic. Only when repair
        is actually needed does the heal escalate to the write lock and
        re-classify under it (the state may have changed in between).

        Dispatch priority: every heal entry point funnels here, so the
        whole operation runs in the BACKGROUND lane — its batched
        reconstructs yield the device/coalescing window to foreground
        encode work (qos/scheduler.py), with aging against starvation."""
        from ..qos.scheduler import background_lane
        with background_lane():
            with self.engine.ns_lock.read_locked(bucket, object_name,
                                                 lock_timeout):
                res = self._heal_object_locked(bucket, object_name,
                                               dry_run=True)
            if (dry_run or res.dangling
                    or not (res.corrupt_disks or res.missing_disks)):
                return res
            with self.engine.ns_lock.write_locked(bucket, object_name,
                                                  lock_timeout):
                return self._heal_object_locked(bucket, object_name,
                                                dry_run=False)

    def heal_object_or_queue(self, bucket: str, object_name: str,
                             dry_run: bool = False) -> HealResult:
        """Sweep-friendly heal: a lock-contended object (e.g. a
        long-lived GET stream holding its read lock) is requeued via MRF
        and reported skipped instead of aborting or stalling the sweep.
        The single helper all sweep loops share, so skip reporting is
        consistent everywhere."""
        try:
            return self.heal_object(bucket, object_name, dry_run)
        except TimeoutError:
            if not dry_run:
                # Audits stay read-only: only REPAIR sweeps requeue the
                # contended object for a real background heal.
                self.engine.mrf.add(bucket, object_name)
            res = HealResult(bucket, object_name,
                             total_disks=len(self.engine.disks))
            res.skipped_lock = True
            return res

    def _heal_object_locked(self, bucket: str, object_name: str,
                            dry_run: bool = False) -> HealResult:
        from ..parallel.quorum import QuorumError
        eng = self.engine
        n_disks = len(eng.disks)
        from .engine import BucketNotFound, ObjectNotFound
        try:
            fi, states = self._classify(bucket, object_name)
        except QuorumError as exc:
            res = HealResult(bucket, object_name, total_disks=n_disks)
            # Dangling requires NOT-FOUND evidence (ref isObjectDangling:
            # only errFileNotFound counts). A transient full-disk outage
            # (real IO errors) must not classify an intact object
            # unrecoverable — that path purges data once acted upon.
            real = [e for e in getattr(exc, "errs", [])
                    if e is not None and not isinstance(
                        e, (serr.FileNotFound, serr.VersionNotFound))]
            res.dangling = not real
            return res
        except (ObjectNotFound, BucketNotFound):
            # Object — or its whole bucket — deleted between listing
            # and healing: nothing to do; the sweep continues.
            return HealResult(bucket, object_name, total_disks=n_disks)
        res = HealResult(bucket, object_name, total_disks=n_disks)
        res.before_ok = states.count("ok")
        res.corrupt_disks = [i for i, s in enumerate(states)
                             if s == "corrupt"]
        res.missing_disks = [i for i, s in enumerate(states)
                             if s == "outdated"]
        bad = res.corrupt_disks + res.missing_disks
        if not bad:
            res.after_ok = res.before_ok
            return res
        k, m = fi.erasure.data_blocks, fi.erasure.parity_blocks
        if res.before_ok < k:
            res.dangling = True  # unrecoverable (ref dangling purge)
            res.after_ok = res.before_ok
            return res
        if dry_run:
            res.after_ok = res.before_ok
            return res

        # A fresh replacement disk may lack the bucket volume entirely —
        # heal it first so shard/metadata writes land (ref healObject's
        # implicit HealBucket dependency). But ONLY while a majority of
        # disks still carry the bucket: healing must never resurrect a
        # bucket a racing delete_bucket(force=True) just removed (the
        # same invariant xl.py's _makedirs_for enforces on write paths).
        if not eng.bucket_exists(bucket):
            res.after_ok = res.before_ok
            return res
        for i in bad:
            try:
                eng.disks[i].stat_volume(bucket)
            except serr.VolumeNotFound:
                try:
                    eng.disks[i].make_volume(bucket)
                except serr.StorageError:
                    pass
            except serr.StorageError:
                pass

        if fi.size == 0 or fi.deleted:
            res.healed_disks = self._rewrite_meta_only(fi, bad)
            res.after_ok = res.before_ok + len(res.healed_disks)
            return res

        # Shard indices (0-based) on good vs bad disks, via each good
        # disk's own metadata index; bad disks get theirs from the quorum
        # distribution.
        dist = fi.erasure.distribution
        good_disks = [i for i, s in enumerate(states) if s == "ok"]
        shard_of_disk = {i: dist[i] - 1 for i in range(len(eng.disks))}

        # Rebuild every part's full shard matrix blockwise from k good
        # shards: one decode per block, shared mask across the whole
        # object (the best TPU batch source). The rebuild STREAMS
        # through a bounded pipeline (utils/pipeline.py): the producer
        # reads survivors, batch-reconstructs one block group, and
        # bitrot-frames it, while the consumer writes the PREVIOUS
        # group's regenerated frames to the bad disks — reconstruct
        # dispatches overlap write-back I/O. The pipeline inherits the
        # heal's background lane, so a deferred kernel dispatch stalls
        # production and the queue drains (defer = drain, don't grow).
        shard_size = fi.erasure.shard_size()
        missing_shards = sorted(shard_of_disk[i] for i in bad)
        # Codec follows the object's xl.meta algorithm stamp: REGEN
        # objects heal through the minimum-bandwidth regen path below,
        # plain-RS objects through the conventional k-survivor decode.
        codec = codec_for_algorithm(
            fi.erasure.algorithm, k, m, fi.erasure.block_size,
            # Heal reconstructs dispatch from this set too: same home
            # device as the serving codec (parallel/mesh.py affinity).
            affinity=getattr(self.engine, "device_affinity", None))
        from ..storage.metadata import ObjectPartInfo
        parts = fi.parts or [ObjectPartInfo(number=1, size=fi.size,
                                            actual_size=fi.size)]

        def part_algo(part) -> str:
            algo = bitrot.DEFAULT_ALGORITHM
            for cs in fi.erasure.checksums:
                if cs.get("part") == part.number:
                    algo = cs.get("algorithm", algo)
            return algo

        # Health-ranked survivors (obs/drivemon.py): read the k shards
        # (or, for REGEN, contact the d helpers) from the healthiest
        # sources first — a suspect drive only serves a heal read when
        # no healthier survivor can (the same any-k-of-n policy the GET
        # path uses).
        from ..obs.drivemon import DRIVEMON, OK as _DM_OK

        def _rank(i: int) -> tuple:
            ep = eng.endpoints[i]
            state = DRIVEMON.state_of(ep)
            return (1 if DRIVEMON.is_quarantined(ep) else 0,
                    0 if state == _DM_OK else 1,
                    DRIVEMON.ewma_for(ep).get("read", 0.0))

        read_order = sorted(good_disks, key=_rank)
        from .regen.repair import REPAIR_BYTES

        def produce_groups():
            """Yield (part_number, {shard_idx: framed bytes}) per block
            group, parts in order, groups in order — consecutive
            groups' frames concatenate into exactly the shard stream
            the old whole-part encode produced."""
            for part in parts:
                # Collect k survivor streams, tolerating read failures
                # from disks that were "ok" at classify time but
                # dropped since (a peer restarting mid-sweep): any k
                # good shards decode; only fewer than k is fatal.
                streams = {}
                for i in read_order:
                    if len(streams) == k:
                        break
                    try:
                        data = eng.disks[i].read_all(
                            bucket,
                            f"{object_name}/{fi.data_dir}"
                            f"/part.{part.number}")
                    except serr.StorageError:
                        continue
                    # Repair-traffic ledger (the RS baseline the regen
                    # path's 2x claim is measured against): a full
                    # survivor chunk is read from media AND crosses the
                    # wire in a distributed set.
                    REPAIR_BYTES.add("rs", "disk", len(data))
                    REPAIR_BYTES.add("rs", "net", len(data))
                    streams[shard_of_disk[i]] = data
                if len(streams) < k:
                    raise serr.FaultyDisk(
                        f"heal {bucket}/{object_name}: only "
                        f"{len(streams)}/{k} survivor shards readable")
                algo = part_algo(part)
                n_blocks = ceil_frac(part.size, fi.erasure.block_size)
                if n_blocks == 0:
                    # Zero-byte part: the (empty) shard file must still
                    # exist on the healed disk.
                    yield part.number, {j: b"" for j in missing_shards}
                    continue
                # All blocks share one erasure mask -> coalesced device
                # dispatches (ops/batching.py), bounded to
                # HEAL_BATCH_BYTES of stacked survivors so peak memory
                # stays O(batch), not O(part).
                group = max(1, HEAL_BATCH_BYTES
                            // max(fi.erasure.block_size, 1))
                for b0 in range(0, n_blocks, group):
                    block_shards: list[list[np.ndarray | None]] = []
                    for b in range(b0, min(b0 + group, n_blocks)):
                        blk_len = min(
                            fi.erasure.block_size,
                            part.size - b * fi.erasure.block_size)
                        chunk = ceil_frac(blk_len, k)
                        shards: list[np.ndarray | None] = \
                            [None] * (k + m)
                        for j, stream in streams.items():
                            data = bitrot.extract_block(
                                stream, b, chunk, shard_size, algo)
                            shards[j] = np.frombuffer(data,
                                                      dtype=np.uint8)
                        block_shards.append(shards)
                    acc = {j: bytearray() for j in missing_shards}
                    for full in codec.decode_all_blocks_batch(
                            block_shards):
                        for j in missing_shards:
                            acc[j] += full[j].tobytes()
                    # Group lengths are multiples of shard_size except
                    # the part's final group, so per-group framing
                    # concatenates byte-identically to whole-part
                    # framing (pinned by tests/test_pipeline.py).
                    yield part.number, {
                        j: bitrot.encode_stream(bytes(acc[j]),
                                                shard_size, algo)
                        for j in missing_shards}

        # Write regenerated shards to the bad disks group by group
        # (tmp append stream -> rename_data, same commit path as PUT;
        # ref Erasure.Heal writes via bitrot writers then
        # writeUniqueFileInfo + rename). Per-disk failures drop that
        # disk from the write set without aborting the others.
        tmp_paths = {i: f"{TMP_PATH}/{uuid.uuid4()}" for i in bad}
        write_errs: dict[int, BaseException] = {}
        # Recovery breadcrumbs: a crash mid write-back leaves staged
        # frames on the bad disks; the boot sweep reads the intent to
        # requeue the (still-degraded) object for heal before GC.
        from .engine import _stage_intent_blob
        intent_blob = _stage_intent_blob(bucket, object_name,
                                         fi.version_id, fi.data_dir)
        for i in bad:
            try:
                eng.disks[i].append_file(
                    MINIO_META_BUCKET, f"{tmp_paths[i]}/{INTENT_FILE}",
                    intent_blob)
            except Exception:
                pass  # best-effort; a dead disk fails its appends next

        def drop_disk(i: int, exc: BaseException) -> None:
            write_errs[i] = exc
            try:
                eng.disks[i].delete(MINIO_META_BUCKET, tmp_paths[i],
                                    recursive=True)
            except Exception:
                pass

        # A single-group object (the common small-object sweep case)
        # has nothing to overlap: consume the generator inline rather
        # than paying a worker-thread handoff per healed object.
        group_blocks = max(1, HEAL_BATCH_BYTES
                           // max(fi.erasure.block_size, 1))
        n_groups = sum(
            max(1, ceil_frac(ceil_frac(p.size, fi.erasure.block_size),
                             group_blocks))
            for p in parts)
        if getattr(codec, "is_regen", False):
            # Minimum-bandwidth REGEN heal: helpers project locally and
            # ship d small rows per block instead of k full chunks
            # (erasure/regen/repair.py); the generator feeds the SAME
            # write-back pipeline, crash points and commit below.
            from .regen.repair import regen_heal_groups
            producer = regen_heal_groups(
                eng, bucket, object_name, fi, codec, parts,
                missing_shards, shard_of_disk, read_order, part_algo,
                HEAL_BATCH_BYTES)
        else:
            producer = produce_groups()
        from ..utils.pipeline import Prefetch
        pf = (Prefetch(producer, depth=eng.pipeline_depth, name="heal")
              if n_groups > 1 else
              contextlib.nullcontext(producer))
        with pf as groups:
            try:
                for part_number, frames in groups:
                    live = [i for i in bad if i not in write_errs]
                    if not live:
                        break  # nobody left to heal; stop decoding
                    # Crash window: fires per block group — staged
                    # frames on the bad disks, object still serving
                    # from its k survivors.
                    FAULTS.crash_point(CRASH_HEAL_MID)
                    _, errs = parallel_map(
                        [lambda i=i: eng.disks[i].append_file(
                            MINIO_META_BUCKET,
                            f"{tmp_paths[i]}/{fi.data_dir}"
                            f"/part.{part_number}",
                            frames[shard_of_disk[i]])
                         for i in live])
                    for i, e in zip(live, errs):
                        if e is not None:
                            drop_disk(i, e)
            except BaseException:
                for i in bad:
                    if i not in write_errs:
                        drop_disk(i, serr.FaultyDisk("heal aborted"))
                raise

        def commit_one(i: int):
            disk = eng.disks[i]
            j = shard_of_disk[i]
            try:
                new_fi = FileInfo(
                    volume=bucket, name=object_name,
                    version_id=fi.version_id, data_dir=fi.data_dir,
                    size=fi.size, mod_time=fi.mod_time,
                    metadata=dict(fi.metadata), parts=list(fi.parts),
                    erasure=type(fi.erasure)(
                        algorithm=fi.erasure.algorithm,
                        data_blocks=k, parity_blocks=m,
                        block_size=fi.erasure.block_size,
                        index=j + 1, distribution=list(dist),
                        checksums=list(fi.erasure.checksums)),
                )
                disk.rename_data(MINIO_META_BUCKET, tmp_paths[i],
                                 new_fi, bucket, object_name)
            except BaseException:
                try:
                    disk.delete(MINIO_META_BUCKET, tmp_paths[i],
                                recursive=True)
                except Exception:
                    pass
                raise

        # Crash window: every regenerated shard staged, rename_data
        # fan-out not yet started.
        FAULTS.crash_point(CRASH_HEAL_PRE_COMMIT)
        alive_bad = [i for i in bad if i not in write_errs]
        _, errs = parallel_map([lambda i=i: commit_one(i)
                                for i in alive_bad])
        res.healed_disks = [i for i, e in zip(alive_bad, errs)
                            if e is None]
        res.after_ok = res.before_ok + len(res.healed_disks)
        return res

    def _rewrite_meta_only(self, fi: FileInfo, bad: list[int]) -> list[int]:
        """Per-disk metadata rewrite; returns indices actually healed
        (failures on individual disks don't abort the rest)."""
        dist = fi.erasure.distribution

        def one(i: int):
            new_fi = FileInfo(
                volume=fi.volume, name=fi.name, version_id=fi.version_id,
                deleted=fi.deleted, data_dir=fi.data_dir, size=fi.size,
                mod_time=fi.mod_time, metadata=dict(fi.metadata),
                parts=list(fi.parts),
                erasure=type(fi.erasure)(
                    algorithm=fi.erasure.algorithm,
                    data_blocks=fi.erasure.data_blocks,
                    parity_blocks=fi.erasure.parity_blocks,
                    block_size=fi.erasure.block_size,
                    index=dist[i] if i < len(dist) else 0,
                    distribution=list(dist),
                    checksums=list(fi.erasure.checksums)),
            )
            self.engine.disks[i].write_metadata(fi.volume, fi.name, new_fi)

        _, errs = parallel_map([lambda i=i: one(i) for i in bad])
        return [i for i, e in zip(bad, errs) if e is None]

    # -- bucket heal ---------------------------------------------------

    def heal_bucket(self, bucket: str) -> list[int]:
        """Create the bucket volume on disks where it's missing
        (ref HealBucket). Guarded by the majority vote: healing
        stragglers must never resurrect a bucket a racing delete_bucket
        just removed from every (or most) disks."""
        eng = self.engine
        if not eng.bucket_exists(bucket):
            return []
        healed = []
        for i, disk in enumerate(eng.disks):
            try:
                disk.stat_volume(bucket)
            except serr.VolumeNotFound:
                try:
                    disk.make_volume(bucket)
                    healed.append(i)
                except serr.StorageError:
                    pass
            except serr.StorageError:
                pass
        return healed

    def heal_disk(self, disk_index: int) -> list[HealResult]:
        """Full sweep healing everything onto one (fresh) disk
        (ref healErasureSet / monitorLocalDisksAndHeal). The listing
        walk between per-object heals also runs in the background lane
        (per-object heals re-enter it via heal_object)."""
        from ..qos.scheduler import background_lane
        with background_lane():
            return self._heal_disk_bg(disk_index)

    def _heal_disk_bg(self, disk_index: int) -> list[HealResult]:
        from ..qos.scheduler import GATE
        eng = self.engine
        results = []
        last_cost = None
        for binfo in eng.list_buckets():
            if self._shutdown.is_set():
                break
            bucket = binfo["name"]
            self.heal_bucket(bucket)
            for obj in eng.list_objects(bucket, max_keys=1_000_000):
                if self._shutdown.is_set():
                    return results
                # Pace the sweep against foreground traffic (ref
                # waitForLowHTTPReq + dynamicSleeper): per-object heal
                # is I/O+hash heavy; yield ~10x the last object's own
                # cost between objects, aging-bounded.
                GATE.throttle_background(last_cost)
                # Per-object isolation: one failing object (lock
                # timeout, peer flapping mid-sweep) must not abort the
                # rest of the sweep — it starved convergence when an
                # early object kept failing while later ones never got
                # reached; the next sweep retries it anyway.
                t0 = time.monotonic()
                try:
                    r = self.heal_object_or_queue(bucket, obj.name)
                except Exception as exc:  # noqa: BLE001 — sweep survives
                    import logging
                    logging.getLogger("minio_tpu.heal").warning(
                        "heal sweep: %s/%s failed: %r", bucket,
                        obj.name, exc)
                    continue
                finally:
                    last_cost = time.monotonic() - t0
                if disk_index in r.healed_disks or not r.healed_disks:
                    results.append(r)
        return results


class NewDiskMonitor:
    """Detects freshly replaced (wiped) disks and auto-triggers the
    full heal sweep onto them (ref monitorLocalDisksAndHeal,
    cmd/background-newdisks-heal-ops.go:113: the reference watches for
    disks carrying a healing tracker written at fresh format).

    Freshness signal here: a reachable disk that is missing bucket
    volumes the rest of the set agrees on — exactly the state a swapped
    drive is in. Object-level drift on a disk that has all volumes is
    the scanner's heal-sampling job, not this monitor's."""

    def __init__(self, healer: Healer, interval: float = 10.0):
        self.healer = healer
        self.interval = interval
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # monotonic time of each disk's last completed sweep. A disk
        # still missing volumes re-sweeps after a slow-cadence backoff
        # (a single sweep can partially fail under write-lock
        # contention; once-ever marking would stall convergence
        # forever), and is cleared when the disk turns healthy so a
        # future re-replacement triggers immediately.
        self._swept: dict[int, float] = {}
        self.sweeps = 0   # observability: completed auto-sweeps

    def _resweep_after(self) -> float:
        return max(self.interval * 4, 5.0)

    def _heal_format(self, i: int, disk) -> bool:
        """Restore a hot-swapped disk's format.json from a healthy set
        peer (ref HealFormat, cmd/erasure-sets.go — the reference
        re-stamps blank replacement drives without a restart; our boot
        path only does this at init_or_load_formats time). The engine's
        disk order IS the format row order, so slot i's uuid is row[i]
        of whichever set row contains a healthy peer's uuid."""
        from ..storage.format import (FormatErasure, load_format,
                                      save_format)
        if load_format(disk) is not None:
            return False
        import logging
        log = logging.getLogger("minio_tpu.heal")
        eng = self.healer.engine
        for j, peer in enumerate(eng.disks):
            if j == i:
                continue
            ref = load_format(peer)
            if ref is None:
                log.debug("restamp probe: peer %d (%s) format "
                          "unreadable", j, peer)
                continue
            pos = ref.find(ref.this)
            if pos is None or pos[1] != j:
                log.debug("restamp probe: peer %d slot mismatch "
                          "pos=%s", j, pos)
                continue  # peer not in this set row at its slot: skip
            row = ref.sets[pos[0]]
            save_format(disk, FormatErasure(
                ref.deployment_id, row[i], ref.sets,
                ref.distribution_algo))
            log.info("restamped fresh disk %d (%s) as %s", i,
                     getattr(disk, "root", disk), row[i][:8])
            return True
        log.debug("restamp: no usable peer for disk %d", i)
        return False

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        # mtpu-lint: disable=R1 -- boot-time daemon; heal work tags its own bg lane at the call sites
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="newdisk-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:
                import logging
                logging.getLogger("minio_tpu.heal").exception(
                    "new-disk monitor tick failed")

    def tick(self) -> list[int]:
        """One detection pass; returns indices of disks swept."""
        eng = self.healer.engine
        buckets = [b["name"] for b in eng.list_buckets()]
        if not buckets:
            return []
        swept = []
        for i, disk in enumerate(eng.disks):
            # LOCAL disks only (ref monitorLocalDisksAndHeal): a wiped
            # remote drive is its own node's monitor's job — every
            # node sweeping the same replacement at once just fights
            # over write locks.
            if not hasattr(disk, "root"):
                continue
            try:
                self._heal_format(i, disk)
            except Exception:
                # Dead disk / no healthy peer reachable right now: the
                # volumes check below still runs, and every later tick
                # retries the re-stamp. Log it — a silently un-stamped
                # drive would fail the NEXT restart's format quorum.
                import logging
                logging.getLogger("minio_tpu.heal").warning(
                    "format re-stamp failed for disk %d (%s)",
                    i, getattr(disk, "root", disk), exc_info=True)
            try:
                vols = set(disk.list_volumes())
            except Exception:
                # Unreachable: not fresh — but forget its healed mark
                # so its eventual replacement is re-swept.
                self._swept.pop(i, None)
                continue
            missing = [b for b in buckets if b not in vols]
            if not missing:
                # Healthy again: clear the mark so a future
                # re-replacement counts as fresh.
                self._swept.pop(i, None)
                continue
            last = self._swept.get(i)
            if last is not None and (time.monotonic() - last
                                     < self._resweep_after()):
                continue
            # heal_disk re-creates missing bucket volumes itself
            # (heal_bucket per quorum-listed bucket) before sweeping.
            self.healer.heal_disk(i)
            self._swept[i] = time.monotonic()
            self.sweeps += 1
            swept.append(i)
        return swept


class QuarantineProber:
    """Probation probes for quarantined drives — the reinstatement half
    of the quarantine lifecycle (obs/drivemon.py).

    Every tick, each quarantined drive in the set is shadow-probed: a
    bitrot-framed blob is staged to the drive's tmp area, read back,
    and verified frame-exact (write path + read path + bitrot layer all
    exercised — the three ways a sick drive lies). One clean round is a
    probation pass; ``DriveMonitor.PROBATION_PASSES`` CONSECUTIVE
    passes reinstate the drive; any failure restarts the streak.
    Reinstatement kicks a background heal sweep onto the drive so the
    writes it missed while quarantined (MRF-requeued degraded writes)
    converge back to full redundancy.

    Probe I/O rides the normal _DiskOp boundary, so the fault-injection
    subsystem perturbs probes exactly like data-plane ops — a drive
    whose injected faults are still active keeps failing probation.

    Start contract mirrors NewDiskMonitor: the server boot starts the
    thread; tests and library users drive tick() directly."""

    PROBE_BYTES = 64 * 1024

    def __init__(self, engine, interval: float = 5.0):
        self.engine = engine
        self.interval = interval
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.probes = 0      # observability: probe rounds run
        self.reinstated = 0  # observability: drives brought back

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        # mtpu-lint: disable=R1 -- boot-time probe daemon; probes carry no request context
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="quarantine-prober")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:
                import logging
                logging.getLogger("minio_tpu.heal").exception(
                    "quarantine prober tick failed")

    def tick(self) -> list[int]:
        """One probe round over this set's quarantined drives; returns
        indices of drives reinstated this round."""
        from ..obs.drivemon import DRIVEMON
        eng = self.engine
        reinstated = []
        for i, disk in enumerate(eng.disks):
            ep = eng.endpoints[i]
            if not DRIVEMON.is_quarantined(ep):
                continue
            self.probes += 1
            if self._probe(disk):
                if DRIVEMON.probation_pass(ep):
                    self.reinstated += 1
                    reinstated.append(i)
                    self._heal_after_reinstate(i)
            else:
                DRIVEMON.probation_fail(ep)
        return reinstated

    def _probe(self, disk) -> bool:
        """One shadow probe: staged bitrot-framed write + read-back +
        frame verification. Deterministic payload so a byte-level
        mangling (injected corruption, real bitrot) is always caught."""
        shard_size = 4096
        payload = bytes(range(256)) * (self.PROBE_BYTES // 256)
        framed = bitrot.encode_stream(payload, shard_size,
                                      bitrot.DEFAULT_ALGORITHM)
        rel = f"{TMP_PATH}/probation-probe-{uuid.uuid4().hex}"
        try:
            disk.write_all(MINIO_META_BUCKET, rel, framed)
            back = disk.read_all(MINIO_META_BUCKET, rel)
            ok = (bytes(back) == bytes(framed)
                  and bitrot.verify_stream(back, shard_size,
                                           bitrot.DEFAULT_ALGORITHM))
        except Exception:
            ok = False
        finally:
            try:
                disk.delete(MINIO_META_BUCKET, rel)
            except Exception:
                pass
        return ok

    def _heal_after_reinstate(self, disk_index: int) -> None:
        """Converge the writes the drive missed while quarantined: a
        full background sweep onto it, like a fresh-disk heal (the
        MRF entries its degraded writes queued may already be
        drained)."""
        import logging
        logging.getLogger("minio_tpu.heal").info(
            "drive %d reinstated after probation; starting heal sweep",
            disk_index)

        def run():
            try:
                self.engine.healer.heal_disk(disk_index)
            except Exception:
                logging.getLogger("minio_tpu.heal").exception(
                    "post-reinstatement heal sweep failed")

        # mtpu-lint: disable=R1 -- reinstatement sweep outlives the probe tick; heal tags its own bg lane at the call sites
        threading.Thread(target=run, daemon=True,
                         name=f"reinstate-heal-{disk_index}").start()


class MRFQueue:
    """Most-recently-failed heal queue: partial PUT failures enqueue the
    object for background healing (ref mrfOpCh, cmd/erasure-object.go:1082
    + healRoutine, cmd/background-heal-ops.go:89).

    Two robustness layers on top of the reference's buffered channel:
    (a) ``add()`` DEDUPS — a flapping drive requeueing the same object
    on every degraded write used to inflate depth and force drops of
    OTHER objects' repairs; now a (bucket, object) already queued is a
    set lookup, not a new entry. (b) every accepted entry is journaled
    to the per-set durable MRF journal (erasure/mrfjournal.py,
    ``.minio.sys/mrf.log``) and replayed at boot, so a crash no longer
    silently discards the queued repairs."""

    # One drop log line per window — a full queue under a disk outage
    # drops thousands of entries, and each dropped heal is data
    # durability silently deferred to the next sweep; the log must say
    # so without becoming the new bottleneck.
    DROP_LOG_WINDOW_S = 60.0

    def __init__(self, healer: Healer, maxsize: int = 10_000):
        self.healer = healer
        self.q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.drops = 0
        self._last_drop_log = 0.0
        # In-flight dedup set guarded by its own tiny lock (the Queue's
        # internal mutex is not reachable for the membership check).
        self._qmu = threading.Lock()
        self._queued: set[tuple[str, str]] = set()
        from .mrfjournal import MRFJournal
        self.journal = MRFJournal(healer.engine.disks)

    def depth(self) -> int:
        return self.q.qsize()

    def add(self, bucket: str, object_name: str) -> None:
        from ..obs.metrics2 import METRICS2
        key = (bucket, object_name)
        dropped = False
        # Dedup-insert, enqueue, AND journal under one critical
        # section, mirrored by _heal's retire path: interleaving them
        # lets a concurrent retire of the SAME key either dedup a
        # fresh repair out of existence or strip a freshly queued
        # repair of its journal entry (crash durability silently
        # lost). MRF adds are failure-path, never hot, and the
        # journal batches its I/O — serializing them is cheap.
        with self._qmu:
            if key in self._queued:
                return  # already queued: dedup, don't inflate depth
            self._queued.add(key)
            try:
                self.q.put_nowait((bucket, object_name))
            except queue.Full:
                # Best effort like the reference's buffered channel —
                # but COUNTED: a silent drop is a heal that never
                # happens until the next full sweep notices.
                self._queued.discard(key)
                self.drops += 1
                dropped = True
            else:
                # Durability: journal the accepted entry so a crash
                # replays it (no-op when already journaled, when the
                # set has no local disks, or past the size cap —
                # drops counted there).
                self.journal.record(bucket, object_name)
        if dropped:
            METRICS2.inc("minio_tpu_v2_mrf_drops_total")
            now = time.monotonic()
            if now - self._last_drop_log >= self.DROP_LOG_WINDOW_S:
                self._last_drop_log = now
                from ..logger import Logger
                Logger.get().info(
                    f"MRF queue full ({self.q.maxsize}): dropped heal "
                    f"for {bucket}/{object_name} "
                    f"({self.drops} drops total)", "heal")
            return
        METRICS2.set_gauge("minio_tpu_v2_mrf_queue_depth", None,
                           self.q.qsize())
        # Background worker starts lazily on first failure so every
        # deployment (server, library use) gets self-healing without
        # explicit wiring.
        if self._thread is None:
            self.start()

    def replay_journal(self) -> int:
        """Boot-time replay (storage/recovery.py): re-queue every
        journaled repair through the normal add() path, so the depth
        gauge reflects the replayed backlog and the worker starts.
        Entries already in the journal are not re-appended (replay
        seeds the journal's dedup set)."""
        entries = self.journal.replay()
        for bucket, object_name in entries:
            self.add(bucket, object_name)
        return len(entries)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        # mtpu-lint: disable=R1 -- boot-time MRF daemon; no request context exists to carry
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            try:
                self.q.put_nowait(None)  # wake; Full is fine — the worker
            except queue.Full:           # checks _stop after every item
                pass
            self._thread.join(timeout=5)
            self._thread = None

    def drain(self) -> None:
        """Synchronously heal everything queued (tests/shutdown)."""
        while True:
            try:
                item = self.q.get_nowait()
            except queue.Empty:
                return
            if item is not None:
                self._heal(item)

    # Contended items retry this many times with a SHORT lock wait so a
    # handful of hot keys can't head-of-line-block the whole queue.
    MAX_TRIES = 8
    LOCK_WAIT_S = 3.0

    def _heal(self, item) -> None:
        from ..qos.scheduler import GATE, background_lane
        bucket, object_name, tries = (item if len(item) == 3
                                      else (*item, 0))
        requeued = False
        converged = False
        try:
            with background_lane():
                GATE.throttle_background()  # MRF drains behind traffic
            res = self.healer.heal_object(bucket, object_name,
                                          lock_timeout=self.LOCK_WAIT_S)
            # Converged: every bad disk healed (or nothing was bad, or
            # the object is dangling/deleted — no future heal will
            # change it). Only then does the JOURNAL entry retire; a
            # failed heal keeps its durability debt on disk for the
            # next boot/retry.
            bad = set(res.corrupt_disks) | set(res.missing_disks)
            converged = (res.dangling
                         or bad <= set(res.healed_disks))
        except TimeoutError:
            # Still contended: requeue to the BACK with a retry cap —
            # the sweep loops that enqueued this expect an eventual
            # retry, not a silent drop.
            if tries + 1 < self.MAX_TRIES:
                try:
                    self.q.put_nowait((bucket, object_name, tries + 1))
                    requeued = True
                except queue.Full:
                    pass
        except Exception:
            pass  # background best-effort
        finally:
            if not requeued:
                # Retire under the same lock add() inserts under (see
                # add): the key leaves the dedup set either way — a
                # FAILED heal must be re-addable by the next degraded
                # write or sweep — and a CONVERGED heal retires its
                # journal entry atomically with it.
                with self._qmu:
                    self._queued.discard((bucket, object_name))
                    if converged:
                        self.journal.complete(bucket, object_name)

    def _run(self) -> None:
        from ..obs.metrics2 import METRICS2
        while not self._stop.is_set():
            item = self.q.get()
            METRICS2.set_gauge("minio_tpu_v2_mrf_queue_depth", None,
                               self.q.qsize())
            if item is None or self._stop.is_set():
                break
            self._heal(item)
