"""Durable MRF journal — the crash-survival half of the
most-recently-failed heal queue (erasure/heal.py MRFQueue).

The MRF queue is the store's durability debt ledger: every degraded
write (quarantined drive skipped, shard commit failed) queues the
object for background heal. Before this module the ledger was pure
memory — a crash or restart silently discarded every queued repair,
and at SSD-array scale (arXiv:1709.05365) un-replayed repairs are
exactly how one more failure turns into data loss while nobody is
paging. Now every queued repair is also APPENDED to a per-set journal
(``.minio.sys/mrf.log`` on each LOCAL disk of the set) and replayed at
boot (storage/recovery.py drives it via ``MRFQueue.replay_journal``).

Design points:

- **Append-only JSONL**, one ``{"b": bucket, "o": object}`` line per
  entry; torn tails (crash mid-append, no fsync) are tolerated at
  replay — a half-written last line parses as garbage and is skipped.
- **Batched writes**: concurrent ``record()`` calls coalesce — entries
  land on a pending list under the bookkeeping lock, and whichever
  thread wins the writer lock flushes EVERYTHING pending in one append
  per disk, so a failure storm costs one I/O round, not one per entry.
- **Dedup**: an entry already journaled (and not yet healed) is never
  re-appended — a flapping drive requeueing the same object repeatedly
  costs memory-set lookups, not journal growth.
- **Size-capped with drops counted**: past ``MAX_BYTES`` the journal
  first tries to COMPACT (rewrite with only the live entries — stale
  healed lines dominate a long-lived file); if the live set itself
  exceeds the cap, new entries are dropped and
  ``minio_tpu_v2_mrf_journal_drops_total`` counts the lost durability.
- **Truncate-on-empty**: when the last live entry heals, the journal
  compacts to empty — the steady state of a healthy set is an empty
  (or absent) mrf.log.
- **Local disks only**: remote RPC disks belong to another node whose
  own journal covers them; every node journals exactly its local
  ground truth.

Replay unions the per-disk files (any one surviving disk is enough)
and re-queues entries through the normal ``MRFQueue.add`` path, so the
``minio_tpu_v2_mrf_queue_depth`` gauge reflects the replayed backlog
and the watchdog's ``recovery_backlog`` rule can see it shrink — or
not (obs/watchdog.py).
"""

from __future__ import annotations

import json
import threading

from ..storage import errors as serr
from ..storage.xl import MINIO_META_BUCKET

# Journal file, relative to the .minio.sys volume on each local disk.
MRF_LOG_PATH = "mrf.log"


def _line(bucket: str, object_name: str) -> bytes:
    return json.dumps({"b": bucket, "o": object_name},
                      separators=(",", ":")).encode() + b"\n"


def parse_journal(raw: bytes) -> list[tuple[str, str]]:
    """Tolerant JSONL parse: bad lines (torn tail, injected
    corruption) are skipped — a journal is best-effort recovery state,
    never a reason to fail a boot."""
    out: list[tuple[str, str]] = []
    seen: set[tuple[str, str]] = set()
    for ln in raw.splitlines():
        if not ln.strip():
            continue
        try:
            doc = json.loads(ln)
            key = (str(doc["b"]), str(doc["o"]))
        except (ValueError, KeyError, TypeError):
            continue
        if key not in seen:
            seen.add(key)
            out.append(key)
    return out


class MRFJournal:
    """Append-only, deduped, size-capped repair journal over a set's
    local disks."""

    MAX_BYTES = 1 << 20  # per-disk cap; compaction before drops

    def __init__(self, disks):
        # Local disks only; a set with no local disks (pure proxy
        # layouts, unit-test fakes) journals nothing and every call
        # is a cheap no-op.
        self.disks = [d for d in disks if hasattr(d, "root")]
        self._mu = threading.Lock()       # bookkeeping
        self._io_mu = threading.Lock()    # serializes file writers
        self._entries: set[tuple[str, str]] = set()
        self._pending: list[tuple[str, str]] = []
        self._bytes = 0  # appended bytes since the last compaction
        # Incremental byte counters: the cap decision must stay O(1)
        # per record — re-serializing the whole backlog per append
        # would make degraded writes O(backlog) exactly during the
        # failure storms that grow it.
        self._live_bytes = 0     # sum of live entries' line lengths
        self._pending_bytes = 0  # lines queued but not yet flushed
        self.drops = 0
        self.appends = 0

    # -- accounting -----------------------------------------------------

    def backlog(self) -> int:
        """Live (journaled-or-pending, not-yet-healed) entry count —
        the durable-queue depth the watchdog's recovery_backlog rule
        watches via the timeline."""
        with self._mu:
            return len(self._entries)

    def _publish(self) -> None:
        from ..obs.metrics2 import METRICS2
        METRICS2.set_gauge("minio_tpu_v2_mrf_journal_backlog", None,
                           self.backlog())

    def stats(self) -> dict:
        with self._mu:
            return {"backlog": len(self._entries),
                    "bytes": self._bytes, "drops": self.drops,
                    "appends": self.appends,
                    "disks": len(self.disks)}

    # -- writes ---------------------------------------------------------

    def record(self, bucket: str, object_name: str) -> bool:
        """Journal one queued repair (MRFQueue.add). Returns False when
        deduped, dropped over the cap, or there is nothing local to
        journal on."""
        if not self.disks:
            return False
        key = (bucket, object_name)
        blob = _line(*key)
        with self._mu:
            if key in self._entries:
                return False  # already durable (or pending) — dedup
            projected = self._bytes + self._pending_bytes + len(blob)
            if projected > self.MAX_BYTES \
                    and self._live_bytes + len(blob) > self.MAX_BYTES:
                # Even a compacted journal couldn't hold it: the cap
                # is a memory/disk bound, not advice. The repair still
                # sits in the in-memory queue; only its crash
                # durability is lost — and counted.
                self.drops += 1
                from ..obs.metrics2 import METRICS2
                METRICS2.inc("minio_tpu_v2_mrf_journal_drops_total")
                return False
            need_compact = projected > self.MAX_BYTES
            self._entries.add(key)
            self._live_bytes += len(blob)
            self._pending.append(key)
            self._pending_bytes += len(blob)
        if need_compact:
            self._compact()
        else:
            self._flush()
        self._publish()
        return True

    def complete(self, bucket: str, object_name: str) -> None:
        """A journaled repair converged: retire the entry. The line
        stays in the file (append-only) until the journal empties or
        compacts — replaying a stale healed entry is a cheap no-op
        heal, losing a live one is silent durability debt."""
        key = (bucket, object_name)
        with self._mu:
            if key not in self._entries:
                return
            self._entries.discard(key)
            self._live_bytes = max(0,
                                   self._live_bytes - len(_line(*key)))
            empty = not self._entries and (self._bytes or self._pending)
        if empty:
            self._compact()  # truncate: healthy sets carry no journal
        self._publish()

    def _flush(self) -> None:
        """Append everything pending in one write per disk. The writer
        lock serializes file access; bookkeeping stays on _mu so
        recorders never wait on disk I/O they didn't cause."""
        with self._io_mu:
            with self._mu:
                batch, self._pending = self._pending, []
                self._pending_bytes = 0
            if not batch:
                return
            blob = b"".join(_line(*k) for k in batch)
            for disk in self.disks:
                try:
                    disk.append_file(MINIO_META_BUCKET, MRF_LOG_PATH,
                                     blob)
                except Exception:
                    continue  # best-effort per disk; replay unions
            with self._mu:
                self._bytes += len(blob)
                self.appends += 1

    def _compact(self) -> None:
        """Rewrite the journal with only the LIVE entries (atomic
        write_all). Entries recorded after the snapshot stay pending
        and append after — compaction can lose a healed line, never a
        live one."""
        with self._io_mu:
            with self._mu:
                snapshot = sorted(self._entries)
                # Pending entries are covered by the snapshot (record
                # adds to _entries first), so they need no re-append.
                self._pending = [k for k in self._pending
                                 if k not in self._entries]
                self._pending_bytes = sum(len(_line(*k))
                                          for k in self._pending)
            blob = b"".join(_line(*k) for k in snapshot)
            for disk in self.disks:
                try:
                    if blob:
                        disk.write_all(MINIO_META_BUCKET, MRF_LOG_PATH,
                                       blob)
                    else:
                        try:
                            disk.delete(MINIO_META_BUCKET, MRF_LOG_PATH)
                        except serr.FileNotFound:
                            pass
                except Exception:
                    continue
            with self._mu:
                self._bytes = len(blob)

    # -- replay ---------------------------------------------------------

    def replay(self) -> list[tuple[str, str]]:
        """Union the per-disk journal files (boot). Populates the
        dedup set so the subsequent MRFQueue.add round does not
        re-append what is already durable."""
        found: dict[tuple[str, str], None] = {}
        max_bytes = 0
        for disk in self.disks:
            try:
                raw = disk.read_all(MINIO_META_BUCKET, MRF_LOG_PATH)
            except Exception:
                continue  # absent / unreadable disk: replay unions
            max_bytes = max(max_bytes, len(raw))
            for key in parse_journal(raw):
                found.setdefault(key)
        entries = list(found)
        with self._mu:
            fresh = [k for k in entries if k not in self._entries]
            self._entries.update(fresh)
            self._live_bytes += sum(len(_line(*k)) for k in fresh)
            self._bytes = max(self._bytes, max_bytes)
        if entries:
            self._publish()
        return entries
