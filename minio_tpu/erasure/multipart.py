"""Multipart uploads: each part independently erasure-coded, complete
stitches parts into one versioned object (ref cmd/erasure-multipart.go:
NewMultipartUpload:314, PutObjectPart:342, CompleteMultipartUpload:678).

On-disk (per disk, inside .minio.sys):
    mpu/<obj-hash>/<upload_id>/upload.json   upload session record
    mpu/<obj-hash>/<upload_id>/part.N        bitrot-wrapped shard of part N
    mpu/<obj-hash>/<upload_id>/part.N.json   part metadata (size, etag)

Complete moves the part shards into a fresh data dir and commits via the
same rename_data path as a single PUT; the object's FileInfo carries the
per-part sizes so ranged reads address (part, block) pairs.
"""

from __future__ import annotations

import hashlib
import json
import uuid

from ..faultinject import FAULTS
from ..parallel.quorum import (QuorumError, first_success, hash_order,
                               parallel_map, reduce_quorum_errs,
                               write_quorum)
from ..storage import errors as serr
from ..storage.metadata import (ErasureInfo, FileInfo, ObjectPartInfo,
                                new_data_dir, now)
from ..storage.xl import INTENT_FILE, MINIO_META_BUCKET, TMP_PATH
from . import bitrot

MPU_PATH = "mpu"
MIN_PART_SIZE = 5 * 1024 * 1024  # S3 minimum for all but the last part

# Crash points on multipart complete — the windows where a process
# death leaves the upload staged, half-linked, or committed-but-not-
# garbage-collected (tests/test_crash_consistency.py).
CRASH_MPU_PRE = FAULTS.register_crash_point(
    "engine.multipart.pre_commit")
CRASH_MPU_LINK = FAULTS.register_crash_point(
    "engine.multipart.mid_link")
CRASH_MPU_POST = FAULTS.register_crash_point(
    "engine.multipart.post_commit")


class UploadNotFound(Exception):
    pass


class InvalidPart(Exception):
    pass


class PartTooSmall(Exception):
    pass


def _upload_base(bucket: str, object_name: str, upload_id: str) -> str:
    h = hashlib.sha256(f"{bucket}/{object_name}".encode()).hexdigest()[:16]
    return f"{MPU_PATH}/{h}/{upload_id}"


def multipart_etag(part_etags: list[str]) -> str:
    """S3 multipart etag: md5 of concatenated binary part md5s + -N."""
    binmd5 = b"".join(bytes.fromhex(e) for e in part_etags)
    return f"{hashlib.md5(binmd5).hexdigest()}-{len(part_etags)}"


class MultipartUploads:
    """Multipart operations over an ErasureObjects engine."""

    def __init__(self, engine, min_part_size: int = MIN_PART_SIZE):
        self.engine = engine
        self.min_part_size = min_part_size

    # -- session ----------------------------------------------------------

    def new_multipart_upload(self, bucket: str, object_name: str,
                             metadata: dict | None = None) -> str:
        eng = self.engine
        eng._check_bucket(bucket)
        upload_id = uuid.uuid4().hex
        base = _upload_base(bucket, object_name, upload_id)
        record = json.dumps({
            "bucket": bucket, "object": object_name,
            "meta": dict(metadata or {}), "created": now(),
            "distribution": hash_order(f"{bucket}/{object_name}",
                                       len(eng.disks)),
        }).encode()
        _, errs = parallel_map(
            [lambda d=d: d.write_all(MINIO_META_BUCKET,
                                     f"{base}/upload.json", record)
             for d in eng.disks])
        reduce_quorum_errs(errs, write_quorum(eng.k, eng.m),
                           "new_multipart_upload")
        return upload_id

    def _load_upload(self, bucket: str, object_name: str,
                     upload_id: str) -> dict:
        """First-SUCCESS parallel probe for the upload record: all
        disks are asked at once and the first healthy answer wins —
        the old serial try/except walk paid a slow or dead disk's full
        timeout on EVERY part upload before the next disk was even
        asked, and a join-all fan-out would still wait for the
        slowest. Under pool saturation first_success degrades to the
        serial early-exit walk (never run-all); the n-1 discarded
        straggler reads are a few hundred bytes each, noise next to
        the n shard-append RPCs every part batch already fans out. A
        torn record (ValueError) propagates, as before."""
        base = _upload_base(bucket, object_name, upload_id)
        try:
            raw = first_success(
                [lambda d=d: d.read_all(MINIO_META_BUCKET,
                                        f"{base}/upload.json")
                 for d in self.engine.disks],
                swallow=serr.StorageError)
        except QuorumError:
            raise UploadNotFound(upload_id) from None
        return json.loads(raw)

    def get_upload_meta(self, bucket: str, object_name: str,
                        upload_id: str) -> dict:
        """The metadata captured at initiate time (SSE envelope,
        content-type, user meta — ref fs/erasure multipart meta)."""
        return dict(self._load_upload(bucket, object_name,
                                      upload_id).get("meta", {}))

    # -- parts ------------------------------------------------------------

    def put_object_part(self, bucket: str, object_name: str,
                        upload_id: str, part_number: int,
                        data,
                        actual_size: int | None = None) -> dict:
        """Streaming part write — the same pipelined data plane as a
        single PUT (engine._stream_shard_writes): batch N+1 is read and
        erasure-encoded (with the etag md5 overlapped) while batch N's
        shards fan out to disks, with the ec.encode / ec.write /
        ec.shard_write tracing spans PutObject already had (ref
        PutObjectPart block loop, cmd/erasure-multipart.go:342).
        `data` is bytes or a chunk reader; memory stays
        O(pipeline_depth × batch). actual_size: pre-transform
        (plaintext/uncompressed) length when the handler encrypted or
        compressed the part body."""
        from ..utils import streams
        eng = self.engine
        if not 1 <= part_number <= 10000:
            raise InvalidPart(f"part number {part_number}")
        up = self._load_upload(bucket, object_name, upload_id)
        dist = up["distribution"]
        base = _upload_base(bucket, object_name, upload_id)
        reader = streams.ensure_reader(data)
        n = len(eng.disks)
        wq = write_quorum(eng.k, eng.m)
        stage = f"{base}/part.{part_number}.{uuid.uuid4().hex}.stage"
        md5 = None if hasattr(reader, "etag") else hashlib.md5()
        alive = [True] * n
        disk_errs: list = [None] * n
        # Degraded write past quarantined drives (same policy as a
        # single PUT; the completed object's missing shards heal via
        # the engine's MRF requeue at complete time).
        eng._quarantine_skip(alive, disk_errs, wq)

        def cleanup(indices):
            parallel_map([
                lambda i=i: eng.disks[i].delete(MINIO_META_BUCKET, stage)
                for i in indices])

        def append_shard(i: int, payload, parent=None):
            if parent is None:  # untraced fast path
                eng.disks[i].append_file(MINIO_META_BUCKET, stage,
                                         payload)
                return
            from ..obs.span import TRACER
            with TRACER.span("ec.shard_write", parent=parent, disk=i,
                             endpoint=str(eng.disks[i]),
                             bytes=len(payload)):
                eng.disks[i].append_file(MINIO_META_BUCKET, stage,
                                         payload)

        def quorum_msg() -> str:
            return f"part write quorum lost ({sum(alive)}/{n})"

        try:
            total, _, _ = eng._stream_shard_writes(
                reader, eng.k, eng.m, eng.codec, dist, append_shard,
                alive, disk_errs, wq, quorum_msg, md5)
            if hasattr(reader, "verify"):
                reader.verify()

            etag = reader.etag() if md5 is None else md5.hexdigest()
            part_meta = json.dumps({
                "number": part_number, "size": total, "etag": etag,
                "actualSize": (actual_size if actual_size is not None
                               else total),
            }).encode()

            def commit_one(i: int):
                if not alive[i]:
                    raise disk_errs[i]
                disk = eng.disks[i]
                if total > 0:
                    disk.rename_file(MINIO_META_BUCKET, stage,
                                     MINIO_META_BUCKET,
                                     f"{base}/part.{part_number}")
                else:
                    # Zero-byte parts still get an (empty) shard file so
                    # the commit/verify/heal paths see every part.N.
                    disk.write_all(MINIO_META_BUCKET,
                                   f"{base}/part.{part_number}", b"")
                disk.write_all(MINIO_META_BUCKET,
                               f"{base}/part.{part_number}.json",
                               part_meta)

            _, errs = parallel_map(
                [lambda i=i: commit_one(i) for i in range(n)])
            reduce_quorum_errs(errs, wq, "put_object_part")
        except BaseException:
            cleanup(range(n))
            raise
        return {"number": part_number, "size": total, "etag": etag}

    def list_parts(self, bucket: str, object_name: str,
                   upload_id: str) -> list[dict]:
        """Union of part records across disks — a part missing on one
        disk (tolerated by write quorum) must still be listable."""
        self._load_upload(bucket, object_name, upload_id)
        base = _upload_base(bucket, object_name, upload_id)
        parts: dict[int, dict] = {}
        for disk in self.engine.disks:
            try:
                entries = disk.list_dir(MINIO_META_BUCKET, base)
            except serr.StorageError:
                continue
            for e in entries:
                if e.startswith("part.") and e.endswith(".json"):
                    try:
                        rec = json.loads(disk.read_all(
                            MINIO_META_BUCKET, f"{base}/{e}"))
                    except serr.StorageError:
                        continue
                    parts.setdefault(rec["number"], rec)
        return [parts[n] for n in sorted(parts)]

    def list_uploads(self, bucket: str,
                     prefix: str = "") -> list[dict]:
        """All in-progress uploads for a bucket (scan the mpu tree)."""
        eng = self.engine
        out = []
        seen = set()
        for disk in eng.disks:
            try:
                hashes = disk.list_dir(MINIO_META_BUCKET, MPU_PATH)
            except serr.StorageError:
                continue
            for h in hashes:
                if not h.endswith("/"):
                    continue
                try:
                    uploads = disk.list_dir(MINIO_META_BUCKET,
                                            f"{MPU_PATH}/{h}")
                except serr.StorageError:
                    continue
                for u in uploads:
                    u = u.rstrip("/")
                    if u in seen:
                        continue
                    try:
                        rec = json.loads(disk.read_all(
                            MINIO_META_BUCKET,
                            f"{MPU_PATH}/{h}{u}/upload.json"))
                    except serr.StorageError:
                        continue
                    if rec["bucket"] != bucket:
                        continue
                    if prefix and not rec["object"].startswith(prefix):
                        continue
                    seen.add(u)
                    out.append({"upload_id": u, "object": rec["object"],
                                "created": rec["created"]})
        return sorted(out, key=lambda x: (x["object"], x["upload_id"]))

    # -- complete / abort -------------------------------------------------

    def complete_multipart_upload(self, bucket: str, object_name: str,
                                  upload_id: str,
                                  parts: list[tuple[int, str]]):
        """parts: [(part_number, etag), ...] as sent by the client."""
        eng = self.engine
        up = self._load_upload(bucket, object_name, upload_id)
        dist = up["distribution"]
        base = _upload_base(bucket, object_name, upload_id)
        have = {p["number"]: p for p in self.list_parts(
            bucket, object_name, upload_id)}

        # Validate the client's part list (ref CompleteMultipartUpload
        # part checks).
        if not parts:
            raise InvalidPart("empty part list")
        last_idx = len(parts) - 1
        prev = 0
        part_infos: list[ObjectPartInfo] = []
        for idx, (num, etag) in enumerate(parts):
            if num <= prev:
                raise InvalidPart("parts not in ascending order")
            prev = num
            meta = have.get(num)
            if meta is None or meta["etag"].strip('"') != etag.strip('"'):
                raise InvalidPart(f"part {num}")
            # Size floor applies to the LOGICAL (pre-SSE/compression)
            # length — ciphertext expansion must not mask a too-small
            # part (ref globalMinPartSize check on actual size).
            logical = meta.get("actualSize", meta["size"])
            if idx != last_idx and logical < self.min_part_size:
                raise PartTooSmall(f"part {num}: {logical} bytes")
            part_infos.append(ObjectPartInfo(
                number=num, size=meta["size"],
                actual_size=meta.get("actualSize", meta["size"]),
                etag=meta["etag"]))

        total_size = sum(p.size for p in part_infos)
        total_actual = sum(p.actual_size for p in part_infos)
        etag = multipart_etag([p.etag for p in part_infos])
        data_dir = new_data_dir()
        mod_time = now()
        meta = dict(up.get("meta") or {})
        meta["etag"] = etag
        if total_actual != total_size:
            # Handler-transformed parts (SSE/compression): record the
            # logical object length (ref X-Minio-Internal-actual-size).
            meta["x-internal-actual-size"] = str(total_actual)
        wq = write_quorum(eng.k, eng.m)

        from .engine import _stage_intent_blob
        intent_blob = _stage_intent_blob(bucket, object_name, "",
                                         data_dir)

        def commit_one(i: int):
            disk = eng.disks[i]
            tmp_path = f"{TMP_PATH}/{uuid.uuid4()}"
            link = getattr(disk, "link_file", None)
            try:
                if total_size > 0:
                    # Recovery breadcrumb before the link/copy loop:
                    # a crash mid-commit leaves this stage dir for the
                    # boot sweep to map back to the object.
                    try:
                        disk.append_file(MINIO_META_BUCKET,
                                         f"{tmp_path}/{INTENT_FILE}",
                                         intent_blob)
                    except serr.StorageError:
                        pass
                # Stage this disk's part shards into the commit data
                # dir, KEEPING the client's part numbers (SSE derives
                # per-part keys from them, and ListParts reports them;
                # ref AWS part-number semantics). Not a rename: a
                # failed quorum must leave the upload intact so the
                # client can retry complete (cleanup happens only after
                # quorum success). Local disks HARD-LINK the immutable
                # shard files (zero bytes moved — the dominant cost of
                # complete for multi-GiB uploads); backends without
                # link support fall back to read+write copy.
                if total_size > 0:
                    for p in part_infos:
                        # Crash window: fires per part, so an `after`
                        # count lands the kill MID hard-link loop —
                        # some parts staged, some not, nothing
                        # visible.
                        FAULTS.crash_point(CRASH_MPU_LINK)
                        if link is not None:
                            try:
                                link(MINIO_META_BUCKET,
                                     f"{base}/part.{p.number}",
                                     MINIO_META_BUCKET,
                                     f"{tmp_path}/{data_dir}"
                                     f"/part.{p.number}")
                                continue
                            except serr.FileNotFound:
                                raise
                            except serr.StorageError:
                                # Filesystem without hard-link support
                                # (FAT, some NFS/overlay mounts): take
                                # the copy lane for the rest of this
                                # disk's parts.
                                link = None
                        shard = disk.read_all(MINIO_META_BUCKET,
                                              f"{base}/part.{p.number}")
                        disk.create_file(
                            MINIO_META_BUCKET,
                            f"{tmp_path}/{data_dir}/part.{p.number}",
                            shard)
                fi = FileInfo(
                    volume=bucket, name=object_name, version_id="",
                    data_dir=data_dir if total_size > 0 else "",
                    size=total_size, mod_time=mod_time, metadata=meta,
                    parts=list(part_infos),
                    erasure=ErasureInfo(
                        data_blocks=eng.k, parity_blocks=eng.m,
                        block_size=eng.block_size, index=dist[i],
                        distribution=list(dist),
                        checksums=[{"part": p.number,
                                    "algorithm": bitrot.DEFAULT_ALGORITHM,
                                    "hash": ""}
                                   for p in part_infos]),
                )
                if total_size > 0:
                    disk.rename_data(MINIO_META_BUCKET, tmp_path, fi,
                                     bucket, object_name)
                else:
                    disk.write_metadata(bucket, object_name, fi)
                return fi
            except BaseException:
                try:
                    disk.delete(MINIO_META_BUCKET, tmp_path,
                                recursive=True)
                except Exception:
                    pass
                raise

        # Crash window: upload validated, nothing staged into tmp yet
        # — a death here must leave the upload intact and retryable.
        FAULTS.crash_point(CRASH_MPU_PRE)
        # Exclusive commit against concurrent put/delete on the same key
        # (ref CompleteMultipartUpload NSLock, cmd/erasure-multipart.go).
        with eng.ns_lock.write_locked(bucket, object_name):
            _, errs = parallel_map(
                [lambda i=i: commit_one(i)
                 for i in range(len(eng.disks))])
            from .engine import BucketNotFound
            try:
                eng.guard_commit_bucket_gone(errs, bucket, object_name,
                                             "", wq=wq)
            except BucketNotFound:
                # Terminal failure: reclaim the staged parts too — the
                # client won't abort an upload of a bucket that no
                # longer exists.
                self._cleanup(bucket, object_name, upload_id)
                raise
            reduce_quorum_errs(errs, wq, "complete_multipart_upload")
        # Crash window: the object is quorum-committed but the upload
        # session (mpu dir) hasn't been reclaimed — a death here must
        # serve the completed object; the leftover upload stays
        # abortable/listable (ref stale-upload cleanup).
        FAULTS.crash_point(CRASH_MPU_POST)
        if any(e is not None for e in errs):
            eng.mrf.add(bucket, object_name)
        self._cleanup(bucket, object_name, upload_id)
        eng._mark_update(bucket, object_name)
        # Multipart complete is an overwrite of the key: invalidate
        # the hot-object cache (local + peer fan-out).
        from ..cache.hotcache import HOTCACHE
        HOTCACHE.invalidate(bucket, object_name)

        from .engine import ObjectInfo
        return ObjectInfo(bucket=bucket, name=object_name,
                          size=total_size, etag=etag, mod_time=mod_time,
                          metadata=meta, parts=part_infos)

    def abort_multipart_upload(self, bucket: str, object_name: str,
                               upload_id: str) -> None:
        self._load_upload(bucket, object_name, upload_id)
        self._cleanup(bucket, object_name, upload_id)

    def _cleanup(self, bucket: str, object_name: str,
                 upload_id: str) -> None:
        base = _upload_base(bucket, object_name, upload_id)
        parallel_map(
            [lambda d=d: d.delete(MINIO_META_BUCKET, base, recursive=True)
             for d in self.engine.disks])
