"""ErasureServerPools — the top-level ObjectLayer: N pools of erasure
sets; writes go to the pool with the most free space unless the object
already exists in another pool (ref cmd/erasure-server-pool.go:42 struct,
:215 getServerPoolsAvailableSpace, :593 PutObject, :524 GetObjectNInfo).
"""

from __future__ import annotations

from ..parallel.quorum import parallel_map
from .engine import (BucketExists, BucketNotFound, ObjectInfo,
                     ObjectNotFound)
from .sets import ErasureSets, fan_out_bucket_op


class ErasureServerPools:
    def __init__(self, pools: list[ErasureSets]):
        if not pools:
            raise ValueError("need at least one pool")
        self.pools = pools

    @property
    def k(self) -> int:
        """First pool's geometry (storage-class parity validation)."""
        return self.pools[0].k

    @property
    def m(self) -> int:
        return self.pools[0].m

    def shutdown(self) -> None:
        """Stop every pool's background daemons (see
        ErasureObjects.shutdown)."""
        for p in self.pools:
            p.shutdown()

    # -- placement ------------------------------------------------------

    def _pool_free_space(self, pool: ErasureSets) -> int:
        total = 0
        for s in pool.sets:
            for d in s.disks:
                try:
                    total += d.disk_info()["free"]
                except Exception:
                    pass
        return total

    def _pool_with_object(self, bucket: str, object_name: str,
                          ) -> int | None:
        """Any-version probe (a delete marker as latest still pins the
        key to its pool); only a definitive not-found means 'not here' —
        quorum/I/O errors abort placement rather than risking a write
        landing in a second pool and later serving stale data."""
        for i, pool in enumerate(self.pools):
            try:
                if pool.object_exists(bucket, object_name):
                    return i
            except BucketNotFound:
                continue
        return None

    def _put_pool_index(self, bucket: str, object_name: str) -> int:
        if len(self.pools) == 1:
            return 0
        existing = self._pool_with_object(bucket, object_name)
        if existing is not None:
            return existing
        frees = [self._pool_free_space(p) for p in self.pools]
        return max(range(len(frees)), key=lambda i: frees[i])

    # -- buckets --------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        fan_out_bucket_op(self.pools, "make_bucket", BucketExists, bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        fan_out_bucket_op(self.pools, "delete_bucket", BucketNotFound,
                          bucket, force=force)

    def list_buckets(self) -> list[dict]:
        return self.pools[0].list_buckets()

    def bucket_exists(self, bucket: str) -> bool:
        return self.pools[0].bucket_exists(bucket)

    # -- objects --------------------------------------------------------

    supports_streaming_put = True

    def put_object(self, bucket: str, object_name: str, data,
                   metadata: dict | None = None,
                   versioned: bool = False,
                   parity_shards: int | None = None,
                   algorithm: str | None = None) -> ObjectInfo:
        idx = self._put_pool_index(bucket, object_name)
        return self.pools[idx].put_object(bucket, object_name, data,
                                          metadata=metadata,
                                          versioned=versioned,
                                          parity_shards=parity_shards,
                                          algorithm=algorithm)

    def _probe(self, bucket: str, object_name: str, op):
        """Try each pool in order; first hit wins (ref pool probe loop,
        cmd/erasure-server-pool.go:569-593)."""
        last: Exception = ObjectNotFound(f"{bucket}/{object_name}")
        for pool in self.pools:
            try:
                return op(pool)
            except ObjectNotFound as e:
                last = e
            except BucketNotFound as e:
                last = e
        raise last

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1, version_id: str = ""):
        return self._probe(bucket, object_name,
                           lambda p: p.get_object(
                               bucket, object_name, offset=offset,
                               length=length, version_id=version_id))

    def get_object_stream(self, bucket: str, object_name: str,
                          offset: int = 0, length: int = -1,
                          version_id: str = ""):
        return self._probe(bucket, object_name,
                           lambda p: p.get_object_stream(
                               bucket, object_name, offset=offset,
                               length=length, version_id=version_id))

    def get_object_info(self, bucket: str, object_name: str,
                        version_id: str = "") -> ObjectInfo:
        return self._probe(bucket, object_name,
                           lambda p: p.get_object_info(
                               bucket, object_name, version_id))

    def delete_object(self, bucket: str, object_name: str,
                      version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        """Delete in the pool that HOLDS the key (a versioned delete
        must write its marker next to the existing versions, not into
        whichever pool answers first; ref DeleteObject pool routing,
        cmd/erasure-server-pool.go). A versioned delete of a key that
        exists nowhere still writes a marker — into the put-placement
        pool, per S3 semantics."""
        idx = self._pool_with_object(bucket, object_name)
        if idx is None:
            if versioned and not version_id:
                idx = self._put_pool_index(bucket, object_name)
            else:
                if not self.pools[0].bucket_exists(bucket):
                    raise BucketNotFound(bucket)
                raise ObjectNotFound(f"{bucket}/{object_name}")
        return self.pools[idx].delete_object(bucket, object_name,
                                             version_id,
                                             versioned=versioned)

    def object_exists(self, bucket: str, object_name: str) -> bool:
        return self._pool_with_object(bucket, object_name) is not None

    def put_object_tags(self, bucket: str, object_name: str, tags: str,
                        version_id: str = "") -> None:
        return self._probe(bucket, object_name,
                           lambda p: p.put_object_tags(
                               bucket, object_name, tags, version_id))

    def update_object_metadata(self, bucket: str, object_name: str,
                               updates: dict, version_id: str = "") -> None:
        return self._probe(bucket, object_name,
                           lambda p: p.update_object_metadata(
                               bucket, object_name, updates, version_id))

    def list_object_versions(self, bucket: str, prefix: str = "",
                             max_keys: int = 1000,
                             marker: str = "") -> list[ObjectInfo]:
        per_pool, _ = parallel_map(
            [lambda p=p: p.list_object_versions(bucket, prefix=prefix,
                                                max_keys=max_keys,
                                                marker=marker)
             for p in self.pools])
        merged: list[ObjectInfo] = []
        seen: set[tuple] = set()
        for lst in per_pool:
            for o in lst or []:
                key = (o.name, o.version_id)
                if key not in seen:
                    seen.add(key)
                    merged.append(o)
        merged.sort(key=lambda o: (o.name, -o.mod_time, o.version_id))
        return merged[:max_keys]

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000,
                     marker: str = "") -> list[ObjectInfo]:
        per_pool, _ = parallel_map(
            [lambda p=p: p.list_objects(bucket, prefix=prefix,
                                        max_keys=max_keys, marker=marker)
             for p in self.pools])
        merged: list[ObjectInfo] = []
        seen: set[str] = set()
        for lst in per_pool:
            for o in lst or []:
                if o.name not in seen:
                    seen.add(o.name)
                    merged.append(o)
        merged.sort(key=lambda o: o.name)
        return merged[:max_keys]

    # -- multipart ------------------------------------------------------

    @property
    def multipart(self):
        return _PoolsMultipart(self)

    @property
    def healer(self):
        return _PoolsHealer(self)


class _PoolsMultipart:
    def __init__(self, pools: ErasureServerPools):
        self._pools = pools

    def _pool_for_upload(self, bucket, object_name, upload_id):
        from .multipart import UploadNotFound
        for pool in self._pools.pools:
            try:
                # Cheap existence probe of the upload record only.
                pool.set_for(object_name).multipart._load_upload(
                    bucket, object_name, upload_id)
                return pool
            except UploadNotFound:
                continue
        raise UploadNotFound(upload_id)

    def new_multipart_upload(self, bucket, object_name, metadata=None):
        idx = self._pools._put_pool_index(bucket, object_name)
        return self._pools.pools[idx].multipart.new_multipart_upload(
            bucket, object_name, metadata)

    def put_object_part(self, bucket, object_name, upload_id,
                        part_number, data, actual_size=None):
        pool = self._pool_for_upload(bucket, object_name, upload_id)
        return pool.multipart.put_object_part(
            bucket, object_name, upload_id, part_number, data,
            actual_size=actual_size)

    def get_upload_meta(self, bucket, object_name, upload_id):
        pool = self._pool_for_upload(bucket, object_name, upload_id)
        return pool.multipart.get_upload_meta(bucket, object_name,
                                              upload_id)

    def list_parts(self, bucket, object_name, upload_id):
        pool = self._pool_for_upload(bucket, object_name, upload_id)
        return pool.multipart.list_parts(bucket, object_name, upload_id)

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts):
        pool = self._pool_for_upload(bucket, object_name, upload_id)
        return pool.multipart.complete_multipart_upload(
            bucket, object_name, upload_id, parts)

    def abort_multipart_upload(self, bucket, object_name, upload_id):
        pool = self._pool_for_upload(bucket, object_name, upload_id)
        return pool.multipart.abort_multipart_upload(
            bucket, object_name, upload_id)

    def list_uploads(self, bucket, prefix=""):
        out = []
        for pool in self._pools.pools:
            out.extend(pool.multipart.list_uploads(bucket, prefix))
        return sorted(out, key=lambda x: (x["object"], x["upload_id"]))


class _PoolsHealer:
    def __init__(self, pools: ErasureServerPools):
        self._pools = pools

    def heal_object(self, bucket, object_name, dry_run=False):
        return self._pools._probe(
            bucket, object_name,
            lambda p: p.healer.heal_object(bucket, object_name,
                                           dry_run=dry_run))

    def heal_object_or_queue(self, bucket, object_name, dry_run=False):
        return self._pools._probe(
            bucket, object_name,
            lambda p: p.healer.heal_object_or_queue(
                bucket, object_name, dry_run=dry_run))

    def heal_bucket(self, bucket):
        out = []
        for pool in self._pools.pools:
            out.extend(pool.healer.heal_bucket(bucket))
        return out

    def heal_all(self):
        out = []
        for pool in self._pools.pools:
            out.extend(pool.healer.heal_all())
        return out
