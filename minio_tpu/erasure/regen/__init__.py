"""Regenerating-code storage class (REGEN): minimum-bandwidth repair.

The codec (`RegenErasure`) mirrors the `Erasure` seams the engine
consumes (shard sizes, batched encode, batched whole-block decode) over
the repair-by-transfer product-matrix MBR construction in
ops/rs_regen.py; `repair` holds the heal-side collector that rebuilds a
lost shard from one stored stripe symbol per helper (the
`repair_project` storage RPC) instead of k full shard reads.
"""

from .codec import RegenErasure  # noqa: F401
from .repair import regen_heal_groups  # noqa: F401
