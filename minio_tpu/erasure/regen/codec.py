"""RegenErasure: the REGEN storage class codec.

Size semantics (the layout contract shared with ops/rs_regen.py,
storage/metadata.ErasureInfo.shard_size and repair.py): a block of L
bytes carries nst = ceil(L / B) stripes; every node stores alpha = d
symbol rows of nst bytes each, flattened row-major, so a node's chunk
for the block is d * nst bytes and stored row r sits contiguous at
byte offset r * nst inside it.  All n node chunks are the same size —
regen shards have no data/parity asymmetry (the code is
non-systematic: every GET decodes).

Dispatch rides the measured lanes exactly like `Erasure`: the batched
GF apply goes to Pallas/XLA (rs_tpu.gf_apply) or native/numpy
(batching.host_apply_tagged) per the autotune plan for the
``regen_code`` kernel; pins ("cpu"/"tpu") bypass the plan.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ...ops import rs_regen
from ...ops.autotune import AUTOTUNE, REGEN_CODE
from ..codec import BLOCK_SIZE


@dataclass
class RegenErasure:
    data_blocks: int
    parity_blocks: int
    block_size: int = BLOCK_SIZE
    backend: str = "auto"  # "auto" | "cpu" | "tpu"
    # Home device of the owning erasure set (parallel/mesh.py).
    affinity: int | None = field(default=None, repr=False)

    # Dispatch seam for the engine: Erasure instances answer False via
    # getattr default, so every regen branch is one attribute probe.
    is_regen = True

    def __post_init__(self):
        # geometry() validates k > 0, m > 0, n <= 255
        rs_regen.geometry(self.data_blocks, self.parity_blocks)

    # --- sizes ---------------------------------------------------------

    @property
    def g(self) -> rs_regen.RegenGeometry:
        return rs_regen.geometry(self.data_blocks, self.parity_blocks)

    @property
    def total_shards(self) -> int:
        return self.g.n

    def stripe_count(self, length: int) -> int:
        return rs_regen.stripe_count(self.data_blocks,
                                     self.parity_blocks, length)

    def chunk_size(self, block_len: int) -> int:
        """Per-node stored bytes for a block of block_len bytes."""
        return self.g.d * self.stripe_count(block_len)

    def shard_size(self) -> int:
        """Per-node size of a full block (the bitrot framing unit)."""
        return self.chunk_size(self.block_size)

    def shard_file_size(self, total_length: int) -> int:
        if total_length == 0:
            return 0
        if total_length < 0:
            return -1
        n_full = total_length // self.block_size
        tail = total_length % self.block_size
        return n_full * self.shard_size() + self.chunk_size(tail)

    def shard_file_offset(self, start_offset: int, length: int,
                          total_length: int) -> int:
        shard_size = self.shard_size()
        end_shard = (start_offset + length) // self.block_size
        till = end_shard * shard_size + shard_size
        return min(till, self.shard_file_size(total_length))

    # --- dispatch ------------------------------------------------------

    def _use_tpu(self, nbytes: int) -> bool:
        if self.backend == "cpu":
            return False
        if self.backend == "tpu":
            return True
        return AUTOTUNE.use_jit_lane(REGEN_CODE, nbytes)

    def _apply(self, mat: np.ndarray, cols: np.ndarray,
               bitplane: np.ndarray | None, blocks: int) -> np.ndarray:
        return rs_regen.apply_regen(
            mat, cols, use_device=self._use_tpu, bitplane=bitplane,
            affinity=self.affinity, blocks=blocks,
            device_fallback=self.backend != "tpu")

    # --- encode --------------------------------------------------------

    def encode_data(self, data: bytes | np.ndarray) -> np.ndarray:
        """Encode one (possibly short) block: (n, chunk) uint8 — node
        i's stored chunk is row i."""
        k, m = self.data_blocks, self.parity_blocks
        g = self.g
        buf = np.frombuffer(data, dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)) else np.asarray(
                data, dtype=np.uint8)
        if buf.size == 0:
            return np.zeros((g.n, 0), dtype=np.uint8)
        W = rs_regen.pack_block(k, m, buf)
        flat = self._apply(rs_regen.encode_matrix_regen(k, m), W,
                           rs_regen.encode_bitplane(k, m), blocks=1)
        # (n*d, nst) row-major -> node i's d rows are contiguous
        return np.ascontiguousarray(flat.reshape(g.n, g.d * W.shape[1]))

    def encode_blocks_batch_bytes(self, blocks: np.ndarray) -> np.ndarray:
        """Batched encode of (nblk, block_size) raw block bytes ->
        shard-major (n, nblk, shard_size) uint8 (the layout the bitrot
        framer wants, mirroring encode_blocks_batch_shardmajor)."""
        k, m = self.data_blocks, self.parity_blocks
        g = self.g
        nblk, L = blocks.shape
        nst = self.stripe_count(L)
        cols = rs_regen.pack_blocks_batch(k, m, blocks)
        flat = self._apply(rs_regen.encode_matrix_regen(k, m), cols,
                           rs_regen.encode_bitplane(k, m), blocks=nblk)
        out = flat.reshape(g.n, g.d, nblk, nst).transpose(0, 2, 1, 3)
        return np.ascontiguousarray(out.reshape(g.n, nblk, g.d * nst))

    # --- decode --------------------------------------------------------

    def _solve_w_groups(self, blocks: list, lens: list[int]):
        """Group blocks by (node set, stripe count) and solve each
        group's message stripes in one batched apply.

        blocks: per block, a length-n list of chunk arrays (d*nst
        bytes) with None for missing nodes.  Yields (idxs, nst, W)
        with W (B, len(idxs)*nst)."""
        k, m = self.data_blocks, self.parity_blocks
        g = self.g
        groups: dict[tuple, list[int]] = {}
        for bi, (shards, L) in enumerate(zip(blocks, lens)):
            avail = tuple(j for j, s in enumerate(shards)
                          if s is not None)
            if len(avail) < k:
                from ...ops.batching import ReconstructError
                raise ReconstructError(
                    f"regen block {bi}: only {len(avail)}/{k} chunks")
            nodes = avail[:k]
            groups.setdefault((nodes, self.stripe_count(L)),
                              []).append(bi)
        for (nodes, nst), idxs in groups.items():
            picks, inv = rs_regen.decode_plan(k, m, nodes)
            sel = np.empty((g.B, len(idxs) * nst), dtype=np.uint8)
            for gi, bi in enumerate(idxs):
                for pi, (node, row) in enumerate(picks):
                    chunk = np.asarray(blocks[bi][node], dtype=np.uint8)
                    sel[pi, gi * nst:(gi + 1) * nst] = \
                        chunk[row * nst:(row + 1) * nst]
            W = self._apply(inv, sel,
                            rs_regen.decode_bitplane(k, m, nodes),
                            blocks=len(idxs))
            yield idxs, nst, W

    def decode_blocks_batch(self, blocks: list,
                            lens: list[int]) -> list[bytes]:
        """Whole-block decode (the GET path — regen is non-systematic,
        so every read decodes): per block a length-n chunk list with
        None for unavailable nodes, plus the block's plain length.
        Any k chunks suffice; mask-grouped into batched dispatches."""
        out: list[bytes | None] = [None] * len(blocks)
        for idxs, nst, W in self._solve_w_groups(blocks, lens):
            for gi, bi in enumerate(idxs):
                out[bi] = rs_regen.unpack_block(
                    W[:, gi * nst:(gi + 1) * nst], lens[bi])
        return out

    def reencode_missing_batch(self, blocks: list, lens: list[int],
                               missing: list[int],
                               ) -> list[dict[int, bytes]]:
        """Conventional repair fallback: solve the message stripes from
        any k chunks, then re-encode the missing nodes' chunks — one
        extra batched apply per group over the stacked missing-node
        generators."""
        k, m = self.data_blocks, self.parity_blocks
        g = self.g
        G = rs_regen.node_generators(k, m)
        mat = np.ascontiguousarray(
            np.concatenate([G[f] for f in missing], axis=0))
        out: list[dict[int, bytes] | None] = [None] * len(blocks)
        for idxs, nst, W in self._solve_w_groups(blocks, lens):
            rebuilt = self._apply(mat, W, None, blocks=len(idxs))
            for gi, bi in enumerate(idxs):
                per: dict[int, bytes] = {}
                for fi_, f in enumerate(missing):
                    rows = rebuilt[fi_ * g.d:(fi_ + 1) * g.d,
                                   gi * nst:(gi + 1) * nst]
                    per[f] = np.ascontiguousarray(rows).tobytes()
                out[bi] = per
        return out
