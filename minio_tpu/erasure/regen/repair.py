"""Minimum-bandwidth heal collection for REGEN objects, plus the
repair-traffic ledger both heal paths (RS and regen) report into.

Repair-by-transfer collection: to rebuild the one lost node f, each of
the d = n-1 helpers ships exactly ONE stored stripe row per block — a
ranged read via the `repair_project` storage RPC (so in a distributed
set only the d * nst projection bytes cross the wire, never the
helper's full chunk).  The shipped rows ARE the lost node's chunk rows
verbatim (ops/rs_regen.repair_rows), so assembly is a permutation, not
math.  Per repaired block this moves d * ceil(block/B) bytes of disk
AND network traffic versus the ~k * ceil(block/k) ≈ block bytes the
conventional k-shard read pays — the ≥2x reduction the regen_repair
bench measures (4+2: ~2.8x).

Fallback ladder (never torn, always byte-exact): any helper shortfall
— a second missing shard, an unreachable helper, a short projection —
drops that part's remaining groups to the conventional path: read any
k full chunks, solve the message stripes, re-encode the lost nodes
(RegenErasure.reencode_missing_batch).  Both paths emit identical
group frames, so a mid-part downgrade resumes seamlessly.  Fewer than
k readable chunks raises RegenRepairFailed (storage/errors.py).

Bitrot note: projection reads are ranged reads INSIDE a bitrot frame,
so they cannot be frame-verified here — corrupt disks were already
excluded by heal's classification pass, the rebuilt shard gets fresh
frames at write-back, and silent helper rot is the deep scrub's job
(the same trust window the reference's ranged shard reads live with).
"""

from __future__ import annotations

import threading

import numpy as np

from ...storage import errors as serr
from ...utils import ceil_frac
from .. import bitrot


class _RepairBytesLedger:
    """Process-wide repair-traffic counters: bytes helpers read from
    media (src=disk) and bytes shipped in helper responses (src=net),
    split by repair mode (rs | regen).  Mirrored into metrics2
    (`minio_tpu_v2_heal_repair_bytes_total`) and snapshotted by the
    admin /recovery report — the observable form of the 2x claim."""

    def __init__(self):
        self._mu = threading.Lock()
        self._counts: dict[tuple[str, str], int] = {}

    def add(self, mode: str, src: str, nbytes: int) -> None:
        if nbytes <= 0:
            return
        from ...obs.metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_heal_repair_bytes_total",
                     {"mode": mode, "src": src}, nbytes)
        with self._mu:
            key = (mode, src)
            self._counts[key] = self._counts.get(key, 0) + nbytes

    def snapshot(self) -> dict:
        with self._mu:
            out: dict[str, dict[str, int]] = {}
            for (mode, src), v in sorted(self._counts.items()):
                out.setdefault(mode, {})[src] = v
            return out

    def reset(self) -> None:
        with self._mu:
            self._counts.clear()


REPAIR_BYTES = _RepairBytesLedger()


def regen_heal_groups(eng, bucket: str, object_name: str, fi, codec,
                      parts, missing_shards: list[int],
                      shard_of_disk: dict[int, int],
                      read_order: list[int], part_algo,
                      group_budget: int):
    """Yield (part_number, {shard: framed bytes}) per block group for a
    REGEN object — the regen counterpart of heal.py's produce_groups,
    consumed by the same write-back pipeline (so crash points, intent
    staging and commit are shared with the RS path)."""
    k, m = codec.data_blocks, codec.parity_blocks
    g = codec.g
    shard_size = codec.shard_size()
    block_size = fi.erasure.block_size
    # Healthiest disk per shard (read_order is already health-ranked).
    disk_of_shard: dict[int, int] = {}
    for i in read_order:
        disk_of_shard.setdefault(shard_of_disk[i], i)
    fast = (len(missing_shards) == 1
            and all(j in disk_of_shard for j in range(g.n)
                    if j != missing_shards[0]))
    from ...ops.rs_regen import repair_rows
    plan = (repair_rows(k, m, missing_shards[0]) if fast else None)

    for part in parts:
        algo = part_algo(part)
        hsz = bitrot.hash_size(algo) if bitrot.is_streaming(algo) else 0
        rel = f"{object_name}/{fi.data_dir}/part.{part.number}"
        n_blocks = ceil_frac(part.size, block_size)
        if n_blocks == 0:
            yield part.number, {j: b"" for j in missing_shards}
            continue
        group = max(1, group_budget // max(block_size, 1))
        part_fast = fast
        streams: dict[int, bytes] | None = None  # fallback full reads
        for b0 in range(0, n_blocks, group):
            metas = []
            for b in range(b0, min(b0 + group, n_blocks)):
                blk_len = min(block_size, part.size - b * block_size)
                metas.append((b, blk_len, codec.stripe_count(blk_len)))
            frames = None
            if part_fast:
                try:
                    frames = _collect_group_rbt(
                        eng, bucket, rel, metas, plan, disk_of_shard,
                        missing_shards[0], g, hsz, shard_size, algo)
                except serr.StorageError as exc:
                    # One flapping helper must not fail the heal: the
                    # rest of this part downgrades to the conventional
                    # any-k path (identical frames, seamless resume).
                    import logging
                    logging.getLogger("minio_tpu.heal").warning(
                        "regen min-bandwidth repair of %s/%s part %d "
                        "fell back to k-chunk decode: %r", bucket,
                        object_name, part.number, exc)
                    part_fast = False
            if frames is None:
                if streams is None:
                    streams = _read_fallback_streams(
                        eng, bucket, rel, read_order, shard_of_disk, k)
                frames = _rebuild_group_conventional(
                    codec, streams, metas, missing_shards, hsz,
                    shard_size, algo)
            yield part.number, frames


def _collect_group_rbt(eng, bucket: str, rel: str, metas, plan,
                       disk_of_shard: dict[int, int], f: int, g,
                       hsz: int, shard_size: int, algo: str,
                       ) -> dict[int, bytes]:
    """One group's lost-node frames via repair-by-transfer: one stored
    row per helper per block, fetched as a single ranged-read RPC per
    helper covering the whole group."""
    rows_by_dest: dict[int, list[bytes]] = {}
    for helper, helper_row, dest_row in plan:
        disk = eng.disks[disk_of_shard[helper]]
        ranges = []
        for b, _blk_len, nst in metas:
            # Block b's data starts after b full framed blocks (only
            # the part-final block is short, and it is never BEFORE
            # another block); stored row r is contiguous at r * nst.
            off = b * (hsz + shard_size) + hsz + helper_row * nst
            ranges.append((off, nst))
        data = disk.repair_project(bucket, rel, ranges)
        expect = sum(nst for _b, _bl, nst in metas)
        if len(data) != expect:
            raise serr.FaultyDisk(
                f"repair_project shard {helper}: got {len(data)} "
                f"bytes, want {expect}")
        REPAIR_BYTES.add("regen", "disk", len(data))
        REPAIR_BYTES.add("regen", "net", len(data))
        pieces, off = [], 0
        for _b, _bl, nst in metas:
            pieces.append(bytes(data[off:off + nst]))
            off += nst
        rows_by_dest[dest_row] = pieces
    acc = bytearray()
    for bi in range(len(metas)):
        for r in range(g.d):
            acc += rows_by_dest[r][bi]
    return {f: bitrot.encode_stream(bytes(acc), shard_size, algo)}


def _read_fallback_streams(eng, bucket: str, rel: str,
                           read_order: list[int],
                           shard_of_disk: dict[int, int],
                           k: int) -> dict[int, bytes]:
    """Conventional path survivor reads: any k full chunk streams,
    healthiest first (counted against the regen repair ledger — the
    fallback's cost must show in the same counters the 2x claim uses)."""
    streams: dict[int, bytes] = {}
    for i in read_order:
        if len(streams) == k:
            break
        j = shard_of_disk[i]
        if j in streams:
            continue
        try:
            data = eng.disks[i].read_all(bucket, rel)
        except serr.StorageError:
            continue
        REPAIR_BYTES.add("regen", "disk", len(data))
        REPAIR_BYTES.add("regen", "net", len(data))
        streams[j] = data
    if len(streams) < k:
        raise serr.RegenRepairFailed(
            f"regen heal {bucket}/{rel}: only {len(streams)}/{k} "
            "survivor chunks readable")
    return streams


def _rebuild_group_conventional(codec, streams: dict[int, bytes],
                                metas, missing_shards: list[int],
                                hsz: int, shard_size: int, algo: str,
                                ) -> dict[int, bytes]:
    """One group's frames via any-k decode + re-encode of the lost
    nodes (RegenErasure.reencode_missing_batch, batched per group)."""
    g = codec.g
    blocks, lens = [], []
    for b, blk_len, nst in metas:
        chunk = g.d * nst
        shards: list[np.ndarray | None] = [None] * g.n
        for j, stream in streams.items():
            data = bitrot.extract_block(stream, b, chunk, shard_size,
                                        algo)
            shards[j] = np.frombuffer(data, dtype=np.uint8)
        blocks.append(shards)
        lens.append(blk_len)
    acc = {j: bytearray() for j in missing_shards}
    for per in codec.reencode_missing_batch(blocks, lens,
                                            missing_shards):
        for j in missing_shards:
            acc[j] += per[j]
    return {j: bitrot.encode_stream(bytes(acc[j]), shard_size, algo)
            for j in missing_shards}
