"""ErasureSets — one pool as M independent erasure sets of K drives,
objects placed by SipHash of the name keyed by deployment id
(ref cmd/erasure-sets.go:54 struct, :623 sipHashMod, :658 getHashedSet).

Sets never talk to each other: every object lives entirely inside the
set its name hashes to; bucket operations fan out to all sets.
"""

from __future__ import annotations

import uuid as uuidlib

from ..parallel.quorum import parallel_map
from ..storage.interface import StorageAPI
from ..utils.siphash import sip_hash_mod
from .codec import BLOCK_SIZE
from .engine import (BucketExists, BucketNotFound, ErasureObjects,
                     ObjectInfo, ObjectNotFound)


def fan_out_bucket_op(targets: list, op_name: str, benign: type,
                      *args, **kwargs) -> None:
    """Run a bucket op on every target; a `benign` error (exists /
    not-found) only surfaces when unanimous, any other error surfaces
    immediately. Shared by sets and pools fan-out."""
    _, errs = parallel_map(
        [lambda t=t: getattr(t, op_name)(*args, **kwargs)
         for t in targets])
    real = [e for e in errs if e is not None
            and not isinstance(e, benign)]
    if real:
        raise real[0]
    if errs and all(isinstance(e, benign) for e in errs):
        raise errs[0]


class ErasureSets:
    def __init__(self, disks: list[StorageAPI], sets_layout: list[int],
                 deployment_id: str,
                 data_shards: int | None = None,
                 parity_shards: int | None = None,
                 block_size: int = BLOCK_SIZE):
        """sets_layout: e.g. [6, 6] = two sets of six drives; `disks`
        is flat, format-ordered (storage.format.init_or_load_formats)."""
        assert sum(sets_layout) == len(disks)
        self.deployment_id = deployment_id
        self._dep_key = uuidlib.UUID(deployment_id).bytes
        self.sets: list[ErasureObjects] = []
        off = 0
        for size in sets_layout:
            self.sets.append(ErasureObjects(
                disks[off:off + size], data_shards, parity_shards,
                block_size=block_size))
            off += size

    # -- placement ------------------------------------------------------

    def set_index(self, object_name: str) -> int:
        return sip_hash_mod(object_name, len(self.sets), self._dep_key)

    def set_for(self, object_name: str) -> ErasureObjects:
        return self.sets[self.set_index(object_name)]

    def shutdown(self) -> None:
        """Stop every set's background daemons (see
        ErasureObjects.shutdown)."""
        for s in self.sets:
            s.shutdown()

    # -- buckets (fan out to every set) ---------------------------------

    def make_bucket(self, bucket: str) -> None:
        fan_out_bucket_op(self.sets, "make_bucket", BucketExists, bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        fan_out_bucket_op(self.sets, "delete_bucket", BucketNotFound,
                          bucket, force=force)

    def list_buckets(self) -> list[dict]:
        return self.sets[0].list_buckets()

    def bucket_exists(self, bucket: str) -> bool:
        return self.sets[0].bucket_exists(bucket)

    # -- objects (dispatch to the hashed set) ---------------------------

    @property
    def k(self) -> int:
        """Set geometry (uniform across sets; ref formatErasureV3)."""
        return self.sets[0].k

    @property
    def m(self) -> int:
        return self.sets[0].m

    supports_streaming_put = True

    def put_object(self, bucket: str, object_name: str, data: bytes,
                   metadata: dict | None = None,
                   versioned: bool = False,
                   parity_shards: int | None = None,
                   algorithm: str | None = None) -> ObjectInfo:
        return self.set_for(object_name).put_object(
            bucket, object_name, data, metadata=metadata,
            versioned=versioned, parity_shards=parity_shards,
            algorithm=algorithm)

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1, version_id: str = ""):
        return self.set_for(object_name).get_object(
            bucket, object_name, offset=offset, length=length,
            version_id=version_id)

    def get_object_stream(self, bucket: str, object_name: str,
                          offset: int = 0, length: int = -1,
                          version_id: str = ""):
        return self.set_for(object_name).get_object_stream(
            bucket, object_name, offset=offset, length=length,
            version_id=version_id)

    def get_object_info(self, bucket: str, object_name: str,
                        version_id: str = "") -> ObjectInfo:
        return self.set_for(object_name).get_object_info(
            bucket, object_name, version_id)

    def delete_object(self, bucket: str, object_name: str,
                      version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        return self.set_for(object_name).delete_object(
            bucket, object_name, version_id, versioned=versioned)

    def object_exists(self, bucket: str, object_name: str) -> bool:
        return self.set_for(object_name).object_exists(bucket, object_name)

    def put_object_tags(self, bucket: str, object_name: str, tags: str,
                        version_id: str = "") -> None:
        return self.set_for(object_name).put_object_tags(
            bucket, object_name, tags, version_id)

    def update_object_metadata(self, bucket: str, object_name: str,
                               updates: dict, version_id: str = "") -> None:
        return self.set_for(object_name).update_object_metadata(
            bucket, object_name, updates, version_id)

    def list_object_versions(self, bucket: str, prefix: str = "",
                             max_keys: int = 1000,
                             marker: str = "") -> list[ObjectInfo]:
        per_set, _ = parallel_map(
            [lambda s=s: s.list_object_versions(bucket, prefix=prefix,
                                                max_keys=max_keys,
                                                marker=marker)
             for s in self.sets])
        merged: list[ObjectInfo] = []
        for lst in per_set:
            if lst:
                merged.extend(lst)
        merged.sort(key=lambda o: (o.name, -o.mod_time, o.version_id))
        return merged[:max_keys]

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000,
                     marker: str = "") -> list[ObjectInfo]:
        """Merge sorted per-set listings."""
        per_set, _ = parallel_map(
            [lambda s=s: s.list_objects(bucket, prefix=prefix,
                                        max_keys=max_keys, marker=marker)
             for s in self.sets])
        merged: list[ObjectInfo] = []
        for lst in per_set:
            if lst:
                merged.extend(lst)
        merged.sort(key=lambda o: o.name)
        return merged[:max_keys]

    # -- multipart (dispatch by object name) ----------------------------

    @property
    def multipart(self):
        return _SetsMultipart(self)

    # -- heal -----------------------------------------------------------

    @property
    def healer(self):
        return _SetsHealer(self)

class _SetsMultipart:
    def __init__(self, sets: ErasureSets):
        self._sets = sets

    def __getattr__(self, name):
        sets = self._sets

        def dispatch(bucket, object_name, *a, **kw):
            return getattr(sets.set_for(object_name).multipart, name)(
                bucket, object_name, *a, **kw)

        if name in ("new_multipart_upload", "put_object_part",
                    "list_parts", "complete_multipart_upload",
                    "abort_multipart_upload", "get_upload_meta"):
            return dispatch
        if name == "list_uploads":
            def list_uploads(bucket, prefix=""):
                out = []
                for s in sets.sets:
                    out.extend(s.multipart.list_uploads(bucket, prefix))
                return sorted(out, key=lambda x: (x["object"],
                                                  x["upload_id"]))
            return list_uploads
        if name == "min_part_size":
            return sets.sets[0].multipart.min_part_size
        raise AttributeError(name)


class _SetsHealer:
    def __init__(self, sets: ErasureSets):
        self._sets = sets

    def heal_object(self, bucket: str, object_name: str,
                    dry_run: bool = False):
        return self._sets.set_for(object_name).healer.heal_object(
            bucket, object_name, dry_run=dry_run)

    def heal_object_or_queue(self, bucket: str, object_name: str,
                             dry_run: bool = False):
        return self._sets.set_for(object_name).healer \
            .heal_object_or_queue(bucket, object_name, dry_run=dry_run)

    def heal_bucket(self, bucket: str) -> list[int]:
        healed = []
        for s in self._sets.sets:
            healed.extend(s.healer.heal_bucket(bucket))
        return healed

    def heal_all(self) -> list:
        out = []
        for s in self._sets.sets:
            for binfo in s.list_buckets():
                s.healer.heal_bucket(binfo["name"])
                for obj in s.list_objects(binfo["name"],
                                          max_keys=1_000_000):
                    out.append(s.healer.heal_object_or_queue(
                        binfo["name"], obj.name))
        return out
