"""Bucket event notification subsystem (ref pkg/event/: Target
interface targetlist.go:25, event names event.go, arn.go; fired from the
S3 handlers via NotificationSys, cmd/notification.go:48)."""
