"""Broker event sinks: the reference's pkg/event/target/ suite
(amqp, elasticsearch, kafka, mqtt, mysql, nats, nsq, postgresql,
redis — ref pkg/event/target/*.go, 8k LoC) rebuilt as minimal
wire-protocol clients over stdlib sockets.

No broker client libraries exist in this image, so each target speaks
the sink's actual wire format directly — enough of it to deliver one
event durably (the queuestore wrapper in targets.py adds disk-backed
retry on top of ANY of these). Tests drive every target against an
in-process fake broker that decodes the real bytes
(tests/test_event_brokers.py).

All targets share the Target contract (arn/send/close) and raise on
failure so TargetList/queuestore retry semantics apply uniformly
(ref pkg/event/targetlist.go:25).
"""

from __future__ import annotations

import hashlib
import json
import socket
import struct
import zlib

from .targets import Target, WebhookTarget


def _connect(host: str, port: int, timeout: float) -> socket.socket:
    s = socket.create_connection((host, port), timeout=timeout)
    s.settimeout(timeout)
    return s


def _recv_exact(s: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("broker closed connection")
        buf += chunk
    return buf


def _key_of(record: dict) -> str:
    try:
        rec = record["Records"][0]
        return (rec["s3"]["bucket"]["name"] + "/"
                + rec["s3"]["object"]["key"])
    except (KeyError, IndexError, TypeError):
        return record.get("Key", "minio-tpu-event")


class _SocketTarget(Target):
    """Shared connect-per-send plumbing (brokers are connect-cheap at
    event rates; a persistent-session variant can pool later)."""

    kind = "socket"

    def __init__(self, host: str, port: int, arn_id: str = "1",
                 timeout: float = 5.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self._arn = f"arn:minio-tpu:sqs::{arn_id}:{self.kind}"

    def arn(self) -> str:
        return self._arn


# ---------------------------------------------------------------------------
# NATS (plain-text protocol: INFO/CONNECT/PUB/+OK)


class NATSTarget(_SocketTarget):
    """ref pkg/event/target/nats.go — PUB <subject> <len>\\r\\n<json>."""

    kind = "nats"
    env_name = "NATS"

    def __init__(self, host: str, port: int, subject: str = "minio-tpu",
                 **kw):
        super().__init__(host, port, **kw)
        self.subject = subject

    def send(self, record: dict) -> None:
        payload = json.dumps(record).encode()
        s = _connect(self.host, self.port, self.timeout)
        try:
            f = s.makefile("rb")
            info = f.readline()            # INFO {...}
            if not info.startswith(b"INFO"):
                raise ConnectionError(f"bad NATS greeting: {info[:40]!r}")
            s.sendall(b'CONNECT {"verbose":true}\r\n')
            if f.readline().strip() != b"+OK":
                raise ConnectionError("NATS CONNECT refused")
            s.sendall(b"PUB " + self.subject.encode()
                      + b" %d\r\n" % len(payload) + payload + b"\r\n")
            if f.readline().strip() != b"+OK":
                raise ConnectionError("NATS PUB refused")
        finally:
            s.close()


# ---------------------------------------------------------------------------
# NSQ ("  V2" magic, PUB <topic>\n[4B size][body], "OK" frame)


class NSQTarget(_SocketTarget):
    """ref pkg/event/target/nsq.go — TCP protocol V2 PUB."""

    kind = "nsq"
    env_name = "NSQ"

    def __init__(self, host: str, port: int, topic: str = "minio-tpu",
                 **kw):
        super().__init__(host, port, **kw)
        self.topic = topic

    def send(self, record: dict) -> None:
        payload = json.dumps(record).encode()
        s = _connect(self.host, self.port, self.timeout)
        try:
            s.sendall(b"  V2")
            s.sendall(b"PUB " + self.topic.encode() + b"\n"
                      + struct.pack(">I", len(payload)) + payload)
            size = struct.unpack(">I", _recv_exact(s, 4))[0]
            frame = _recv_exact(s, size)   # [4B frame type]["OK"]
            ftype = struct.unpack(">i", frame[:4])[0]
            if ftype != 0 or frame[4:] != b"OK":
                raise ConnectionError(f"NSQ PUB failed: {frame!r}")
        finally:
            s.close()


# ---------------------------------------------------------------------------
# MQTT 3.1.1 (CONNECT/CONNACK, PUBLISH QoS0)


def _mqtt_string(b: bytes) -> bytes:
    return struct.pack(">H", len(b)) + b


def _mqtt_remaining_length(n: int) -> bytes:
    out = bytearray()
    while True:
        byte = n % 128
        n //= 128
        out.append(byte | (0x80 if n else 0))
        if not n:
            return bytes(out)


class MQTTTarget(_SocketTarget):
    """ref pkg/event/target/mqtt.go — MQTT 3.1.1 QoS0 publish."""

    kind = "mqtt"
    env_name = "MQTT"

    def __init__(self, host: str, port: int, topic: str = "minio-tpu",
                 client_id: str = "minio-tpu", **kw):
        super().__init__(host, port, **kw)
        self.topic = topic
        self.client_id = client_id

    def send(self, record: dict) -> None:
        payload = json.dumps(record).encode()
        s = _connect(self.host, self.port, self.timeout)
        try:
            var = (_mqtt_string(b"MQTT") + b"\x04"   # protocol level 4
                   + b"\x02"                          # clean session
                   + struct.pack(">H", 60)            # keepalive
                   + _mqtt_string(self.client_id.encode()))
            s.sendall(b"\x10" + _mqtt_remaining_length(len(var)) + var)
            ack = _recv_exact(s, 4)                   # CONNACK
            if ack[0] != 0x20 or ack[3] != 0:
                raise ConnectionError(f"MQTT CONNACK: {ack!r}")
            body = _mqtt_string(self.topic.encode()) + payload
            s.sendall(b"\x30" + _mqtt_remaining_length(len(body)) + body)
            # QoS0: no PUBACK. DISCONNECT politely.
            s.sendall(b"\xe0\x00")
        finally:
            s.close()


# ---------------------------------------------------------------------------
# Redis (RESP: RPUSH for list format / HSET for namespace format)


def _resp_command(*args: bytes) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        out.append(b"$%d\r\n" % len(a) + a + b"\r\n")
    return b"".join(out)


class RedisTarget(_SocketTarget):
    """ref pkg/event/target/redis.go — 'access' format RPUSHes the
    event onto a list key; 'namespace' format HSETs key->state."""

    kind = "redis"
    env_name = "REDIS"

    def __init__(self, host: str, port: int, key: str = "minio-tpu",
                 fmt: str = "access", **kw):
        super().__init__(host, port, **kw)
        self.key = key
        self.fmt = fmt

    def send(self, record: dict) -> None:
        payload = json.dumps(record).encode()
        s = _connect(self.host, self.port, self.timeout)
        try:
            if self.fmt == "namespace":
                cmd = _resp_command(b"HSET", self.key.encode(),
                                    _key_of(record).encode(), payload)
            else:
                cmd = _resp_command(b"RPUSH", self.key.encode(), payload)
            s.sendall(cmd)
            reply = _recv_exact(s, 1)
            if reply in (b"-",):
                raise ConnectionError("redis error reply")
            # drain the rest of the line
            while not reply.endswith(b"\r\n"):
                chunk = s.recv(64)
                if not chunk:
                    raise ConnectionError(
                        "redis closed connection mid-reply")
                reply += chunk
        finally:
            s.close()


# ---------------------------------------------------------------------------
# Elasticsearch (HTTP index API — JSON document per event)


class ElasticsearchTarget(WebhookTarget):
    """ref pkg/event/target/elasticsearch.go — POST /<index>/_doc.
    Reuses the webhook POST machinery (https/ports/paths handled
    there); only the document URL and ARN differ."""

    kind = "elasticsearch"
    env_name = "ELASTICSEARCH"

    def __init__(self, endpoint: str, index: str = "minio-tpu",
                 arn_id: str = "1", timeout: float = 5.0):
        self.index = index
        super().__init__(endpoint.rstrip("/") + f"/{index}/_doc",
                         arn_id=arn_id, timeout=timeout)
        self._arn = f"arn:minio-tpu:sqs::{arn_id}:elasticsearch"


# ---------------------------------------------------------------------------
# Kafka (wire protocol: Produce v0 with legacy v0 message set)


def _kafka_str(s: bytes) -> bytes:
    return struct.pack(">h", len(s)) + s


class KafkaTarget(_SocketTarget):
    """ref pkg/event/target/kafka.go — one Produce v0 request per
    event (legacy message format with CRC32, acks=1)."""

    kind = "kafka"
    env_name = "KAFKA"

    def __init__(self, host: str, port: int, topic: str = "minio-tpu",
                 **kw):
        super().__init__(host, port, **kw)
        self.topic = topic

    def send(self, record: dict) -> None:
        key = _key_of(record).encode()
        value = json.dumps(record).encode()
        # v0 Message: crc32(magic..value) + magic(0) + attrs(0) + key + value
        def _bytes(b: bytes) -> bytes:
            return struct.pack(">i", len(b)) + b
        msg_body = b"\x00\x00" + _bytes(key) + _bytes(value)
        msg = struct.pack(">I", zlib.crc32(msg_body)) + msg_body
        # MessageSet entry: offset(8) + size(4) + message
        mset = struct.pack(">qi", 0, len(msg)) + msg
        # ProduceRequest v0: acks(2) timeout(4) [topic [partition mset]]
        req_body = (struct.pack(">hi", 1, int(self.timeout * 1000))
                    + struct.pack(">i", 1) + _kafka_str(self.topic.encode())
                    + struct.pack(">i", 1) + struct.pack(">i", 0)
                    + struct.pack(">i", len(mset)) + mset)
        # Request header: api_key=0 (Produce), version=0, correlation, client
        header = (struct.pack(">hhi", 0, 0, 1)
                  + _kafka_str(b"minio-tpu"))
        frame = struct.pack(">i", len(header) + len(req_body)) \
            + header + req_body
        s = _connect(self.host, self.port, self.timeout)
        try:
            s.sendall(frame)
            size = struct.unpack(">i", _recv_exact(s, 4))[0]
            resp = _recv_exact(s, size)
            # corr(4) + topics(4) + topic + partitions: [id(4) err(2) off(8)]
            off = 4
            ntopics = struct.unpack_from(">i", resp, off)[0]
            off += 4
            for _ in range(ntopics):
                tlen = struct.unpack_from(">h", resp, off)[0]
                off += 2 + tlen
                nparts = struct.unpack_from(">i", resp, off)[0]
                off += 4
                for _ in range(nparts):
                    _pid, err = struct.unpack_from(">ih", resp, off)
                    off += 4 + 2 + 8
                    if err != 0:
                        raise ConnectionError(f"kafka produce error {err}")
        finally:
            s.close()


# ---------------------------------------------------------------------------
# AMQP 0-9-1 (connection/channel handshake + basic.publish)


def _amqp_frame(ftype: int, channel: int, payload: bytes) -> bytes:
    return struct.pack(">BHI", ftype, channel, len(payload)) \
        + payload + b"\xce"


def _amqp_read_frame(s: socket.socket) -> tuple[int, int, bytes]:
    hdr = _recv_exact(s, 7)
    ftype, channel, size = struct.unpack(">BHI", hdr)
    payload = _recv_exact(s, size)
    if _recv_exact(s, 1) != b"\xce":
        raise ConnectionError("AMQP frame-end missing")
    return ftype, channel, payload


def _amqp_shortstr(b: bytes) -> bytes:
    return struct.pack(">B", len(b)) + b


def _amqp_longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


class AMQPTarget(_SocketTarget):
    """ref pkg/event/target/amqp.go — 0-9-1 PLAIN login then
    basic.publish to a direct exchange/routing key."""

    kind = "amqp"
    env_name = "AMQP"

    def __init__(self, host: str, port: int, exchange: str = "",
                 routing_key: str = "minio-tpu", user: str = "guest",
                 password: str = "guest", **kw):
        super().__init__(host, port, **kw)
        self.exchange = exchange
        self.routing_key = routing_key
        self.user = user
        self.password = password

    def _method(self, cls: int, mid: int, args: bytes = b"") -> bytes:
        return struct.pack(">HH", cls, mid) + args

    def send(self, record: dict) -> None:
        payload = json.dumps(record).encode()
        s = _connect(self.host, self.port, self.timeout)
        try:
            s.sendall(b"AMQP\x00\x00\x09\x01")
            _t, _c, p = _amqp_read_frame(s)          # connection.start
            if struct.unpack(">HH", p[:4]) != (10, 10):
                raise ConnectionError("expected connection.start")
            sasl = b"\x00" + self.user.encode() + b"\x00" \
                + self.password.encode()
            args = (struct.pack(">I", 0)              # client-properties
                    + _amqp_shortstr(b"PLAIN")
                    + _amqp_longstr(sasl)
                    + _amqp_shortstr(b"en_US"))
            s.sendall(_amqp_frame(1, 0, self._method(10, 11, args)))
            _t, _c, p = _amqp_read_frame(s)          # connection.tune
            if struct.unpack(">HH", p[:4]) != (10, 30):
                raise ConnectionError("expected connection.tune")
            chmax, fmax, hb = struct.unpack(">HIH", p[4:12])
            s.sendall(_amqp_frame(1, 0, self._method(
                10, 31, struct.pack(">HIH", chmax or 1, fmax, 0))))
            s.sendall(_amqp_frame(1, 0, self._method(
                10, 40, _amqp_shortstr(b"/") + b"\x00\x00")))
            _t, _c, p = _amqp_read_frame(s)          # connection.open-ok
            if struct.unpack(">HH", p[:4]) != (10, 41):
                raise ConnectionError("expected connection.open-ok")
            s.sendall(_amqp_frame(1, 1, self._method(
                20, 10, _amqp_shortstr(b""))))       # channel.open
            _t, _c, p = _amqp_read_frame(s)
            if struct.unpack(">HH", p[:4]) != (20, 11):
                raise ConnectionError("expected channel.open-ok")
            # basic.publish (60,40): reserved + exchange + rkey + flags
            s.sendall(_amqp_frame(1, 1, self._method(
                60, 40, b"\x00\x00"
                + _amqp_shortstr(self.exchange.encode())
                + _amqp_shortstr(self.routing_key.encode()) + b"\x00")))
            # content header: class 60, weight 0, size, no props
            s.sendall(_amqp_frame(2, 1, struct.pack(
                ">HHQH", 60, 0, len(payload), 0)))
            s.sendall(_amqp_frame(3, 1, payload))    # body frame
            # Close the connection and WAIT for close-ok: a broker
            # rejecting the publish (unroutable exchange etc.) sends
            # channel.close/connection.close first, which must become
            # an error so the queuestore retries instead of dropping
            # the event.
            s.sendall(_amqp_frame(1, 0, self._method(
                10, 50, struct.pack(">H", 200)
                + _amqp_shortstr(b"bye") + struct.pack(">HH", 0, 0))))
            while True:
                _t, _c, p = _amqp_read_frame(s)
                cls_mid = struct.unpack(">HH", p[:4])
                if cls_mid == (10, 51):          # connection.close-ok
                    break
                if cls_mid in ((20, 40), (10, 50)):  # broker close
                    code = struct.unpack(">H", p[4:6])[0]
                    raise ConnectionError(
                        f"AMQP publish rejected: code {code}")
        finally:
            s.close()


# ---------------------------------------------------------------------------
# PostgreSQL (simple protocol: startup, trust auth, INSERT via Query)


class PostgresTarget(_SocketTarget):
    """ref pkg/event/target/postgresql.go — one INSERT per event into
    <table>(key, value) via the simple-query protocol (trust auth)."""

    kind = "postgresql"
    env_name = "POSTGRES"

    def __init__(self, host: str, port: int, table: str = "minio_tpu",
                 user: str = "postgres", database: str = "postgres",
                 **kw):
        super().__init__(host, port, **kw)
        self.table = table
        self.user = user
        self.database = database

    def send(self, record: dict) -> None:
        payload = json.dumps(record).replace("'", "''")
        key = _key_of(record).replace("'", "''")
        s = _connect(self.host, self.port, self.timeout)
        try:
            params = (b"user\x00" + self.user.encode() + b"\x00"
                      + b"database\x00" + self.database.encode()
                      + b"\x00\x00")
            body = struct.pack(">I", 196608) + params   # protocol 3.0
            s.sendall(struct.pack(">I", len(body) + 4) + body)
            # Read until ReadyForQuery ('Z'); require AuthenticationOk.
            authed = False
            while True:
                tag = _recv_exact(s, 1)
                size = struct.unpack(">I", _recv_exact(s, 4))[0]
                data = _recv_exact(s, size - 4)
                if tag == b"R":
                    if struct.unpack(">I", data[:4])[0] != 0:
                        raise ConnectionError(
                            "postgres requires auth (trust only)")
                    authed = True
                elif tag == b"E":
                    raise ConnectionError(f"postgres error: {data!r}")
                elif tag == b"Z":
                    break
            if not authed:
                raise ConnectionError("postgres never authenticated")
            sql = (f"INSERT INTO {self.table} (event_key, event_value) "
                   f"VALUES ('{key}', '{payload}')")
            q = sql.encode() + b"\x00"
            s.sendall(b"Q" + struct.pack(">I", len(q) + 4) + q)
            while True:
                tag = _recv_exact(s, 1)
                size = struct.unpack(">I", _recv_exact(s, 4))[0]
                data = _recv_exact(s, size - 4)
                if tag == b"E":
                    raise ConnectionError(f"postgres error: {data!r}")
                if tag == b"Z":
                    break
        finally:
            s.close()


# ---------------------------------------------------------------------------
# MySQL (handshake v10 + mysql_native_password + COM_QUERY INSERT)


def _mysql_scramble(password: bytes, salt: bytes) -> bytes:
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(salt + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


class MySQLTarget(_SocketTarget):
    """ref pkg/event/target/mysql.go — mysql_native_password login and
    one INSERT per event."""

    kind = "mysql"
    env_name = "MYSQL"

    def __init__(self, host: str, port: int, table: str = "minio_tpu",
                 user: str = "root", password: str = "",
                 database: str = "minio_tpu", **kw):
        super().__init__(host, port, **kw)
        self.table = table
        self.user = user
        self.password = password
        self.database = database

    @staticmethod
    def _read_packet(s: socket.socket) -> tuple[int, bytes]:
        hdr = _recv_exact(s, 4)
        size = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
        return hdr[3], _recv_exact(s, size)

    @staticmethod
    def _packet(seq: int, body: bytes) -> bytes:
        n = len(body)
        return bytes((n & 0xFF, (n >> 8) & 0xFF, (n >> 16) & 0xFF,
                      seq)) + body

    def send(self, record: dict) -> None:
        def esc(text: str) -> str:
            # MySQL treats backslash as an escape char even inside
            # '...' strings: double it BEFORE doubling quotes, or an
            # object key ending in a backslash re-opens the string
            # (SQL injection via key names).
            return text.replace("\\", "\\\\").replace("'", "''")
        payload = esc(json.dumps(record))
        key = esc(_key_of(record))
        s = _connect(self.host, self.port, self.timeout)
        try:
            _seq, greet = self._read_packet(s)
            if greet[0] != 10:
                raise ConnectionError("unsupported mysql protocol")
            rest = greet[1:]
            nul = rest.index(b"\x00")
            rest = rest[nul + 1:]
            salt1 = rest[4:12]
            # skip filler, capability low, charset, status, cap high,
            # auth len, 10 reserved
            salt2 = rest[12 + 1 + 2 + 2 + 1 + 2 + 2 + 10:][:12]
            scramble = _mysql_scramble(self.password.encode(),
                                       salt1 + salt2)
            # CLIENT_LONG_PASSWORD | PROTOCOL_41 | SECURE_CONNECTION
            # | CONNECT_WITH_DB (db name trails the auth response).
            caps = 0x00000001 | 0x00000200 | 0x00008000 | 0x00000008
            body = (struct.pack("<IIB", caps, 1 << 24, 33)
                    + b"\x00" * 23 + self.user.encode() + b"\x00"
                    + bytes([len(scramble)]) + scramble
                    + self.database.encode() + b"\x00")
            s.sendall(self._packet(1, body))
            _seq, ok = self._read_packet(s)
            if ok[:1] == b"\xff":
                raise ConnectionError(f"mysql auth failed: {ok[3:]!r}")
            sql = (f"INSERT INTO {self.table} (event_key, event_value) "
                   f"VALUES ('{key}', '{payload}')")
            s.sendall(self._packet(0, b"\x03" + sql.encode()))
            _seq, resp = self._read_packet(s)
            if resp[:1] == b"\xff":
                raise ConnectionError(f"mysql insert failed: {resp[3:]!r}")
        finally:
            s.close()


# ---------------------------------------------------------------------------
# env config (ref config/notify subsystem env conventions:
# MINIO_NOTIFY_<SINK>_ENABLE / _ADDRESS ("host:port") / sink knobs)


def targets_from_env(env=None) -> list[Target]:
    """Instantiate every broker sink enabled via environment. Each may
    additionally set MINIO_NOTIFY_<SINK>_QUEUE_DIR for disk-backed
    retry (wrapped by the caller, same as the webhook sink)."""
    import os as _os
    env = env if env is not None else _os.environ
    out: list[Target] = []

    def addr(name, default_port):
        raw = env.get(f"MINIO_NOTIFY_{name}_ADDRESS", "")
        host, _, port = raw.partition(":")
        return host or "127.0.0.1", int(port or default_port)

    def on(name):
        return env.get(f"MINIO_NOTIFY_{name}_ENABLE", "") == "on"

    if on("NATS"):
        h, p = addr("NATS", 4222)
        out.append(NATSTarget(
            h, p, subject=env.get("MINIO_NOTIFY_NATS_SUBJECT",
                                  "minio-tpu")))
    if on("NSQ"):
        h, p = addr("NSQ", 4150)
        out.append(NSQTarget(
            h, p, topic=env.get("MINIO_NOTIFY_NSQ_TOPIC", "minio-tpu")))
    if on("MQTT"):
        h, p = addr("MQTT", 1883)
        out.append(MQTTTarget(
            h, p, topic=env.get("MINIO_NOTIFY_MQTT_TOPIC", "minio-tpu")))
    if on("REDIS"):
        h, p = addr("REDIS", 6379)
        out.append(RedisTarget(
            h, p, key=env.get("MINIO_NOTIFY_REDIS_KEY", "minio-tpu"),
            fmt=env.get("MINIO_NOTIFY_REDIS_FORMAT", "access")))
    if on("ELASTICSEARCH"):
        out.append(ElasticsearchTarget(
            env.get("MINIO_NOTIFY_ELASTICSEARCH_URL",
                    "http://127.0.0.1:9200"),
            index=env.get("MINIO_NOTIFY_ELASTICSEARCH_INDEX",
                          "minio-tpu")))
    if on("KAFKA"):
        h, p = addr("KAFKA", 9092)
        out.append(KafkaTarget(
            h, p, topic=env.get("MINIO_NOTIFY_KAFKA_TOPIC",
                                "minio-tpu")))
    if on("AMQP"):
        h, p = addr("AMQP", 5672)
        out.append(AMQPTarget(
            h, p,
            exchange=env.get("MINIO_NOTIFY_AMQP_EXCHANGE", ""),
            routing_key=env.get("MINIO_NOTIFY_AMQP_ROUTING_KEY",
                                "minio-tpu"),
            user=env.get("MINIO_NOTIFY_AMQP_USER", "guest"),
            password=env.get("MINIO_NOTIFY_AMQP_PASSWORD", "guest")))
    if on("POSTGRES"):
        h, p = addr("POSTGRES", 5432)
        out.append(PostgresTarget(
            h, p, table=env.get("MINIO_NOTIFY_POSTGRES_TABLE",
                                "minio_tpu"),
            user=env.get("MINIO_NOTIFY_POSTGRES_USER", "postgres"),
            database=env.get("MINIO_NOTIFY_POSTGRES_DATABASE",
                             "postgres")))
    if on("MYSQL"):
        h, p = addr("MYSQL", 3306)
        out.append(MySQLTarget(
            h, p, table=env.get("MINIO_NOTIFY_MYSQL_TABLE", "minio_tpu"),
            user=env.get("MINIO_NOTIFY_MYSQL_USER", "root"),
            password=env.get("MINIO_NOTIFY_MYSQL_PASSWORD", ""),
            database=env.get("MINIO_NOTIFY_MYSQL_DATABASE",
                             "minio_tpu")))
    return out
