"""S3 event records and event-name matching.

Ref pkg/event/event.go (Event struct, the AWS event-record JSON shape)
and pkg/event/name.go (Name enum + expansion: "s3:ObjectCreated:*"
expands to every ObjectCreated sub-event).
"""

from __future__ import annotations

import time
import urllib.parse
from dataclasses import dataclass, field

# Canonical event names (subset actively fired; ref pkg/event/name.go).
OBJECT_CREATED_PUT = "s3:ObjectCreated:Put"
OBJECT_CREATED_POST = "s3:ObjectCreated:Post"
OBJECT_CREATED_COPY = "s3:ObjectCreated:Copy"
OBJECT_CREATED_COMPLETE_MULTIPART = \
    "s3:ObjectCreated:CompleteMultipartUpload"
OBJECT_ACCESSED_GET = "s3:ObjectAccessed:Get"
OBJECT_ACCESSED_HEAD = "s3:ObjectAccessed:Head"
OBJECT_REMOVED_DELETE = "s3:ObjectRemoved:Delete"
OBJECT_REMOVED_DELETE_MARKER = "s3:ObjectRemoved:DeleteMarkerCreated"

_EXPANSIONS = {
    "s3:ObjectCreated:*": [
        OBJECT_CREATED_PUT, OBJECT_CREATED_POST, OBJECT_CREATED_COPY,
        OBJECT_CREATED_COMPLETE_MULTIPART,
    ],
    "s3:ObjectAccessed:*": [OBJECT_ACCESSED_GET, OBJECT_ACCESSED_HEAD],
    "s3:ObjectRemoved:*": [OBJECT_REMOVED_DELETE,
                           OBJECT_REMOVED_DELETE_MARKER],
}


def expand_event_name(name: str) -> list[str]:
    """'s3:ObjectCreated:*' -> every concrete ObjectCreated event
    (ref pkg/event/name.go Expand)."""
    return list(_EXPANSIONS.get(name, [name]))


@dataclass
class Event:
    """One S3 notification record (ref pkg/event/event.go:77 Event)."""
    event_name: str
    bucket: str
    key: str
    size: int = 0
    etag: str = ""
    version_id: str = ""
    region: str = "us-east-1"
    user_identity: str = ""
    sequencer: str = ""
    event_time: float = field(default_factory=time.time)

    def to_record(self) -> dict:
        """The AWS-compatible record JSON shape."""
        t = time.strftime("%Y-%m-%dT%H:%M:%S.000Z",
                          time.gmtime(self.event_time))
        obj = {
            "key": urllib.parse.quote(self.key),
            "sequencer": self.sequencer or
            format(int(self.event_time * 1e9), "X"),
        }
        if not self.event_name.startswith("s3:ObjectRemoved:"):
            obj["size"] = self.size
            obj["eTag"] = self.etag
        if self.version_id:
            obj["versionId"] = self.version_id
        return {
            "eventVersion": "2.0",
            "eventSource": "minio-tpu:s3",
            "awsRegion": self.region,
            "eventTime": t,
            "eventName": self.event_name,
            "userIdentity": {"principalId": self.user_identity},
            "s3": {
                "s3SchemaVersion": "1.0",
                "bucket": {
                    "name": self.bucket,
                    "arn": f"arn:aws:s3:::{self.bucket}",
                    "ownerIdentity": {
                        "principalId": self.user_identity},
                },
                "object": obj,
            },
        }
