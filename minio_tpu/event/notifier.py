"""NotificationSys: routes fired events to subscribed targets.

Ref cmd/notification.go:48 (NotificationSys), cmd/event-notification.go
(EventNotifier.Send: look up the bucket's rules map, fan out to matching
targets). Delivery is async — the S3 handler never blocks on a sink.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

from .event import Event
from .rules import RulesMap, parse_notification_xml
from .targets import Target


class NotificationSys:
    def __init__(self, bucket_meta=None, region: str = "us-east-1"):
        self.bucket_meta = bucket_meta
        self.region = region
        self.targets: dict[str, Target] = {}
        self._mu = threading.RLock()
        self._pool = ThreadPoolExecutor(max_workers=4,
                                        thread_name_prefix="event-send")
        # Tests / callers may inject per-bucket rules directly instead of
        # going through bucket metadata XML.
        self._static_rules: dict[str, RulesMap] = {}
        # Parsed-rules cache keyed by the raw XML (the hot path must not
        # re-parse the notification config on every fired event).
        self._parsed: dict[str, tuple[str, RulesMap]] = {}

    def register_target(self, target: Target) -> None:
        with self._mu:
            self.targets[target.arn()] = target

    def remove_target(self, arn: str) -> None:
        with self._mu:
            t = self.targets.pop(arn, None)
        if t:
            t.close()

    def target_arns(self) -> list[str]:
        with self._mu:
            return list(self.targets)

    def rules_for(self, bucket: str) -> RulesMap:
        if bucket in self._static_rules:
            return self._static_rules[bucket]
        if self.bucket_meta is None:
            return RulesMap()
        raw = self.bucket_meta.get(bucket).notification_xml
        with self._mu:
            hit = self._parsed.get(bucket)
            if hit and hit[0] == raw:
                return hit[1]
        rules = parse_notification_xml(raw)
        with self._mu:
            self._parsed[bucket] = (raw, rules)
        return rules

    def set_rules(self, bucket: str, rules: RulesMap) -> None:
        self._static_rules[bucket] = rules

    def send(self, event: Event) -> None:
        """Fan out asynchronously to every matching target
        (ref EventNotifier.Send)."""
        rules = self.rules_for(event.bucket)
        if not rules:
            return
        arns = rules.match(event.event_name, event.key)
        if not arns:
            return
        event.region = event.region or self.region
        record = {"EventName": event.event_name,
                  "Key": f"{event.bucket}/{event.key}",
                  "Records": [event.to_record()]}
        with self._mu:
            targets = [self.targets[a] for a in arns if a in self.targets]
        for t in targets:
            # mtpu-lint: disable=R1 -- post-response fan-out: delivery must not be canceled by the finished request's burnt budget
            self._pool.submit(self._send_one, t, record)

    @staticmethod
    def _send_one(target: Target, record: dict) -> None:
        try:
            target.send(record)
        except Exception:
            pass  # target-level retry (queue store) owns persistence

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._mu:
            for t in self.targets.values():
                t.close()
