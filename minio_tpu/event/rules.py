"""Notification config: XML parsing + event routing rules.

Ref pkg/event/config.go (Config/Queue structs, filter-rule validation)
and pkg/event/rules.go (RulesMap: event-name -> pattern -> target-ID
set). A bucket's <NotificationConfiguration> maps (event, key) pairs to
target ARNs; patterns come from prefix/suffix FilterRules.
"""

from __future__ import annotations

from ..s3.xmlutil import parse
from .event import expand_event_name


def _pattern(prefix: str, suffix: str) -> str:
    """prefix+suffix -> one wildcard pattern (ref pkg/event/rules.go
    NewPattern: 'p*' + '*s' joined with a single star)."""
    pat = ""
    if prefix:
        pat = prefix if prefix.endswith("*") else prefix + "*"
    if suffix:
        s = suffix if suffix.startswith("*") else "*" + suffix
        pat = pat + s if pat else s
    if not pat:
        pat = "*"
    return pat.replace("**", "*")


def _match_simple(pattern: str, text: str) -> bool:
    """Wildcard match with '*' only (ref pkg/wildcard MatchSimple)."""
    parts = pattern.split("*")
    if len(parts) == 1:
        return pattern == text
    if not text.startswith(parts[0]) or not text.endswith(parts[-1]):
        return False
    pos = len(parts[0])
    for part in parts[1:-1]:
        if not part:
            continue
        idx = text.find(part, pos)
        if idx < 0:
            return False
        pos = idx + len(part)
    return pos <= len(text) - len(parts[-1])


class RulesMap:
    """event-name -> [(pattern, arn)] (ref pkg/event/rules.go)."""

    def __init__(self):
        self.rules: dict[str, list[tuple[str, str]]] = {}

    def add(self, event_names: list[str], pattern: str, arn: str) -> None:
        for name in event_names:
            for concrete in expand_event_name(name):
                self.rules.setdefault(concrete, []).append((pattern, arn))

    def match(self, event_name: str, key: str) -> set[str]:
        """Target ARNs subscribed to (event, key)."""
        out: set[str] = set()
        for pattern, arn in self.rules.get(event_name, []):
            if _match_simple(pattern, key):
                out.add(arn)
        return out

    def __bool__(self) -> bool:
        return bool(self.rules)


def parse_notification_xml(raw: str) -> RulesMap:
    """<NotificationConfiguration> -> RulesMap. Supports Queue/Topic/
    CloudFunction configurations uniformly (all route by ARN; ref
    pkg/event/config.go Config.ToRulesMap)."""
    rules = RulesMap()
    if not raw:
        return rules
    doc = parse(raw.encode() if isinstance(raw, str) else raw)
    for tag, arn_tag in (("QueueConfiguration", "Queue"),
                        ("TopicConfiguration", "Topic"),
                        ("CloudFunctionConfiguration", "CloudFunction")):
        for qc in doc.findall(tag):
            arn = qc.findtext(arn_tag) or ""
            events = [e.text or "" for e in qc.findall("Event")]
            prefix = suffix = ""
            filt = qc.find("Filter")
            if filt is not None:
                s3key = filt.find("S3Key")
                if s3key is not None:
                    for fr in s3key.findall("FilterRule"):
                        name = (fr.findtext("Name") or "").lower()
                        value = fr.findtext("Value") or ""
                        if name == "prefix":
                            prefix = value
                        elif name == "suffix":
                            suffix = value
            if arn and events:
                rules.add(events, _pattern(prefix, suffix), arn)
    return rules
