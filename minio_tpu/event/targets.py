"""Event targets: where notification records get delivered.

Ref pkg/event/targetlist.go:25 (Target interface: ID/Save/Send/Close),
pkg/event/target/webhook.go (HTTP POST sink) and
pkg/event/target/queuestore.go (disk-backed retry queue replayed by a
background sender — delivery survives sink outages and restarts).
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.parse
import uuid


class Target:
    """Interface (ref pkg/event/targetlist.go Target)."""

    def arn(self) -> str:
        raise NotImplementedError

    def send(self, record: dict) -> None:
        """Deliver one event record; raise on failure."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryTarget(Target):
    """In-process sink for tests and for the admin trace stream."""

    def __init__(self, arn_id: str = "1"):
        self._arn = f"arn:minio-tpu:sqs::{arn_id}:memory"
        self.records: list[dict] = []
        self._mu = threading.Lock()

    def arn(self) -> str:
        return self._arn

    def send(self, record: dict) -> None:
        with self._mu:
            self.records.append(record)


class WebhookTarget(Target):
    """POSTs the event payload to an HTTP endpoint
    (ref pkg/event/target/webhook.go Send)."""

    def __init__(self, endpoint: str, arn_id: str = "1",
                 timeout: float = 5.0):
        self.endpoint = endpoint
        self.timeout = timeout
        self._arn = f"arn:minio-tpu:sqs::{arn_id}:webhook"
        u = urllib.parse.urlsplit(endpoint)
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or (443 if u.scheme == "https" else 80)
        # Keep the query string — webhook endpoints often carry auth
        # tokens as URL parameters.
        self._path = (u.path or "/") + (f"?{u.query}" if u.query else "")
        self._https = u.scheme == "https"

    def arn(self) -> str:
        return self._arn

    def send(self, record: dict) -> None:
        body = json.dumps(record).encode()
        cls = (http.client.HTTPSConnection if self._https
               else http.client.HTTPConnection)
        conn = cls(self._host, self._port, timeout=self.timeout)
        try:
            conn.request("POST", self._path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            if resp.status // 100 != 2:
                raise IOError(f"webhook {self.endpoint}: "
                              f"HTTP {resp.status}")
        finally:
            conn.close()


class QueueStoreTarget(Target):
    """Wraps a target with a disk-backed retry queue: failed sends are
    persisted as JSON files and replayed by a background thread (ref
    pkg/event/target/queuestore.go + the target boot replay)."""

    RETRY_INTERVAL = 2.0

    def __init__(self, inner: Target, store_dir: str, limit: int = 10000):
        self.inner = inner
        self.dir = store_dir
        self.limit = limit
        os.makedirs(store_dir, exist_ok=True)
        self._stop = threading.Event()
        self._kick = threading.Event()
        # mtpu-lint: disable=R1 -- queue-store retry daemon: delivery must survive (not inherit) the request deadline
        self._thread = threading.Thread(target=self._retry_loop,
                                        daemon=True)
        self._thread.start()

    def arn(self) -> str:
        return self.inner.arn()

    def send(self, record: dict) -> None:
        # While older failed events sit in the queue, new ones must park
        # BEHIND them — a direct send would reorder (e.g. a Delete
        # overtaking its key's queued Put).
        if self.pending():
            self._persist(record)
            return
        try:
            self.inner.send(record)
        except Exception:
            self._persist(record)

    def _persist(self, record: dict) -> None:
        if len(os.listdir(self.dir)) >= self.limit:
            return  # queue full: drop (ref queuestore limit behavior)
        name = f"{time.time():.6f}-{uuid.uuid4().hex}.json"
        tmp = os.path.join(self.dir, f".tmp-{name}")
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, os.path.join(self.dir, name))
        self._kick.set()

    def pending(self) -> int:
        return len([n for n in os.listdir(self.dir)
                    if not n.startswith(".tmp-")])

    def _retry_loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.RETRY_INTERVAL)
            self._kick.clear()
            for name in sorted(os.listdir(self.dir)):
                if self._stop.is_set() or name.startswith(".tmp-"):
                    continue
                path = os.path.join(self.dir, name)
                try:
                    with open(path) as f:
                        record = json.load(f)
                except (OSError, ValueError):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                try:
                    self.inner.send(record)
                except Exception:
                    break  # sink still down; retry next tick, keep order
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def close(self) -> None:
        self._stop.set()
        self._kick.set()
        self._thread.join(timeout=5)
        self.inner.close()
