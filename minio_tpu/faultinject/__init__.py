"""Runtime fault-injection subsystem: a seeded, deterministic
fault-plan engine the whole stack consults at its failure boundaries.

Every robustness claim in this codebase ultimately reduces to "when X
breaks, the system does Y" — and proving that needs X to break on
demand, reproducibly. Earlier rounds used one-off shims (a
``fault_latency_s`` attribute on XLStorage, monkeypatched disks in
tests); this module promotes injection to a first-class subsystem so
the data plane, the RPC transport, and the kernel dispatch layer all
share ONE plan with ONE deterministic decision procedure:

    plan = {"seed": 7, "rules": [
        {"kind": "latency", "target": "/disks/d5", "op": "read",
         "latency_ms": 80},
        {"kind": "error",   "target": "/disks/d3", "probability": 0.5},
        {"kind": "corrupt", "target": "/disks/d1", "op": "read"},
        {"kind": "torn_write", "target": "/disks/d2"},
        {"kind": "partition",  "target": "10.0.0.2:9000"},
        {"kind": "slow_wire",  "target": "10.0.0.2:9000",
         "latency_ms": 30},
        {"kind": "kernel", "target": "rs_encode"},
        {"kind": "loop_block", "target": "s3-0", "latency_ms": 400},
    ]}

Rule fields: ``kind`` (required), ``target`` (substring matched against
the drive endpoint / peer endpoint / kernel name / crash-point name;
empty matches all), ``op`` (exact storage op name or drivemon op class
read/write/stat/delete; ``*`` matches all), ``latency_ms``,
``probability`` (default 1.0), ``after`` (skip the first N matching
occurrences), ``count`` (fire at most N times; 0 = unlimited).

The ``crash`` kind is the crash-consistency harness's lever: the
commit paths in ``storage/xl.py`` / ``erasure/engine.py`` /
``erasure/multipart.py`` / ``erasure/heal.py`` declare NAMED crash
points (:meth:`FaultInjector.crash_point`) at every boundary where a
process death leaves interesting on-disk state — post-tmp-write,
between per-disk shard commits, mid multipart hard-link loop,
straddling the xl.meta replace, mid heal write-back. A fired crash
rule calls ``os._exit(137)``: no atexit handlers, no flushes, no
finally blocks — the closest in-process stand-in for SIGKILL, so the
restart-and-assert harness (tests/test_crash_consistency.py)
exercises REAL torn state, not a politely unwound exception. Points
register at import time, so the admin ``/fault-inject`` GET can
enumerate coverage (name + traversal count + armed flag) before any
traffic flows.

Determinism: whether occurrence ``n`` of a rule fires is a pure
function of (seed, rule index, n) — a SHA-256-derived fraction compared
against ``probability`` — so the same plan over the same op sequence
always injects the same faults, which is what makes scenario matrices
(tests/test_fault_harness.py) debuggable.

Hook points (each a one-attribute check when no plan is loaded):
  - ``storage/xl.py``  ``_DiskOp.__enter__`` -> :meth:`disk_op`
    (latency + error), ``read_*``/write paths -> :meth:`filter_read`
    / :meth:`filter_write` (corrupt, torn_write);
  - ``rpc/transport.py`` ``RPCClient.call`` -> :meth:`peer`
    (partition, slow_wire); ``rpc/storage.py`` read results ->
    :meth:`filter_read` (corrupt over the wire);
  - ``ops/batching.py`` device dispatch -> :meth:`kernel`
    (kernel-dispatch failure; exercises the host-fallback lane);
  - ``obs/loopmon.py`` heartbeat -> :meth:`loop_block` (deterministic
    blocking callback on a named event loop; proves the stall
    detect -> blame -> fire -> resolve chain).

Configured via the admin API (``/minio-tpu/admin/v1/fault-inject``)
or config-KV (``fault_inject enable=on plan=<compact JSON>``).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

KINDS = ("latency", "error", "corrupt", "torn_write", "partition",
         "slow_wire", "kernel", "crash", "loop_block")

# kinds consulted at each hook
_DISK_KINDS = ("latency", "error")
_PEER_KINDS = ("partition", "slow_wire")


class InjectedFault(Exception):
    """Marker base so injected failures are distinguishable in logs."""


class FaultPlanError(ValueError):
    """The submitted plan document is malformed."""


class _Rule:
    __slots__ = ("index", "kind", "target", "op", "latency_ms",
                 "probability", "after", "count", "seen", "fired")

    def __init__(self, index: int, doc: dict):
        if not isinstance(doc, dict):
            raise FaultPlanError(f"rule {index}: not an object")
        kind = doc.get("kind")
        if kind not in KINDS:
            raise FaultPlanError(
                f"rule {index}: kind {kind!r} not in {KINDS}")
        self.index = index
        self.kind = kind
        self.target = str(doc.get("target", ""))
        self.op = str(doc.get("op", "*")) or "*"
        try:
            self.latency_ms = float(doc.get("latency_ms", 0.0))
            self.probability = float(doc.get("probability", 1.0))
            self.after = int(doc.get("after", 0))
            self.count = int(doc.get("count", 0))
        except (TypeError, ValueError) as e:
            raise FaultPlanError(f"rule {index}: {e}")
        if not (0.0 <= self.probability <= 1.0):
            raise FaultPlanError(
                f"rule {index}: probability {self.probability} "
                "outside [0, 1]")
        if self.latency_ms < 0 or self.after < 0 or self.count < 0:
            raise FaultPlanError(f"rule {index}: negative field")
        unknown = set(doc) - {"kind", "target", "op", "latency_ms",
                              "probability", "after", "count"}
        if unknown:
            raise FaultPlanError(
                f"rule {index}: unknown fields {sorted(unknown)}")
        self.seen = 0     # matching occurrences observed
        self.fired = 0    # occurrences that actually injected

    def to_dict(self) -> dict:
        return {"kind": self.kind, "target": self.target, "op": self.op,
                "latency_ms": self.latency_ms,
                "probability": self.probability, "after": self.after,
                "count": self.count, "seen": self.seen,
                "fired": self.fired}


def _op_matches(rule_op: str, op: str) -> bool:
    if rule_op == "*" or rule_op == op:
        return True
    from ..obs.drivemon import op_class
    return rule_op == op_class(op)


class FaultInjector:
    """Process-wide fault-plan engine (singleton ``FAULTS``).

    Hot-path discipline: with no plan loaded every hook is a single
    attribute read (``self.enabled``); with a plan loaded, decisions
    are computed under the lock but SLEEPS AND RAISES happen outside
    it (lint R3 — a fault injector must not serialize the fan-outs it
    is trying to perturb)."""

    # Test seam for the crash kind: the harness's subprocess servers
    # die for real; in-process unit tests swap this for a recorder.
    # os._exit, not sys.exit: no atexit, no finally, no flushes — the
    # whole point is that NOTHING between the crash point and the
    # kernel runs.
    _exit = staticmethod(os._exit)
    CRASH_EXIT_CODE = 137  # what a SIGKILL-ed process reports

    def __init__(self):
        self.enabled = False
        self._mu = threading.Lock()
        self._rules: list[_Rule] = []
        self._seed = 0
        self._loaded_at = 0.0
        # Named crash points, declared at import time by the modules
        # that host them (name -> traversals observed while a plan was
        # armed). Static registration is deliberate: the harness
        # enumerates coverage from the admin GET, so a point that is
        # never traversed must still be LISTED (a registry built from
        # traffic would silently under-report coverage).
        self._crash_points: dict[str, int] = {}
        # Fires recorded AT the point itself, not inferred from rule
        # counters — a broad rule target matches many points, and its
        # fired total must not smear across all of them. (Almost
        # always unobservable post-fire — the process exits — but an
        # inferred-wrong positive is worse than an honest zero.)
        self._crash_fired: dict[str, int] = {}

    # -- plan management ----------------------------------------------

    @staticmethod
    def validate(doc: dict) -> list[_Rule]:
        if not isinstance(doc, dict):
            raise FaultPlanError("plan must be a JSON object")
        unknown = set(doc) - {"seed", "rules"}
        if unknown:
            raise FaultPlanError(f"unknown plan fields {sorted(unknown)}")
        rules = doc.get("rules", [])
        if not isinstance(rules, list):
            raise FaultPlanError("rules must be a list")
        return [_Rule(i, r) for i, r in enumerate(rules)]

    def load_plan(self, doc: dict) -> None:
        """Validate + atomically install a plan (replaces any active
        one; counters restart so determinism restarts with it)."""
        rules = self.validate(doc)
        seed = int(doc.get("seed", 0))
        with self._mu:
            self._rules = rules
            self._seed = seed
            self._loaded_at = time.time()
            self.enabled = bool(rules)
        from ..logger import Logger
        Logger.get().info(
            f"faultinject: plan loaded ({len(rules)} rules, "
            f"seed {seed})", "faultinject")

    def clear(self) -> None:
        with self._mu:
            had = bool(self._rules)
            self._rules = []
            self.enabled = False
        if had:
            from ..logger import Logger
            Logger.get().info("faultinject: plan cleared", "faultinject")

    def register_crash_point(self, name: str) -> str:
        """Declare a named crash point (module-import time). Idempotent;
        returns the name so hook modules can keep the constant."""
        with self._mu:
            self._crash_points.setdefault(name, 0)
        return name

    def crash_points(self) -> list[str]:
        with self._mu:
            return sorted(self._crash_points)

    def snapshot(self) -> dict:
        with self._mu:
            armed = set()
            for r in self._rules:
                if r.kind != "crash":
                    continue
                for name in self._crash_points:
                    if not r.target or r.target in name:
                        armed.add(name)
            return {"active": self.enabled, "seed": self._seed,
                    "loadedAt": self._loaded_at,
                    "rules": [r.to_dict() for r in self._rules],
                    # Per-point coverage counters for the crash
                    # harness and operators: hits counts traversals
                    # observed while a plan was armed (the no-plan hot
                    # path is one attribute read and counts nothing);
                    # fired counts kills AT the point.
                    "crashPoints": [
                        {"name": name, "hits": hits,
                         "armed": name in armed,
                         "fired": self._crash_fired.get(name, 0)}
                        for name, hits in sorted(
                            self._crash_points.items())]}

    # -- deterministic decision ---------------------------------------

    def _fires(self, rule: _Rule) -> bool:
        """Caller holds self._mu. Advances the rule's occurrence
        counter and decides deterministically whether it injects."""
        n = rule.seen
        rule.seen += 1
        if n < rule.after:
            return False
        if rule.count and rule.fired >= rule.count:
            return False
        if rule.probability < 1.0:
            h = hashlib.sha256(
                f"{self._seed}:{rule.index}:{n}".encode()).digest()
            frac = int.from_bytes(h[:8], "big") / float(1 << 64)
            if frac >= rule.probability:
                return False
        rule.fired += 1
        from ..obs.metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_fault_injections_total",
                     {"kind": rule.kind})
        return True

    def _collect(self, kinds, target: str, op: str = "*") -> list[_Rule]:
        """Fired rules of the given kinds matching target/op."""
        out = []
        with self._mu:
            for r in self._rules:
                if r.kind not in kinds:
                    continue
                if r.target and r.target not in target:
                    continue
                if op != "*" and not _op_matches(r.op, op):
                    continue
                if self._fires(r):
                    out.append(r)
        return out

    # -- hooks ---------------------------------------------------------

    def disk_op(self, endpoint: str, op: str) -> None:
        """Per-drive latency/error injection at the _DiskOp boundary.
        Sleeps land INSIDE the measured op window; errors raise
        FaultyDisk — exactly what a degraded physical drive looks like
        to the drive monitor."""
        if not self.enabled:
            return
        fired = self._collect(_DISK_KINDS, endpoint, op)
        err = None
        for r in fired:
            if r.kind == "latency" and r.latency_ms > 0:
                time.sleep(r.latency_ms / 1e3)
            elif r.kind == "error":
                err = r
        if err is not None:
            from ..storage.errors import FaultyDisk
            raise FaultyDisk(
                f"injected fault: {endpoint} {op} (rule {err.index})")

    def filter_read(self, endpoint: str, op: str, data: bytes) -> bytes:
        """Corrupt injection on read results: deterministically flip
        one byte (bitrot detection must catch it). The position is
        derived per OCCURRENCE, not fixed: the local-disk and
        remote-client read hooks can stack on one payload (loopback
        RPC), and two flips of the same byte would cancel into an
        uncorrupted read that silently passes verification."""
        if not self.enabled or not data:
            return data
        fired = self._collect(("corrupt",), endpoint, op)
        if not fired:
            return data
        blob = bytearray(data)
        for r in fired:
            h = hashlib.sha256(
                f"{self._seed}:{r.index}:{r.fired}:pos".encode()
            ).digest()
            blob[int.from_bytes(h[:8], "big") % len(blob)] ^= 0xFF
        return bytes(blob)

    def filter_write(self, endpoint: str, op: str, data: bytes) -> bytes:
        """Torn-write injection: the write persists only the first half
        of the payload (a crash mid-write), without erroring."""
        if not self.enabled or not data:
            return data
        fired = self._collect(("torn_write",), endpoint, op)
        if not fired:
            return data
        return bytes(data[:max(1, len(data) // 2)])

    def peer(self, endpoint: str) -> tuple[float, bool]:
        """Per-peer wire faults: returns (extra latency seconds,
        partitioned). The transport sleeps/raises; raising here would
        hide which rule matched."""
        if not self.enabled:
            return 0.0, False
        lat = 0.0
        part = False
        for r in self._collect(_PEER_KINDS, endpoint):
            if r.kind == "slow_wire":
                lat += r.latency_ms / 1e3
            else:
                part = True
        return lat, part

    def loop_block(self, loop_name: str) -> float:
        """Event-loop blocker: seconds the named loop's loopmon
        heartbeat should schedule as a REAL blocking time.sleep
        callback onto its own loop (obs/loopmon.py
        ``_injected_loop_block``) — the deterministic stall that
        proves the detect -> blame -> fire -> resolve chain.  Returns
        0.0 with no plan loaded (single attribute read; the hook runs
        at 10Hz per loop)."""
        if not self.enabled:
            return 0.0
        total = 0.0
        for r in self._collect(("loop_block",), loop_name):
            total += r.latency_ms / 1e3
        return total

    def kernel(self, name: str) -> None:
        """Kernel-dispatch failure: raises inside the device dispatch
        try-block so the host-fallback lane is exercised."""
        if not self.enabled:
            return
        if self._collect(("kernel",), name):
            raise InjectedFault(f"injected kernel-dispatch fault: {name}")

    def crash_point(self, name: str) -> None:
        """Named commit-path crash point: when an armed ``crash`` rule
        matches, the PROCESS DIES HERE via os._exit(137) — no
        exception, no cleanup, no flush. ``after``/``count``/
        ``probability`` apply as usual, so a harness can let N disks
        commit before the kill lands mid-fan-out. With no plan loaded
        this is a single attribute read (the hook sits on the PUT
        commit path)."""
        if not self.enabled:
            return
        with self._mu:
            if name in self._crash_points:
                self._crash_points[name] += 1
        if self._collect(("crash",), name):
            with self._mu:
                self._crash_fired[name] = \
                    self._crash_fired.get(name, 0) + 1
            # Best-effort breadcrumb; os._exit will NOT flush it, and
            # that is correct — a real power cut doesn't either.
            try:
                from ..logger import Logger
                Logger.get().info(
                    f"faultinject: crash point {name} fired — "
                    f"exiting {self.CRASH_EXIT_CODE}", "faultinject")
            except Exception:
                pass
            self._exit(self.CRASH_EXIT_CODE)


# The process-wide injector every hook point shares.
FAULTS = FaultInjector()
