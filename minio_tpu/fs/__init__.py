from .backend import FSObjects

__all__ = ["FSObjects"]
