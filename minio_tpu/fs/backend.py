"""Single-disk POSIX ObjectLayer — no erasure coding (ref FSObjects,
cmd/fs-v1.go:53; metadata cmd/fs-v1-metadata.go; multipart
cmd/fs-v1-multipart.go).

Layout under one root directory:
    <root>/<bucket>/<object>                          object data (plain file)
    <root>/.minio.sys/buckets/<bucket>/<object>/fs.json   per-object metadata
    <root>/.minio.sys/tmp/                            staging for atomic commit
    <root>/.minio.sys/multipart/<obj-hash>/<upload_id>/   part files + session

Like the reference FS backend, versioning APIs are not supported
(ref cmd/fs-v1.go:1090,1444 return NotImplemented); delete removes the
object, puts overwrite in place via temp-write + rename.
"""

from __future__ import annotations

import errno
import hashlib
import json
import os
import shutil
import time
import uuid

from ..erasure.engine import (BucketExists, BucketNotFound,
                              MethodNotAllowed, ObjectInfo, ObjectNotFound)
from ..erasure.multipart import (InvalidPart, MIN_PART_SIZE, PartTooSmall,
                                 UploadNotFound, multipart_etag)
from ..storage.metadata import ObjectPartInfo

META_DIR = ".minio.sys"
_RESERVED = {META_DIR}


def _valid_bucket(bucket: str) -> bool:
    return (bucket not in _RESERVED and bucket == os.path.basename(bucket)
            and bucket not in ("", ".", ".."))


class ParentIsObject(Exception):
    """A parent prefix of the key already exists as an object, or the
    key itself is an existing prefix (ref errFileParentIsFile /
    parentDirIsObject, cmd/fs-v1.go:1067)."""


class FSObjects:
    """Filesystem ObjectLayer over a single directory (no EC, no quorum)."""

    # The versioning APIs are unsupported (ref cmd/fs-v1.go:1090,1444).
    supports_versioning = False

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(os.path.join(self.root, META_DIR, "tmp"), exist_ok=True)
        os.makedirs(os.path.join(self.root, META_DIR, "buckets"),
                    exist_ok=True)
        os.makedirs(os.path.join(self.root, META_DIR, "multipart"),
                    exist_ok=True)
        # Single meta "disk" so IAM/bucket-metadata ConfigStores work
        # unchanged on FS deployments (ref .minio.sys reuse) — and so
        # admin metrics/health that iterate set.disks see one drive.
        from ..storage.xl import XLStorage
        self.meta_disk = XLStorage(self.root)
        self.disks = [self.meta_disk]
        self.k, self.m = 1, 0

    # -- paths ------------------------------------------------------------

    def _bucket_dir(self, bucket: str) -> str:
        return os.path.join(self.root, bucket)

    def _obj_path(self, bucket: str, object_name: str) -> str:
        p = os.path.normpath(os.path.join(self._bucket_dir(bucket),
                                          *object_name.split("/")))
        if not p.startswith(self._bucket_dir(bucket) + os.sep):
            raise ObjectNotFound(object_name)
        return p

    def _meta_path(self, bucket: str, object_name: str) -> str:
        return os.path.join(self.root, META_DIR, "buckets", bucket,
                            *object_name.split("/"), "fs.json")

    def _tmp_path(self) -> str:
        return os.path.join(self.root, META_DIR, "tmp", uuid.uuid4().hex)

    def _check_bucket(self, bucket: str) -> None:
        if not _valid_bucket(bucket):
            raise BucketNotFound(bucket)
        if not os.path.isdir(self._bucket_dir(bucket)):
            raise BucketNotFound(bucket)

    def _check_key_placement(self, bucket: str, dst: str) -> None:
        """Reject parent/child key conflicts the POSIX namespace cannot
        hold: 'a' as a file forbids 'a/b', and 'a/' as a prefix forbids
        object 'a' (ref parentDirIsObject, cmd/fs-v1.go:1067)."""
        if os.path.isdir(dst):
            raise ParentIsObject(dst)
        p = os.path.dirname(dst)
        stop = self._bucket_dir(bucket)
        while p != stop:
            if os.path.isfile(p):
                raise ParentIsObject(p)
            p = os.path.dirname(p)

    # -- buckets ----------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        if not _valid_bucket(bucket):
            raise BucketNotFound(bucket)
        d = self._bucket_dir(bucket)
        if os.path.isdir(d):
            raise BucketExists(bucket)
        os.makedirs(d)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self._check_bucket(bucket)
        d = self._bucket_dir(bucket)
        if not force:
            if any(os.scandir(d)):
                raise OSError(errno.ENOTEMPTY, "bucket not empty", bucket)
            os.rmdir(d)
        else:
            shutil.rmtree(d)
        shutil.rmtree(os.path.join(self.root, META_DIR, "buckets", bucket),
                      ignore_errors=True)

    def list_buckets(self) -> list[dict]:
        out = []
        for e in sorted(os.scandir(self.root), key=lambda e: e.name):
            if e.is_dir() and _valid_bucket(e.name):
                out.append({"name": e.name,
                            "created": e.stat().st_mtime})
        return out

    def bucket_exists(self, bucket: str) -> bool:
        return _valid_bucket(bucket) and os.path.isdir(
            self._bucket_dir(bucket))

    # -- objects ----------------------------------------------------------

    supports_streaming_put = True

    def put_object(self, bucket: str, object_name: str, data,
                   metadata: dict | None = None,
                   versioned: bool = False,
                   parity_shards: int | None = None) -> ObjectInfo:
        # parity_shards is an EC knob; a single POSIX disk has no shards.
        if versioned:
            # ref cmd/fs-v1.go:1090: versioned PUT -> NotImplemented
            raise MethodNotAllowed("FS backend does not support versioning")
        from ..utils import streams
        self._check_bucket(bucket)
        reader = streams.ensure_reader(data)
        md5 = None if hasattr(reader, "etag") else hashlib.md5()
        size = 0
        dst = self._obj_path(bucket, object_name)
        self._check_key_placement(bucket, dst)
        tmp = self._tmp_path()
        try:
            # Chunked copy: O(chunk) memory for any object size (the
            # reference streams through fsCreateFile, cmd/fs-v1.go).
            with open(tmp, "wb") as f:
                while chunk := reader.read(1 << 20):
                    if md5 is not None:
                        md5.update(chunk)
                    size += len(chunk)
                    f.write(chunk)
                f.flush()
                os.fsync(f.fileno())
            if hasattr(reader, "verify"):
                reader.verify()
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            os.replace(tmp, dst)  # atomic commit (ref fsRenameFile)
        except (NotADirectoryError, FileExistsError, IsADirectoryError):
            # Lost a race with a conflicting key creation.
            raise ParentIsObject(dst) from None
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        meta = dict(metadata or {})
        meta["etag"] = reader.etag() if md5 is None else md5.hexdigest()
        self._write_fs_json(bucket, object_name, meta, size=size)
        return self.get_object_info(bucket, object_name)

    def _write_fs_json(self, bucket: str, object_name: str, meta: dict,
                       size: int, parts: list[dict] | None = None) -> None:
        mp = self._meta_path(bucket, object_name)
        os.makedirs(os.path.dirname(mp), exist_ok=True)
        doc = {"version": "1.0.2", "meta": meta, "size": size,
               "parts": parts or []}
        tmp = self._tmp_path()
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, mp)

    def _read_fs_json(self, bucket: str, object_name: str) -> dict:
        try:
            with open(self._meta_path(bucket, object_name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            # Objects written out-of-band get defaults
            # (ref defaultFsJSON, cmd/fs-v1.go:897).
            return {"meta": {}, "parts": []}

    def get_object_info(self, bucket: str, object_name: str,
                        version_id: str = "") -> ObjectInfo:
        self._check_bucket(bucket)
        if version_id:
            raise MethodNotAllowed("FS backend does not support versioning")
        p = self._obj_path(bucket, object_name)
        try:
            st = os.stat(p)
        except OSError:
            raise ObjectNotFound(f"{bucket}/{object_name}") from None
        if not os.path.isfile(p):
            raise ObjectNotFound(f"{bucket}/{object_name}")
        doc = self._read_fs_json(bucket, object_name)
        meta = doc.get("meta", {})
        parts = [ObjectPartInfo(number=q["number"], size=q["size"],
                                actual_size=q.get("actual_size", q["size"]),
                                etag=q.get("etag", ""))
                 for q in doc.get("parts", [])]
        return ObjectInfo(bucket=bucket, name=object_name, size=st.st_size,
                          etag=meta.get("etag", ""), mod_time=st.st_mtime,
                          metadata=meta, parts=parts)

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1, version_id: str = "",
                   ) -> tuple[bytes, ObjectInfo]:
        info = self.get_object_info(bucket, object_name,
                                    version_id=version_id)
        if offset < 0 or offset > info.size:
            raise ValueError("invalid range")
        if length < 0:
            length = info.size - offset
        if offset + length > info.size:
            raise ValueError("invalid range")
        with open(self._obj_path(bucket, object_name), "rb") as f:
            f.seek(offset)
            return f.read(length), info

    def get_object_stream(self, bucket: str, object_name: str,
                          offset: int = 0, length: int = -1,
                          version_id: str = ""):
        """(info, chunk iterator) — the FS streaming GET twin of the
        erasure engine's, O(chunk) memory for any range."""
        info = self.get_object_info(bucket, object_name,
                                    version_id=version_id)
        if offset < 0 or offset > info.size:
            raise ValueError("invalid range")
        if length < 0:
            length = info.size - offset
        if offset + length > info.size:
            raise ValueError("invalid range")
        path = self._obj_path(bucket, object_name)

        def gen():
            left = length
            with open(path, "rb") as f:
                f.seek(offset)
                while left > 0:
                    chunk = f.read(min(1 << 20, left))
                    if not chunk:
                        break
                    left -= len(chunk)
                    yield chunk

        return info, gen()

    def delete_object(self, bucket: str, object_name: str,
                      version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        self._check_bucket(bucket)
        if version_id or versioned:
            raise MethodNotAllowed("FS backend does not support versioning")
        p = self._obj_path(bucket, object_name)
        if not os.path.isfile(p):
            raise ObjectNotFound(f"{bucket}/{object_name}")
        os.remove(p)
        self._prune_dirs(os.path.dirname(p), self._bucket_dir(bucket))
        mp = self._meta_path(bucket, object_name)
        shutil.rmtree(os.path.dirname(mp), ignore_errors=True)
        return ObjectInfo(bucket=bucket, name=object_name)

    @staticmethod
    def _prune_dirs(path: str, stop: str) -> None:
        while path != stop:
            try:
                os.rmdir(path)
            except OSError:
                return
            path = os.path.dirname(path)

    def object_exists(self, bucket: str, object_name: str) -> bool:
        try:
            self.get_object_info(bucket, object_name)
            return True
        except (BucketNotFound, ObjectNotFound):
            return False

    def put_object_tags(self, bucket: str, object_name: str, tags: str,
                        version_id: str = "") -> None:
        self.update_object_metadata(bucket, object_name,
                                    {"x-amz-tagging": tags or None},
                                    version_id)

    def update_object_metadata(self, bucket: str, object_name: str,
                               updates: dict, version_id: str = "") -> None:
        """Metadata-only fs.json update; None value deletes the key."""
        info = self.get_object_info(bucket, object_name,
                                    version_id=version_id)
        meta = dict(info.metadata)
        for k, v in updates.items():
            if v is None:
                meta.pop(k, None)
            else:
                meta[k] = v
        doc = self._read_fs_json(bucket, object_name)
        self._write_fs_json(bucket, object_name, meta, size=info.size,
                            parts=doc.get("parts"))

    # -- listing ----------------------------------------------------------

    def walk_object_names(self, bucket: str) -> list[str]:
        self._check_bucket(bucket)
        base = self._bucket_dir(bucket)
        names = []
        for dirpath, _dirs, files in os.walk(base):
            rel = os.path.relpath(dirpath, base)
            for fn in files:
                names.append(fn if rel == "." else
                             "/".join((*rel.split(os.sep), fn)))
        names.sort()
        return names

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000,
                     marker: str = "") -> list[ObjectInfo]:
        out = []
        for name in self.walk_object_names(bucket):
            if prefix and not name.startswith(prefix):
                continue
            if marker and name <= marker:
                continue
            try:
                out.append(self.get_object_info(bucket, name))
            except ObjectNotFound:
                continue
            if len(out) >= max_keys:
                break
        return out

    def list_object_versions(self, bucket: str, prefix: str = "",
                             max_keys: int = 1000,
                             marker: str = "") -> list[ObjectInfo]:
        # ref cmd/fs-v1.go:1444: NotImplemented
        raise MethodNotAllowed("FS backend does not support versioning")

    # -- subsystems -------------------------------------------------------

    @property
    def multipart(self):
        return _FSMultipart(self)

    @property
    def healer(self):
        return _FSHealer()


class _FSHealer:
    """FS has no redundancy: heal is a no-op report (ref FS heal APIs
    return NotImplemented / success-no-op)."""

    def heal_object(self, bucket, object_name, dry_run=False):
        from ..erasure.heal import HealResult
        return HealResult(bucket=bucket, object_name=object_name,
                          total_disks=1, before_ok=1, after_ok=1)

    heal_object_or_queue = heal_object

    def heal_bucket(self, bucket):
        return None

    def heal_all(self):
        return []


class _FSMultipart:
    """Multipart over the FS backend (ref cmd/fs-v1-multipart.go)."""

    def __init__(self, fs: FSObjects):
        self.fs = fs
        self.min_part_size = MIN_PART_SIZE

    def _base(self, bucket: str, object_name: str, upload_id: str) -> str:
        h = hashlib.sha256(f"{bucket}/{object_name}".encode()
                           ).hexdigest()[:16]
        return os.path.join(self.fs.root, META_DIR, "multipart", h,
                            upload_id)

    def new_multipart_upload(self, bucket: str, object_name: str,
                             metadata: dict | None = None) -> str:
        self.fs._check_bucket(bucket)
        upload_id = uuid.uuid4().hex
        base = self._base(bucket, object_name, upload_id)
        os.makedirs(base, exist_ok=True)
        with open(os.path.join(base, "upload.json"), "w") as f:
            json.dump({"bucket": bucket, "object": object_name,
                       "meta": dict(metadata or {}),
                       "created": time.time()}, f)
        return upload_id

    def _load(self, bucket: str, object_name: str, upload_id: str) -> dict:
        base = self._base(bucket, object_name, upload_id)
        try:
            with open(os.path.join(base, "upload.json")) as f:
                return json.load(f)
        except OSError:
            raise UploadNotFound(upload_id) from None

    def get_upload_meta(self, bucket: str, object_name: str,
                        upload_id: str) -> dict:
        return self._load(bucket, object_name, upload_id).get("meta", {})

    def put_object_part(self, bucket: str, object_name: str,
                        upload_id: str, part_number: int,
                        data,
                        actual_size: int | None = None) -> dict:
        """`data` is bytes or a chunk reader — parts stream to disk in
        O(chunk) memory like single PUTs."""
        from ..utils import streams
        if not 1 <= part_number <= 10000:
            raise InvalidPart(f"part number {part_number}")
        self._load(bucket, object_name, upload_id)
        base = self._base(bucket, object_name, upload_id)
        reader = streams.ensure_reader(data)
        md5 = None if hasattr(reader, "etag") else hashlib.md5()
        size = 0
        tmp = self.fs._tmp_path()
        try:
            with open(tmp, "wb") as f:
                while chunk := reader.read(1 << 20):
                    if md5 is not None:
                        md5.update(chunk)
                    size += len(chunk)
                    f.write(chunk)
            if hasattr(reader, "verify"):
                reader.verify()
            os.replace(tmp, os.path.join(base, f"part.{part_number}"))
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        etag = reader.etag() if md5 is None else md5.hexdigest()
        rec = {"number": part_number, "size": size, "etag": etag,
               "actualSize": (actual_size if actual_size is not None
                              else size)}
        with open(os.path.join(base, f"part.{part_number}.json"), "w") as f:
            json.dump(rec, f)
        return {"number": part_number, "size": size, "etag": etag}

    def list_parts(self, bucket: str, object_name: str,
                   upload_id: str) -> list[dict]:
        self._load(bucket, object_name, upload_id)
        base = self._base(bucket, object_name, upload_id)
        parts = []
        for fn in os.listdir(base):
            if fn.startswith("part.") and fn.endswith(".json"):
                with open(os.path.join(base, fn)) as f:
                    parts.append(json.load(f))
        parts.sort(key=lambda p: p["number"])
        return parts

    def list_uploads(self, bucket: str, prefix: str = "") -> list[dict]:
        self.fs._check_bucket(bucket)
        root = os.path.join(self.fs.root, META_DIR, "multipart")
        out = []
        for dirpath, _dirs, files in os.walk(root):
            if "upload.json" not in files:
                continue
            with open(os.path.join(dirpath, "upload.json")) as f:
                rec = json.load(f)
            if rec.get("bucket") != bucket:
                continue
            if prefix and not rec.get("object", "").startswith(prefix):
                continue
            out.append({"object": rec["object"],
                        "upload_id": os.path.basename(dirpath),
                        "created": rec.get("created", 0)})
        out.sort(key=lambda u: (u["object"], u["upload_id"]))
        return out

    def complete_multipart_upload(self, bucket: str, object_name: str,
                                  upload_id: str,
                                  parts: list[tuple[int, str]],
                                  ) -> ObjectInfo:
        rec = self._load(bucket, object_name, upload_id)
        have = {p["number"]: p for p in self.list_parts(
            bucket, object_name, upload_id)}
        base = self._base(bucket, object_name, upload_id)

        if not parts:
            raise InvalidPart("empty part list")
        etags, infos = [], []
        prev = 0
        for i, (num, etag) in enumerate(parts):
            if num <= prev:
                raise InvalidPart("parts not in ascending order")
            prev = num
            p = have.get(num)
            if p is None or p["etag"].strip('"') != etag.strip('"'):
                raise InvalidPart(f"part {num}")
            logical = p.get("actualSize", p["size"])
            if i < len(parts) - 1 and logical < self.min_part_size:
                raise PartTooSmall(f"part {num}")
            etags.append(p["etag"])
            infos.append(p)

        dst = self.fs._obj_path(bucket, object_name)
        self.fs._check_key_placement(bucket, dst)
        tmp = self.fs._tmp_path()
        total = 0
        try:
            with open(tmp, "wb") as out:
                for p in infos:
                    with open(os.path.join(base, f"part.{p['number']}"),
                              "rb") as f:
                        shutil.copyfileobj(f, out)
                    total += p["size"]
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            os.replace(tmp, dst)
        except (NotADirectoryError, FileExistsError, IsADirectoryError):
            raise ParentIsObject(dst) from None
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

        meta = dict(rec.get("meta", {}))
        meta["etag"] = multipart_etag(etags)
        self.fs._write_fs_json(
            bucket, object_name, meta, size=total,
            parts=[{"number": p["number"], "size": p["size"],
                    "actual_size": p.get("actualSize", p["size"]),
                    "etag": p["etag"]} for p in infos])
        self._cleanup(bucket, object_name, upload_id)
        return self.fs.get_object_info(bucket, object_name)

    def abort_multipart_upload(self, bucket: str, object_name: str,
                               upload_id: str) -> None:
        self._load(bucket, object_name, upload_id)
        self._cleanup(bucket, object_name, upload_id)

    def _cleanup(self, bucket: str, object_name: str,
                 upload_id: str) -> None:
        base = self._base(bucket, object_name, upload_id)
        shutil.rmtree(base, ignore_errors=True)
        try:
            os.rmdir(os.path.dirname(base))
        except OSError:
            pass
