"""Gateway backends: serve the S3 API over foreign storage (ref
Gateway interface, cmd/gateway-interface.go:34 — NewGatewayLayer(creds)
returns an ObjectLayer; backends cmd/gateway/{nas,s3,...})."""

from .cloud import AzureGateway, GCSGateway, HDFSGateway  # noqa: F401
from .nas import NASGateway  # noqa: F401
from .s3 import S3Gateway  # noqa: F401
