"""Cloud gateways: the S3 front end over Azure Blob, Google Cloud
Storage, and HDFS (ref cmd/gateway/azure/gateway-azure.go,
cmd/gateway/gcs/gateway-gcs.go, cmd/gateway/hdfs/gateway-hdfs.go —
together ~7k LoC of SDK plumbing; here each backend is a small REST
client over its actual wire API, sharing one ObjectLayer adapter).

Shared shape: `_BlobGatewayLayer` implements the ObjectLayer contract
(same surface as gateway/s3.S3GatewayLayer) on top of nine primitive
backend operations. Multipart uploads stage parts LOCALLY and commit
as one upload — the reference's azure/gcs gateways likewise emulate
multipart on backends whose native chunk APIs don't match S3 part
semantics. Tags live in the local metadata dir (no upstream analog).

Backends:
  AzureBlobBackend  Blob REST API, SharedKey authorization
  GCSBackend        GCS JSON API, Bearer-token (or anonymous) auth
  HDFSBackend       WebHDFS REST, one-redirect CREATE/OPEN
"""

from __future__ import annotations

import base64
import email.utils
import hashlib
import hmac
import http.client
import json
import os
import time
import urllib.parse

from ..erasure.engine import (BucketExists, BucketNotFound, ObjectInfo,
                              ObjectNotFound)
from .s3 import (GatewayUnsupported, _GatewayHealer, _parse_http_date,
                 _parse_iso)


def _http(host: str, port: int, https: bool, timeout: float = 30.0):
    cls = http.client.HTTPSConnection if https else \
        http.client.HTTPConnection
    return cls(host, port, timeout=timeout)


class _Resp:
    def __init__(self, status: int, headers: dict, body: bytes):
        self.status = status
        self.headers = headers
        self.body = body


def _request(host, port, https, method, path, query="", body=b"",
             headers=None) -> _Resp:
    conn = _http(host, port, https)
    try:
        url = path + (f"?{query}" if query else "")
        conn.request(method, url, body=body, headers=headers or {})
        r = conn.getresponse()
        return _Resp(r.status,
                     {k.lower(): v for k, v in r.getheaders()},
                     r.read())
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# Azure Blob (SharedKey)


class AzureBlobBackend:
    """Azure Blob REST: containers=buckets, block blobs=objects
    (ref gateway-azure.go; auth per 'Authorize with Shared Key')."""

    def __init__(self, host: str, port: int, account: str, key_b64: str,
                 https: bool = False):
        self.host, self.port, self.https = host, port, https
        self.account = account
        self.key = base64.b64decode(key_b64) if key_b64 else b""

    def _auth(self, method, path, query_pairs, headers, body_len):
        # Canonicalized headers: x-ms-* sorted; canonicalized resource:
        # /account/path plus sorted query params (one per line).
        ms = sorted((k.lower(), v) for k, v in headers.items()
                    if k.lower().startswith("x-ms-"))
        canon_headers = "".join(f"{k}:{v}\n" for k, v in ms)
        canon_res = f"/{self.account}{path}"
        for k in sorted(dict(query_pairs)):
            canon_res += f"\n{k}:{dict(query_pairs)[k]}"
        sts = "\n".join([
            method, "", "",                      # content-encoding/lang
            str(body_len) if body_len else "",   # content-length
            "", headers.get("content-type", ""), "", "", "", "", "", "",
            canon_headers + canon_res])
        sig = base64.b64encode(hmac.new(
            self.key, sts.encode(), hashlib.sha256).digest()).decode()
        return f"SharedKey {self.account}:{sig}"

    def _call(self, method, path, query_pairs=(), body=b"",
              extra=None) -> _Resp:
        headers = {"x-ms-date": email.utils.formatdate(usegmt=True),
                   "x-ms-version": "2021-08-06"}
        headers.update(extra or {})
        if body:
            headers["Content-Length"] = str(len(body))
        if self.key:
            headers["Authorization"] = self._auth(
                method, path, query_pairs, headers, len(body))
        query = urllib.parse.urlencode(list(query_pairs))
        return _request(self.host, self.port, self.https, method, path,
                        query, body, headers)

    @staticmethod
    def _blob_path(bucket, key):
        return f"/{bucket}/{urllib.parse.quote(key, safe='/-_.~')}"

    def make_bucket(self, b):
        r = self._call("PUT", f"/{b}", (("restype", "container"),))
        if r.status == 409:
            raise BucketExists(b)
        if r.status // 100 != 2:
            raise IOError(f"azure create container: {r.status}")

    def delete_bucket(self, b):
        r = self._call("DELETE", f"/{b}", (("restype", "container"),))
        if r.status == 404:
            raise BucketNotFound(b)
        if r.status // 100 != 2:
            raise IOError(f"azure delete container: {r.status}")

    def list_buckets(self):
        r = self._call("GET", "/", (("comp", "list"),))
        if r.status != 200:
            raise IOError(f"azure list containers: {r.status}")
        import xml.etree.ElementTree as ET
        out = []
        for c in ET.fromstring(r.body).iter("Container"):
            out.append({"name": c.findtext("Name") or "",
                        "created": _parse_http_date(
                            c.findtext(".//Last-Modified") or "")})
        return out

    def bucket_exists(self, b):
        return self._call("HEAD", f"/{b}",
                          (("restype", "container"),)).status == 200

    def put(self, b, k, data, content_type):
        r = self._call("PUT", self._blob_path(b, k), body=data, extra={
            "x-ms-blob-type": "BlockBlob",
            "content-type": content_type or "application/octet-stream"})
        if r.status == 404:
            raise BucketNotFound(b)
        if r.status // 100 != 2:
            raise IOError(f"azure put blob: {r.status}")
        return r.headers.get("etag", "").strip('"')

    def get(self, b, k, offset, length):
        extra = {}
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            extra["x-ms-range"] = f"bytes={offset}-{end}"
        r = self._call("GET", self._blob_path(b, k), extra=extra)
        if r.status == 404:
            raise ObjectNotFound(f"{b}/{k}")
        if r.status // 100 != 2:
            raise IOError(f"azure get blob: {r.status}")
        return r.body, {
            "etag": r.headers.get("etag", "").strip('"'),
            "mtime": _parse_http_date(
                r.headers.get("last-modified", "")),
            "content-type": r.headers.get("content-type", "")}

    def head(self, b, k):
        r = self._call("HEAD", self._blob_path(b, k))
        if r.status == 404:
            raise ObjectNotFound(f"{b}/{k}")
        if r.status // 100 != 2:
            raise IOError(f"azure head blob: {r.status}")
        return (int(r.headers.get("content-length", 0)),
                _parse_http_date(r.headers.get("last-modified", "")),
                r.headers.get("etag", "").strip('"'),
                r.headers.get("content-type", ""))

    def delete(self, b, k):
        r = self._call("DELETE", self._blob_path(b, k))
        if r.status not in (200, 202, 204, 404):
            raise IOError(f"azure delete blob: {r.status}")

    def list(self, b, prefix):
        import xml.etree.ElementTree as ET
        out = []
        marker = ""
        while True:
            pairs = [("restype", "container"), ("comp", "list")]
            if prefix:
                pairs.append(("prefix", prefix))
            if marker:
                pairs.append(("marker", marker))
            r = self._call("GET", f"/{b}", tuple(pairs))
            if r.status == 404:
                raise BucketNotFound(b)
            if r.status != 200:
                raise IOError(f"azure list blobs: {r.status}")
            doc = ET.fromstring(r.body)
            for blob in doc.iter("Blob"):
                props = blob.find("Properties")
                out.append((
                    blob.findtext("Name") or "",
                    int(props.findtext("Content-Length") or "0")
                    if props is not None else 0,
                    _parse_http_date(
                        props.findtext("Last-Modified") or "")
                    if props is not None else 0.0,
                    (props.findtext("Etag") or "").strip('"')
                    if props is not None else ""))
            marker = doc.findtext("NextMarker") or ""
            if not marker:
                return out


# ---------------------------------------------------------------------------
# Google Cloud Storage (JSON API)


class GCSBackend:
    """GCS JSON API (ref gateway-gcs.go; storage/v1 + upload/storage/v1
    media uploads). Auth: Bearer token (MINIO_GCS_TOKEN) — anonymous
    against emulators/fakes."""

    def __init__(self, host: str, port: int, project: str,
                 token: str = "", https: bool = False):
        self.host, self.port, self.https = host, port, https
        self.project = project
        self.token = token

    def _hdrs(self, extra=None):
        h = dict(extra or {})
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _call(self, method, path, query="", body=b"", extra=None):
        return _request(self.host, self.port, self.https, method, path,
                        query, body, self._hdrs(extra))

    @staticmethod
    def _obj(key):
        return urllib.parse.quote(key, safe="")

    def make_bucket(self, b):
        r = self._call("POST", "/storage/v1/b",
                       query=urllib.parse.urlencode(
                           {"project": self.project}),
                       body=json.dumps({"name": b}).encode(),
                       extra={"Content-Type": "application/json"})
        if r.status == 409:
            raise BucketExists(b)
        if r.status // 100 != 2:
            raise IOError(f"gcs insert bucket: {r.status}")

    def delete_bucket(self, b):
        r = self._call("DELETE", f"/storage/v1/b/{b}")
        if r.status == 404:
            raise BucketNotFound(b)
        if r.status == 409:
            raise BucketExists(b)  # not empty
        if r.status // 100 != 2:
            raise IOError(f"gcs delete bucket: {r.status}")

    def list_buckets(self):
        r = self._call("GET", "/storage/v1/b",
                       query=urllib.parse.urlencode(
                           {"project": self.project}))
        if r.status != 200:
            raise IOError(f"gcs list buckets: {r.status}")
        doc = json.loads(r.body or b"{}")
        return [{"name": it.get("name", ""),
                 "created": _parse_iso(it.get("timeCreated", ""))}
                for it in doc.get("items", [])]

    def bucket_exists(self, b):
        return self._call("GET", f"/storage/v1/b/{b}").status == 200

    def put(self, b, k, data, content_type):
        q = urllib.parse.urlencode({"uploadType": "media", "name": k})
        r = self._call("POST", f"/upload/storage/v1/b/{b}/o", query=q,
                       body=data,
                       extra={"Content-Type": content_type
                              or "application/octet-stream"})
        if r.status == 404:
            raise BucketNotFound(b)
        if r.status // 100 != 2:
            raise IOError(f"gcs insert object: {r.status}")
        return json.loads(r.body or b"{}").get("etag", "")

    def get(self, b, k, offset, length):
        extra = {}
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            extra["Range"] = f"bytes={offset}-{end}"
        r = self._call("GET", f"/storage/v1/b/{b}/o/{self._obj(k)}",
                       query="alt=media", extra=extra)
        if r.status == 404:
            raise ObjectNotFound(f"{b}/{k}")
        if r.status // 100 != 2:
            raise IOError(f"gcs get object: {r.status}")
        info = {}
        if r.headers.get("etag"):
            info = {"etag": r.headers["etag"].strip('"'),
                    "mtime": _parse_http_date(
                        r.headers.get("last-modified", "")),
                    "content-type": r.headers.get("content-type", "")}
        return r.body, info

    def head(self, b, k):
        r = self._call("GET", f"/storage/v1/b/{b}/o/{self._obj(k)}")
        if r.status == 404:
            raise ObjectNotFound(f"{b}/{k}")
        if r.status != 200:
            raise IOError(f"gcs stat object: {r.status}")
        doc = json.loads(r.body or b"{}")
        return (int(doc.get("size", 0)),
                _parse_iso(doc.get("updated", "")),
                doc.get("etag", ""),
                doc.get("contentType", ""))

    def delete(self, b, k):
        r = self._call("DELETE",
                       f"/storage/v1/b/{b}/o/{self._obj(k)}")
        if r.status not in (200, 204, 404):
            raise IOError(f"gcs delete object: {r.status}")

    def list(self, b, prefix):
        out = []
        token = ""
        while True:
            q = {}
            if prefix:
                q["prefix"] = prefix
            if token:
                q["pageToken"] = token
            r = self._call("GET", f"/storage/v1/b/{b}/o",
                           query=urllib.parse.urlencode(q))
            if r.status == 404:
                raise BucketNotFound(b)
            if r.status != 200:
                raise IOError(f"gcs list objects: {r.status}")
            doc = json.loads(r.body or b"{}")
            out.extend(
                (it.get("name", ""), int(it.get("size", 0)),
                 _parse_iso(it.get("updated", "")), it.get("etag", ""))
                for it in doc.get("items", []))
            token = doc.get("nextPageToken", "")
            if not token:
                return out


# ---------------------------------------------------------------------------
# HDFS (WebHDFS)


class HDFSBackend:
    """WebHDFS REST (ref gateway-hdfs.go maps buckets to directories
    under a root path). CREATE/OPEN follow one NameNode->DataNode
    redirect, as the protocol specifies."""

    def __init__(self, host: str, port: int, root: str = "/minio-tpu",
                 user: str = "minio", https: bool = False):
        self.host, self.port, self.https = host, port, https
        self.root = root.rstrip("/")
        self.user = user

    def _path(self, b, k=""):
        p = f"{self.root}/{b}"
        if k:
            p += "/" + k
        return "/webhdfs/v1" + urllib.parse.quote(p, safe="/-_.~")

    def _call(self, method, path, op, params=None, body=b"",
              follow=True, body_after_redirect=False) -> _Resp:
        q = {"op": op, "user.name": self.user}
        q.update(params or {})
        # WebHDFS CREATE/APPEND: the NameNode request carries NO data —
        # it answers 307 with the DataNode location, which gets the
        # body (sending it twice would double every PUT's wire cost).
        first_body = b"" if body_after_redirect else body
        r = _request(self.host, self.port, self.https, method, path,
                     urllib.parse.urlencode(q), first_body)
        if follow and r.status in (307, 302):
            loc = urllib.parse.urlsplit(r.headers.get("location", ""))
            r = _request(loc.hostname or self.host,
                         loc.port or self.port, self.https, method,
                         loc.path, loc.query, body)
        return r

    def make_bucket(self, b):
        st = self._call("GET", self._path(b), "GETFILESTATUS",
                        follow=False)
        if st.status == 200:
            raise BucketExists(b)
        r = self._call("PUT", self._path(b), "MKDIRS")
        if r.status != 200:
            raise IOError(f"hdfs mkdirs: {r.status}")

    def delete_bucket(self, b):
        if self.list(b, ""):
            raise BucketExists(b)  # not empty
        r = self._call("DELETE", self._path(b), "DELETE",
                       {"recursive": "true"})
        if r.status != 200:
            raise IOError(f"hdfs delete: {r.status}")

    def list_buckets(self):
        r = self._call("GET", "/webhdfs/v1" + (self.root or "/"),
                       "LISTSTATUS")
        if r.status == 404:
            return []
        doc = json.loads(r.body or b"{}")
        out = []
        for st in doc.get("FileStatuses", {}).get("FileStatus", []):
            if st.get("type") == "DIRECTORY":
                out.append({"name": st.get("pathSuffix", ""),
                            "created": st.get("modificationTime",
                                              0) / 1000.0})
        return out

    def bucket_exists(self, b):
        r = self._call("GET", self._path(b), "GETFILESTATUS",
                       follow=False)
        return r.status == 200

    def put(self, b, k, data, content_type):
        r = self._call("PUT", self._path(b, k), "CREATE",
                       {"overwrite": "true"}, body=data,
                       body_after_redirect=True)
        if r.status not in (200, 201):
            raise IOError(f"hdfs create: {r.status}")
        return hashlib.md5(data).hexdigest()

    def get(self, b, k, offset, length):
        params = {}
        if offset:
            params["offset"] = str(offset)
        if length >= 0:
            params["length"] = str(length)
        r = self._call("GET", self._path(b, k), "OPEN", params)
        if r.status == 404:
            raise ObjectNotFound(f"{b}/{k}")
        if r.status != 200:
            raise IOError(f"hdfs open: {r.status}")
        return r.body, {}

    def head(self, b, k):
        r = self._call("GET", self._path(b, k), "GETFILESTATUS",
                       follow=False)
        if r.status == 404:
            raise ObjectNotFound(f"{b}/{k}")
        if r.status != 200:
            raise IOError(f"hdfs stat: {r.status}")
        st = json.loads(r.body).get("FileStatus", {})
        if st.get("type") == "DIRECTORY":
            raise ObjectNotFound(f"{b}/{k}")
        return (int(st.get("length", 0)),
                st.get("modificationTime", 0) / 1000.0, "", "")

    def delete(self, b, k):
        self._call("DELETE", self._path(b, k), "DELETE")

    def list(self, b, prefix):
        """Recursive walk from the bucket dir (WebHDFS lists one level;
        object keys with '/' become subdirectories, like the
        reference's hdfs gateway)."""
        out = []
        stack = [""]
        while stack:
            rel = stack.pop()
            path = self._path(b, rel) if rel else self._path(b)
            r = self._call("GET", path, "LISTSTATUS", follow=False)
            if r.status == 404:
                if not rel:
                    raise BucketNotFound(b)
                continue
            doc = json.loads(r.body or b"{}")
            for st in doc.get("FileStatuses", {}).get("FileStatus", []):
                name = st.get("pathSuffix", "")
                full = f"{rel}/{name}" if rel else name
                if st.get("type") == "DIRECTORY":
                    # Prune subtrees that can neither extend nor be
                    # extended by the prefix.
                    subdir = full + "/"
                    if (not prefix or subdir.startswith(prefix)
                            or prefix.startswith(subdir)):
                        stack.append(full)
                elif full.startswith(prefix):
                    out.append((full, int(st.get("length", 0)),
                                st.get("modificationTime", 0) / 1000.0,
                                ""))
        return sorted(out)


# ---------------------------------------------------------------------------
# shared ObjectLayer adapter


class _BlobGatewayLayer:
    """ObjectLayer over a blob-store backend (same contract as
    gateway/s3.S3GatewayLayer; consumed by S3Server unchanged)."""

    supports_versioning = False
    supports_transforms = False

    def __init__(self, backend, meta_dir: str):
        self.backend = backend
        from ..storage.xl import XLStorage
        os.makedirs(meta_dir, exist_ok=True)
        self.meta_disk = XLStorage(meta_dir)
        self.disks = [self.meta_disk]
        self.k, self.m = 1, 0
        self.meta_dir = meta_dir
        self.multipart = _LocalStageMultipart(self)
        self.healer = _GatewayHealer()

    # -- buckets --------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        self.backend.make_bucket(bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        self.backend.delete_bucket(bucket)

    def list_buckets(self) -> list[dict]:
        return self.backend.list_buckets()

    def bucket_exists(self, bucket: str) -> bool:
        return self.backend.bucket_exists(bucket)

    # -- objects --------------------------------------------------------

    def put_object(self, bucket: str, object_name: str, data,
                   metadata: dict | None = None,
                   versioned: bool = False,
                   parity_shards: int | None = None) -> ObjectInfo:
        if versioned:
            raise GatewayUnsupported("gateway: no versioning")
        if not isinstance(data, (bytes, bytearray)):
            from ..utils.streams import ensure_reader
            r = ensure_reader(data)
            chunks = []
            while chunk := r.read(1 << 20):
                chunks.append(chunk)
            data = b"".join(chunks)
        meta = metadata or {}
        etag = self.backend.put(bucket, object_name, bytes(data),
                                meta.get("content-type", ""))
        if meta.get("x-amz-tagging"):
            self.put_object_tags(bucket, object_name,
                                 meta["x-amz-tagging"])
        return ObjectInfo(bucket=bucket, name=object_name,
                          size=len(data),
                          etag=etag or hashlib.md5(data).hexdigest(),
                          mod_time=time.time(), metadata=dict(meta))

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1, version_id: str = "",
                   ) -> tuple[bytes, ObjectInfo]:
        body, binfo = self.backend.get(bucket, object_name, offset,
                                       length)
        if binfo:
            # ObjectInfo from the SAME response (one round trip, no
            # head/get race; same as gateway/s3.py).
            info = ObjectInfo(
                bucket=bucket, name=object_name, size=len(body),
                etag=binfo.get("etag", ""),
                mod_time=binfo.get("mtime", 0.0),
                metadata={"content-type": binfo.get("content-type")
                          or "application/octet-stream"})
        else:
            info = self.get_object_info(bucket, object_name)
            info.size = len(body) if (offset or length >= 0) \
                else info.size
        return body, info

    def get_object_info(self, bucket: str, object_name: str,
                        version_id: str = "") -> ObjectInfo:
        try:
            size, mtime, etag, ctype = self.backend.head(bucket,
                                                         object_name)
        except ObjectNotFound:
            if not self.bucket_exists(bucket):
                raise BucketNotFound(bucket)
            raise
        meta = {"content-type": ctype or "application/octet-stream"}
        return ObjectInfo(bucket=bucket, name=object_name, size=size,
                          etag=etag, mod_time=mtime, metadata=meta)

    def delete_object(self, bucket: str, object_name: str,
                      version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        self.backend.delete(bucket, object_name)
        self._tags_store(bucket, object_name, None)
        return ObjectInfo(bucket=bucket, name=object_name)

    def object_exists(self, bucket: str, object_name: str) -> bool:
        try:
            self.backend.head(bucket, object_name)
            return True
        except Exception:
            return False

    # -- tags (local store: no upstream analog) ------------------------

    def _tags_path(self, bucket, key):
        digest = hashlib.sha256(f"{bucket}/{key}".encode()).hexdigest()
        return os.path.join(self.meta_dir, "tags", digest + ".json")

    def _tags_store(self, bucket, key, tags: str | None):
        path = self._tags_path(bucket, key)
        if tags is None:
            try:
                os.remove(path)
            except OSError:
                pass
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"tags": tags}, f)

    def put_object_tags(self, bucket: str, object_name: str, tags: str,
                        version_id: str = "") -> None:
        self.get_object_info(bucket, object_name)  # must exist
        self._tags_store(bucket, object_name, tags or None)

    def get_object_tags(self, bucket: str, object_name: str,
                        version_id: str = "") -> str:
        try:
            with open(self._tags_path(bucket, object_name)) as f:
                return json.load(f).get("tags", "")
        except OSError:
            return ""

    def update_object_metadata(self, bucket: str, object_name: str,
                               updates: dict,
                               version_id: str = "") -> None:
        raise GatewayUnsupported("gateway: metadata rewrite")

    # -- listing --------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000,
                     marker: str = "") -> list[ObjectInfo]:
        out = []
        for name, size, mtime, etag in self.backend.list(bucket, prefix):
            if marker and name <= marker:
                continue
            out.append(ObjectInfo(bucket=bucket, name=name, size=size,
                                  etag=etag, mod_time=mtime))
            if len(out) >= max_keys:
                break
        return out

    def list_object_versions(self, bucket: str, prefix: str = "",
                             max_keys: int = 1000,
                             marker: str = "") -> list[ObjectInfo]:
        raise GatewayUnsupported("gateway: versions listing")

    def walk_object_names(self, bucket: str) -> list[str]:
        return [o.name for o in self.list_objects(bucket,
                                                  max_keys=1_000_000)]


class _LocalStageMultipart:
    """Multipart emulation: parts stage locally; complete concatenates
    and issues ONE backend put (ref azure/gcs gateway multipart
    emulation over block lists / compose — same observable contract)."""

    def __init__(self, layer: _BlobGatewayLayer):
        self.layer = layer
        self.dir = os.path.join(layer.meta_dir, "uploads")

    def _base(self, bucket, key, upload_id):
        digest = hashlib.sha256(f"{bucket}/{key}".encode()).hexdigest()
        return os.path.join(self.dir, digest, upload_id)

    def new_multipart_upload(self, bucket, object_name,
                             metadata=None) -> str:
        if not self.layer.bucket_exists(bucket):
            raise BucketNotFound(bucket)
        import uuid
        upload_id = uuid.uuid4().hex
        base = self._base(bucket, object_name, upload_id)
        os.makedirs(base, exist_ok=True)
        with open(os.path.join(base, "meta.json"), "w") as f:
            json.dump({"meta": dict(metadata or {})}, f)
        return upload_id

    def _check(self, bucket, object_name, upload_id) -> str:
        from ..erasure.multipart import UploadNotFound
        base = self._base(bucket, object_name, upload_id)
        if not os.path.isdir(base):
            raise UploadNotFound(upload_id)
        return base

    def get_upload_meta(self, bucket, object_name, upload_id) -> dict:
        base = self._check(bucket, object_name, upload_id)
        with open(os.path.join(base, "meta.json")) as f:
            return json.load(f).get("meta", {})

    def put_object_part(self, bucket, object_name, upload_id,
                        part_number, data, actual_size=None) -> dict:
        base = self._check(bucket, object_name, upload_id)
        if not isinstance(data, (bytes, bytearray)):
            from ..utils.streams import ensure_reader
            r = ensure_reader(data)
            chunks = []
            while chunk := r.read(1 << 20):
                chunks.append(chunk)
            data = b"".join(chunks)
        etag = hashlib.md5(data).hexdigest()
        with open(os.path.join(base, f"part.{part_number}"), "wb") as f:
            f.write(data)
        # Sidecar records size+etag so ListParts/Complete never re-read
        # and re-hash staged bytes.
        with open(os.path.join(base, f"part.{part_number}.info"),
                  "w") as f:
            json.dump({"size": len(data), "etag": etag}, f)
        return {"number": part_number, "size": len(data), "etag": etag}

    def list_parts(self, bucket, object_name, upload_id) -> list[dict]:
        base = self._check(bucket, object_name, upload_id)
        out = []
        for name in sorted(os.listdir(base)):
            if name.startswith("part.") and name.endswith(".info"):
                num = int(name.split(".")[1])
                with open(os.path.join(base, name)) as f:
                    rec = json.load(f)
                out.append({"number": num, "size": rec["size"],
                            "etag": rec["etag"]})
        return sorted(out, key=lambda p: p["number"])

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts) -> ObjectInfo:
        from ..erasure.multipart import (InvalidPart, multipart_etag)
        base = self._check(bucket, object_name, upload_id)
        have = {p["number"]: p for p in self.list_parts(
            bucket, object_name, upload_id)}
        blob = bytearray()
        etags = []
        for num, etag in parts:
            p = have.get(num)
            if p is None or p["etag"] != etag.strip('"'):
                raise InvalidPart(f"part {num}")
            with open(os.path.join(base, f"part.{num}"), "rb") as pf:
                blob += pf.read()
            etags.append(p["etag"])
        meta = self.get_upload_meta(bucket, object_name, upload_id)
        info = self.layer.put_object(bucket, object_name, bytes(blob),
                                     metadata=meta)
        info.etag = multipart_etag(etags)
        self.abort_multipart_upload(bucket, object_name, upload_id)
        return info

    def abort_multipart_upload(self, bucket, object_name,
                               upload_id) -> None:
        import shutil
        base = self._check(bucket, object_name, upload_id)
        shutil.rmtree(base, ignore_errors=True)

    def list_uploads(self, bucket, prefix="") -> list[dict]:
        return []  # local staging: ids are opaque; parity with ref gcs


# ---------------------------------------------------------------------------
# gateway entrypoints (ref Gateway interface, cmd/gateway-interface.go)


class AzureGateway:
    name = "azure"

    def __init__(self, host: str, port: int, account: str, key_b64: str,
                 meta_dir: str, https: bool = False):
        self.backend = AzureBlobBackend(host, port, account, key_b64,
                                        https)
        self.meta_dir = meta_dir

    def new_gateway_layer(self):
        return _BlobGatewayLayer(self.backend, self.meta_dir)


class GCSGateway:
    name = "gcs"

    def __init__(self, host: str, port: int, project: str,
                 meta_dir: str, token: str = "", https: bool = False):
        self.backend = GCSBackend(host, port, project, token, https)
        self.meta_dir = meta_dir

    def new_gateway_layer(self):
        return _BlobGatewayLayer(self.backend, self.meta_dir)


class HDFSGateway:
    name = "hdfs"

    def __init__(self, host: str, port: int, meta_dir: str,
                 root: str = "/minio-tpu", user: str = "minio",
                 https: bool = False):
        self.backend = HDFSBackend(host, port, root, user, https)
        self.meta_dir = meta_dir

    def new_gateway_layer(self):
        return _BlobGatewayLayer(self.backend, self.meta_dir)
