"""NAS gateway: the S3 front end over one POSIX mount (ref
cmd/gateway/nas/gateway-nas.go, 121 LoC — it literally returns the FS
ObjectLayer over the given path; so do we)."""

from __future__ import annotations

from ..fs.backend import FSObjects


class NASGateway:
    name = "nas"

    def __init__(self, path: str):
        self.path = path

    def new_gateway_layer(self):
        return FSObjects(self.path)
