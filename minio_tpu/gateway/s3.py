"""S3 gateway: an ObjectLayer backed by a REMOTE S3-compatible store
(ref cmd/gateway/s3/gateway-s3.go — every ObjectLayer method maps to a
minio-go client call against the upstream; here the transport is our
own SigV4 S3Client).

Bucket-scoped configs (policy, notification, ...) live in a LOCAL
metadata directory, as gateways have no `.minio.sys` on the remote.
"""

from __future__ import annotations

import email.utils
import urllib.parse
import xml.etree.ElementTree as ET

from ..erasure.engine import (BucketExists, BucketNotFound,
                              MethodNotAllowed, ObjectInfo,
                              ObjectNotFound)
from ..s3.client import S3Client
from ..storage.metadata import ObjectPartInfo


def _strip_ns(root: ET.Element) -> ET.Element:
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def _parse_http_date(s: str) -> float:
    try:
        return email.utils.parsedate_to_datetime(s).timestamp()
    except (TypeError, ValueError):
        return 0.0


def _parse_iso(s: str) -> float:
    import calendar
    import time as _t
    try:
        return calendar.timegm(_t.strptime(s.split(".")[0].rstrip("Z"),
                                           "%Y-%m-%dT%H:%M:%S"))
    except ValueError:
        return 0.0


class GatewayUnsupported(MethodNotAllowed):
    """Operation has no upstream analog (ref errors like
    NotImplemented in gateway-s3.go)."""


class S3Gateway:
    name = "s3"

    def __init__(self, host: str, port: int, access_key: str,
                 secret_key: str, meta_dir: str):
        self.host, self.port = host, port
        self.access_key, self.secret_key = access_key, secret_key
        self.meta_dir = meta_dir

    def new_gateway_layer(self) -> "S3GatewayLayer":
        return S3GatewayLayer(
            S3Client(self.host, self.port, self.access_key,
                     self.secret_key), self.meta_dir)


class S3GatewayLayer:
    """ObjectLayer over a remote S3 endpoint."""

    supports_versioning = False
    # API-layer SSE/compression envelopes live in backend metadata the
    # upstream would drop; the reference likewise disables local SSE
    # in gateway mode unless the backend handles it.
    supports_transforms = False

    def __init__(self, client: S3Client, meta_dir: str):
        self.client = client
        # Local home for bucket metadata / IAM config stores; also
        # keeps the admin plane's disk iteration meaningful.
        from ..storage.xl import XLStorage
        self.meta_disk = XLStorage(meta_dir)
        self.disks = [self.meta_disk]
        self.k, self.m = 1, 0
        self.multipart = _GatewayMultipart(self)
        self.healer = _GatewayHealer()

    # -- helpers --------------------------------------------------------

    def _raise_for(self, resp, bucket: str, key: str = "") -> None:
        if resp.status == 404:
            if key and b"NoSuchBucket" not in resp.body:
                raise ObjectNotFound(f"{bucket}/{key}")
            raise BucketNotFound(bucket)
        if resp.status == 409:
            raise BucketExists(bucket)
        if resp.status >= 400:
            raise MethodNotAllowed(
                f"upstream {resp.status}: {resp.body[:200]!r}")

    @staticmethod
    def _info_from_headers(bucket: str, key: str, headers: dict,
                           size: int | None = None) -> ObjectInfo:
        meta = {"content-type": headers.get("content-type",
                                            "application/octet-stream")}
        for k, v in headers.items():
            if k.startswith("x-amz-meta-"):
                meta[k] = v
        return ObjectInfo(
            bucket=bucket, name=key,
            size=(size if size is not None
                  else int(headers.get("content-length", 0))),
            etag=headers.get("etag", "").strip('"'),
            mod_time=_parse_http_date(headers.get("last-modified", "")),
            version_id=headers.get("x-amz-version-id", ""),
            metadata=meta)

    # -- buckets --------------------------------------------------------

    def make_bucket(self, bucket: str) -> None:
        self._raise_for(self.client.make_bucket(bucket), bucket)

    def delete_bucket(self, bucket: str, force: bool = False) -> None:
        r = self.client.delete_bucket(bucket)
        if r.status == 409:
            raise BucketExists(bucket)  # not empty, same mapping as FS
        if r.status not in (200, 204):
            self._raise_for(r, bucket)

    def list_buckets(self) -> list[dict]:
        r = self.client.request("GET", "/")
        self._raise_for(r, "")
        out = []
        for b in _strip_ns(ET.fromstring(r.body)).iter("Bucket"):
            out.append({"name": b.findtext("Name") or "",
                        "created": _parse_iso(
                            b.findtext("CreationDate") or "")})
        return out

    def bucket_exists(self, bucket: str) -> bool:
        return self.client.request("HEAD", f"/{bucket}").status == 200

    # -- objects --------------------------------------------------------

    def put_object(self, bucket: str, object_name: str, data: bytes,
                   metadata: dict | None = None,
                   versioned: bool = False,
                   parity_shards: int | None = None) -> ObjectInfo:
        if versioned:
            raise GatewayUnsupported("gateway: no versioning")
        headers = {}
        for k, v in (metadata or {}).items():
            if k.startswith("x-amz-meta-") or k in ("content-type",
                                                    "x-amz-tagging"):
                headers[k] = v
        r = self.client.put_object(bucket, object_name, data,
                                   headers=headers)
        self._raise_for(r, bucket, object_name)
        return self._info_from_headers(bucket, object_name, r.headers,
                                       size=len(data))

    def get_object(self, bucket: str, object_name: str, offset: int = 0,
                   length: int = -1, version_id: str = "",
                   ) -> tuple[bytes, ObjectInfo]:
        headers = {}
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            headers["range"] = f"bytes={offset}-{end}"
        r = self.client.get_object(bucket, object_name, headers=headers)
        self._raise_for(r, bucket, object_name)
        info = self._info_from_headers(bucket, object_name, r.headers)
        info.size = len(r.body) if offset or length >= 0 else info.size
        return r.body, info

    def get_object_info(self, bucket: str, object_name: str,
                        version_id: str = "") -> ObjectInfo:
        r = self.client.head_object(bucket, object_name)
        if r.status == 404:
            # HEAD bodies are empty; probe the bucket to tell
            # NoSuchBucket from NoSuchKey.
            if not self.bucket_exists(bucket):
                raise BucketNotFound(bucket)
            raise ObjectNotFound(f"{bucket}/{object_name}")
        self._raise_for(r, bucket, object_name)
        return self._info_from_headers(bucket, object_name, r.headers)

    def delete_object(self, bucket: str, object_name: str,
                      version_id: str = "",
                      versioned: bool = False) -> ObjectInfo:
        r = self.client.delete_object(bucket, object_name)
        if r.status not in (200, 204):
            self._raise_for(r, bucket, object_name)
        return ObjectInfo(bucket=bucket, name=object_name)

    def object_exists(self, bucket: str, object_name: str) -> bool:
        return self.client.head_object(bucket,
                                       object_name).status == 200

    def put_object_tags(self, bucket: str, object_name: str, tags: str,
                        version_id: str = "") -> None:
        enc = urllib.parse.quote(object_name, safe="/-_.~")
        if not tags:
            r = self.client.request("DELETE", f"/{bucket}/{enc}",
                                    query="tagging")
        else:
            from xml.sax.saxutils import escape
            body = ["<Tagging><TagSet>"]
            for pair in tags.split("&"):
                k, _, v = pair.partition("=")
                body.append(
                    f"<Tag>"
                    f"<Key>{escape(urllib.parse.unquote_plus(k))}</Key>"
                    f"<Value>{escape(urllib.parse.unquote_plus(v))}"
                    f"</Value></Tag>")
            body.append("</TagSet></Tagging>")
            r = self.client.request("PUT", f"/{bucket}/{enc}",
                                    query="tagging",
                                    body="".join(body).encode())
        if r.status not in (200, 204):
            self._raise_for(r, bucket, object_name)

    def get_object_tags(self, bucket: str, object_name: str,
                        version_id: str = "") -> str:
        """Tags live upstream, not in HEAD metadata: fetch them (the
        handler prefers this hook when a layer provides it)."""
        enc = urllib.parse.quote(object_name, safe="/-_.~")
        r = self.client.request("GET", f"/{bucket}/{enc}",
                                query="tagging")
        self._raise_for(r, bucket, object_name)
        pairs = []
        for t in _strip_ns(ET.fromstring(r.body)).iter("Tag"):
            pairs.append(
                f"{urllib.parse.quote_plus(t.findtext('Key') or '')}="
                f"{urllib.parse.quote_plus(t.findtext('Value') or '')}")
        return "&".join(pairs)

    def update_object_metadata(self, bucket: str, object_name: str,
                               updates: dict,
                               version_id: str = "") -> None:
        raise GatewayUnsupported("gateway: metadata rewrite")

    # -- listing --------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000,
                     marker: str = "") -> list[ObjectInfo]:
        out: list[ObjectInfo] = []
        token = ""
        while len(out) < max_keys:
            q = {"list-type": "2",
                 "max-keys": str(min(1000, max_keys - len(out)))}
            if prefix:
                q["prefix"] = prefix
            if token:
                q["continuation-token"] = token
            r = self.client.request(
                "GET", f"/{bucket}", query=urllib.parse.urlencode(q))
            self._raise_for(r, bucket)
            doc = _strip_ns(ET.fromstring(r.body))
            for c in doc.iter("Contents"):
                out.append(ObjectInfo(
                    bucket=bucket, name=c.findtext("Key") or "",
                    size=int(c.findtext("Size") or "0"),
                    etag=(c.findtext("ETag") or "").strip('"'),
                    mod_time=_parse_iso(
                        c.findtext("LastModified") or "")))
            token = doc.findtext("NextContinuationToken") or ""
            if not token:
                break
        return out[:max_keys]

    def list_object_versions(self, bucket: str, prefix: str = "",
                             max_keys: int = 1000,
                             marker: str = "") -> list[ObjectInfo]:
        raise GatewayUnsupported("gateway: versions listing")

    def walk_object_names(self, bucket: str) -> list[str]:
        return [o.name for o in self.list_objects(bucket,
                                                  max_keys=1_000_000)]


class _GatewayMultipart:
    """Multipart pass-through to the upstream (ref gateway-s3.go
    NewMultipartUpload/PutObjectPart/Complete...)."""

    def __init__(self, layer: S3GatewayLayer):
        self.layer = layer
        self.client = layer.client

    def _path(self, bucket, key):
        return f"/{bucket}/{urllib.parse.quote(key, safe='/-_.~')}"

    def new_multipart_upload(self, bucket, object_name,
                             metadata=None) -> str:
        headers = {k: v for k, v in (metadata or {}).items()
                   if k.startswith("x-amz-meta-")
                   or k == "content-type"}
        r = self.client.request("POST", self._path(bucket, object_name),
                                query="uploads", headers=headers)
        self.layer._raise_for(r, bucket, object_name)
        return _strip_ns(ET.fromstring(r.body)).findtext(
            "UploadId") or ""

    def put_object_part(self, bucket, object_name, upload_id,
                        part_number, data, actual_size=None) -> dict:
        from ..erasure.multipart import UploadNotFound
        q = urllib.parse.urlencode({"partNumber": str(part_number),
                                    "uploadId": upload_id})
        r = self.client.request("PUT", self._path(bucket, object_name),
                                query=q, body=data)
        if r.status == 404:
            raise UploadNotFound(upload_id)
        self.layer._raise_for(r, bucket, object_name)
        return {"number": part_number, "size": len(data),
                "etag": r.headers.get("etag", "").strip('"')}

    def list_parts(self, bucket, object_name, upload_id) -> list[dict]:
        from ..erasure.multipart import UploadNotFound
        q = urllib.parse.urlencode({"uploadId": upload_id})
        r = self.client.request("GET", self._path(bucket, object_name),
                                query=q)
        if r.status == 404:
            raise UploadNotFound(upload_id)
        self.layer._raise_for(r, bucket, object_name)
        out = []
        for p in _strip_ns(ET.fromstring(r.body)).iter("Part"):
            out.append({
                "number": int(p.findtext("PartNumber") or "0"),
                "size": int(p.findtext("Size") or "0"),
                "etag": (p.findtext("ETag") or "").strip('"')})
        return out

    def get_upload_meta(self, bucket, object_name, upload_id) -> dict:
        # Upstream holds the metadata; nothing SSE-sealed locally.
        return {}

    def complete_multipart_upload(self, bucket, object_name, upload_id,
                                  parts) -> ObjectInfo:
        from ..erasure.multipart import UploadNotFound
        body = ["<CompleteMultipartUpload>"]
        for num, etag in parts:
            body.append(f"<Part><PartNumber>{num}</PartNumber>"
                        f"<ETag>\"{etag}\"</ETag></Part>")
        body.append("</CompleteMultipartUpload>")
        q = urllib.parse.urlencode({"uploadId": upload_id})
        r = self.client.request("POST", self._path(bucket, object_name),
                                query=q, body="".join(body).encode())
        if r.status == 404:
            raise UploadNotFound(upload_id)
        self.layer._raise_for(r, bucket, object_name)
        doc = _strip_ns(ET.fromstring(r.body))
        # S3 can answer 200 with an <Error> document for Complete.
        if doc.tag == "Error" or not doc.findtext("ETag"):
            raise MethodNotAllowed(
                f"upstream complete failed: {r.body[:200]!r}")
        return ObjectInfo(
            bucket=bucket, name=object_name,
            etag=(doc.findtext("ETag") or "").strip('"'),
            parts=[ObjectPartInfo(number=n, size=0, actual_size=0,
                                  etag=e)
                   for n, e in parts])

    def abort_multipart_upload(self, bucket, object_name,
                               upload_id) -> None:
        from ..erasure.multipart import UploadNotFound
        q = urllib.parse.urlencode({"uploadId": upload_id})
        r = self.client.request("DELETE",
                                self._path(bucket, object_name), query=q)
        if r.status == 404:
            raise UploadNotFound(upload_id)
        if r.status not in (200, 204):
            self.layer._raise_for(r, bucket, object_name)

    def list_uploads(self, bucket, prefix="") -> list[dict]:
        q = {"uploads": ""}
        if prefix:
            q["prefix"] = prefix
        r = self.client.request("GET", f"/{bucket}",
                                query=urllib.parse.urlencode(q))
        self.layer._raise_for(r, bucket)
        out = []
        for u in _strip_ns(ET.fromstring(r.body)).iter("Upload"):
            out.append({
                "object": u.findtext("Key") or "",
                "upload_id": u.findtext("UploadId") or "",
                "created": _parse_iso(u.findtext("Initiated") or "")})
        return out


class _GatewayHealer:
    """Gateways own no shards; healing is a backend concern (ref
    gateway HealObject -> NotImplemented)."""

    def heal_object(self, bucket, object_name, dry_run=False):
        raise GatewayUnsupported("gateway: heal")

    heal_object_or_queue = heal_object

    def heal_bucket(self, bucket):
        raise GatewayUnsupported("gateway: heal")

    def heal_all(self):
        return []
