"""Identity & access: users, groups, canned policies, AWS-compatible
policy evaluation, STS temporary credentials (ref cmd/iam.go:204 IAMSys,
pkg/iam/policy, cmd/sts-handlers.go)."""
