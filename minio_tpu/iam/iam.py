"""IAMSys: users, groups, policy attachment, service accounts, STS temp
credentials — persisted as JSON objects under .minio.sys/config/iam/ on
the cluster's own disks (the reference bootstraps IAM on its own object
store the same way; ref cmd/iam.go:204, cmd/iam-object-store.go).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import threading
import time
from dataclasses import dataclass, field

from ..parallel.quorum import parallel_map
from ..storage import errors as serr
from ..storage.xl import MINIO_META_BUCKET
from .policy import DEFAULT_POLICIES, Policy

IAM_PREFIX = "config/iam"


@dataclass
class UserIdentity:
    access_key: str
    secret_key: str
    status: str = "enabled"          # enabled | disabled
    policies: list[str] = field(default_factory=list)
    groups: list[str] = field(default_factory=list)
    parent: str = ""                 # for service accounts / STS
    session_token: str = ""
    expiration: float = 0.0          # 0 = permanent
    session_policy: dict | None = None

    def to_dict(self) -> dict:
        return {"accessKey": self.access_key,
                "secretKey": self.secret_key,
                "status": self.status, "policies": self.policies,
                "groups": self.groups, "parent": self.parent,
                "expiration": self.expiration,
                "sessionToken": self.session_token,
                "sessionPolicy": self.session_policy}

    @classmethod
    def from_dict(cls, d: dict) -> "UserIdentity":
        return cls(access_key=d["accessKey"], secret_key=d["secretKey"],
                   status=d.get("status", "enabled"),
                   policies=list(d.get("policies", [])),
                   groups=list(d.get("groups", [])),
                   parent=d.get("parent", ""),
                   expiration=d.get("expiration", 0.0),
                   session_token=d.get("sessionToken", ""),
                   session_policy=d.get("sessionPolicy"))

    @property
    def expired(self) -> bool:
        return self.expiration > 0 and time.time() > self.expiration


class ConfigStore:
    """Quorum JSON config storage on the erasure set's disks (the
    system's own object store, ref .minio.sys/config)."""

    def __init__(self, disks: list):
        self.disks = disks

    def _first_success(self, read):
        """Run ``read(disk)`` against healthy disks first, quarantined
        only as a last resort — config reads obey the same hygiene as
        the data plane (obs/drivemon.py quarantine lifecycle). A
        healthy disk answering "not found" is a DEFINITIVE miss
        (config docs are optional — most never exist), so only
        transient failures on every healthy disk justify probing a
        possibly-stalling quarantined drive (availability over
        hygiene). Returns the first successful read, or None."""
        from ..obs.drivemon import DRIVEMON, drive_key
        healthy: list = []
        quarantined: list = []
        for d in self.disks:
            (quarantined if DRIVEMON.is_quarantined(drive_key(d))
             else healthy).append(d)
        definitive_miss = False
        for d in healthy:
            try:
                return read(d)
            except (serr.FileNotFound, serr.VolumeNotFound):
                definitive_miss = True
            except serr.StorageError:
                continue
        if not definitive_miss:
            for d in quarantined:
                try:
                    return read(d)
                except serr.StorageError:
                    continue
        return None

    def save(self, path: str, doc: dict) -> None:
        raw = json.dumps(doc, sort_keys=True).encode()
        _, errs = parallel_map(
            [lambda d=d: d.write_all(MINIO_META_BUCKET, path, raw)
             for d in self.disks])
        ok = sum(1 for e in errs if e is None)
        if ok < len(self.disks) // 2 + 1:
            raise serr.FaultyDisk(f"config write quorum failed: {path}")

    def load(self, path: str) -> dict | None:
        return self._first_success(
            lambda d: json.loads(d.read_all(MINIO_META_BUCKET, path)))

    def delete(self, path: str) -> None:
        parallel_map([lambda d=d: d.delete(MINIO_META_BUCKET, path)
                      for d in self.disks])

    def list(self, prefix: str) -> list[str]:
        out = self._first_success(
            lambda d: [e for e in d.list_dir(MINIO_META_BUCKET, prefix)
                       if not e.endswith("/")])
        return [] if out is None else out


class IAMSys:
    """Identity and policy registry (ref IAMSys, cmd/iam.go:204)."""

    def __init__(self, store: ConfigStore, root_access: str,
                 root_secret: str):
        self.store = store
        self.root_access = root_access
        self.root_secret = root_secret
        self._mu = threading.RLock()
        self.users: dict[str, UserIdentity] = {}
        self.policies: dict[str, Policy] = dict(DEFAULT_POLICIES)
        self.policy_docs: dict[str, dict] = {}
        self.groups: dict[str, dict] = {}  # name -> {members, policies}
        self._sts_key = hashlib.sha256(
            f"sts:{root_secret}".encode()).digest()
        self._last_load = 0.0
        # Fallback freshness poll (seconds). With the peer push wired
        # (distributed mode), the boot path stretches this: pushes are
        # the primary mechanism, the poll is the safety net (ref
        # peer-notified IAM reload, cmd/notification.go LoadUser etc).
        self.reload_interval = 1.0
        # NotificationSys.load_iam in distributed mode; None otherwise.
        self.notify = None
        self.load()

    def _maybe_reload(self) -> None:
        """On-demand refresh so identities created via another cluster
        node become visible (ref peer-notified IAM reload; here a cheap
        miss-triggered re-read with rate limiting)."""
        if time.time() - self._last_load >= self.reload_interval:
            self.load()

    # -- persistence ----------------------------------------------------

    def load(self) -> None:
        """Full rebuild from the store — REPLACE, don't merge, so
        entities deleted on another node disappear here too (a merge
        would keep revoked credentials alive until restart; all
        identities including STS temp creds are store-persisted, so a
        rebuild loses nothing)."""
        with self._mu:
            self._last_load = time.time()
            users: dict[str, UserIdentity] = {}
            for name in self.store.list(f"{IAM_PREFIX}/users"):
                doc = self.store.load(f"{IAM_PREFIX}/users/{name}")
                if doc:
                    u = UserIdentity.from_dict(doc)
                    users[u.access_key] = u
            policies = dict(DEFAULT_POLICIES)
            policy_docs: dict[str, dict] = {}
            for name in self.store.list(f"{IAM_PREFIX}/policies"):
                doc = self.store.load(f"{IAM_PREFIX}/policies/{name}")
                if doc:
                    pname = name.removesuffix(".json")
                    policies[pname] = Policy.from_dict(doc)
                    policy_docs[pname] = doc
            groups: dict[str, dict] = {}
            for name in self.store.list(f"{IAM_PREFIX}/groups"):
                doc = self.store.load(f"{IAM_PREFIX}/groups/{name}")
                if doc:
                    groups[name.removesuffix(".json")] = doc
            self.users = users
            self.policies = policies
            self.policy_docs = policy_docs
            self.groups = groups
            self.sts_policy_map = (
                self.store.load(f"{IAM_PREFIX}/sts-policy-map.json")
                or {})

    def _notify_peers(self) -> None:
        if self.notify is not None:
            self.notify()

    # -- users ----------------------------------------------------------

    def add_user(self, access_key: str, secret_key: str,
                 policies: list[str] | None = None) -> UserIdentity:
        if access_key == self.root_access:
            raise ValueError("cannot modify root credentials")
        if len(access_key) < 3 or len(secret_key) < 8:
            raise ValueError("access key >= 3 chars, secret >= 8 chars")
        u = UserIdentity(access_key, secret_key,
                         policies=list(policies or []))
        with self._mu:
            self.users[access_key] = u
            self.store.save(f"{IAM_PREFIX}/users/{access_key}.json",
                            u.to_dict())
        self._notify_peers()
        return u

    def remove_user(self, access_key: str) -> None:
        with self._mu:
            if access_key not in self.users:
                raise KeyError(access_key)
            del self.users[access_key]
            self.store.delete(f"{IAM_PREFIX}/users/{access_key}.json")
        self._notify_peers()

    def set_user_status(self, access_key: str, status: str) -> None:
        with self._mu:
            u = self.users[access_key]
            u.status = status
            self.store.save(f"{IAM_PREFIX}/users/{access_key}.json",
                            u.to_dict())
        self._notify_peers()

    def set_user_policy(self, access_key: str,
                        policies: list[str]) -> None:
        with self._mu:
            u = self.users[access_key]
            u.policies = list(policies)
            self.store.save(f"{IAM_PREFIX}/users/{access_key}.json",
                            u.to_dict())
        self._notify_peers()

    def list_users(self) -> list[dict]:
        with self._mu:
            return [{"accessKey": u.access_key, "status": u.status,
                     "policies": u.policies}
                    for u in self.users.values() if not u.parent]

    # -- groups ---------------------------------------------------------

    def add_group(self, name: str, members: list[str],
                  policies: list[str] | None = None) -> None:
        with self._mu:
            g = self.groups.setdefault(
                name, {"members": [], "policies": list(policies or [])})
            g["members"] = sorted(set(g["members"]) | set(members))
            if policies is not None:
                g["policies"] = list(policies)
            self.store.save(f"{IAM_PREFIX}/groups/{name}.json", g)
            for m in members:
                u = self.users.get(m)
                if u and name not in u.groups:
                    u.groups.append(name)
                    self.store.save(f"{IAM_PREFIX}/users/{m}.json",
                                    u.to_dict())
        self._notify_peers()

    # -- policies -------------------------------------------------------

    def set_policy(self, name: str, doc: dict) -> None:
        with self._mu:
            self.policies[name] = Policy.from_dict(doc)
            self.policy_docs[name] = doc
            self.store.save(f"{IAM_PREFIX}/policies/{name}.json", doc)
        self._notify_peers()

    def delete_policy(self, name: str) -> None:
        with self._mu:
            if name in DEFAULT_POLICIES:
                raise ValueError(f"cannot delete built-in policy {name}")
            self.policies.pop(name, None)
            self.policy_docs.pop(name, None)
            self.store.delete(f"{IAM_PREFIX}/policies/{name}.json")
        self._notify_peers()

    def list_policies(self) -> list[str]:
        with self._mu:
            return sorted(self.policies)

    # -- STS ------------------------------------------------------------

    def _mint_temp_credentials(self, claims: dict, parent: str,
                               duration_seconds: int,
                               policies: list[str] | None = None,
                               session_policy: dict | None = None,
                               ) -> UserIdentity:
        """Shared STS tail: clamp duration, mint keys, sign the session
        token, persist so every cluster node honors the credential (ref
        STS creds stored in the IAM object store)."""
        duration_seconds = max(900, min(duration_seconds, 7 * 24 * 3600))
        exp = time.time() + duration_seconds
        tmp_access = "MTPU" + secrets.token_hex(8).upper()
        tmp_secret = secrets.token_urlsafe(24)
        token = self._sign_token(
            dict(claims, exp=exp, secret=tmp_secret))
        u = UserIdentity(tmp_access, tmp_secret,
                         policies=list(policies or []), parent=parent,
                         session_token=token, expiration=exp,
                         session_policy=session_policy)
        with self._mu:
            self.users[tmp_access] = u
            self.store.save(f"{IAM_PREFIX}/users/{tmp_access}.json",
                            u.to_dict())
        return u

    def assume_role(self, access_key: str,
                    duration_seconds: int = 3600,
                    session_policy: dict | None = None) -> UserIdentity:
        """Mint temp credentials for an authenticated identity
        (ref AssumeRole, cmd/sts-handlers.go)."""
        claims: dict = {"parent": access_key}
        if session_policy:
            claims["policy"] = session_policy
        return self._mint_temp_credentials(
            claims, access_key, duration_seconds,
            session_policy=session_policy)

    def assume_role_web_identity(self, subject: str, policy_name: str,
                                 duration_seconds: int = 3600,
                                 ) -> UserIdentity:
        """Temp credentials for an EXTERNAL (OpenID) identity; the
        token's policy claim names the canned policy to attach (ref
        AssumeRoleWithWebIdentity, cmd/sts-handlers.go)."""
        with self._mu:
            if policy_name not in self.policies:
                raise KeyError(f"no such policy {policy_name!r}")
        return self._mint_temp_credentials(
            {"sub": subject}, f"oidc:{subject}", duration_seconds,
            policies=[policy_name])

    def set_sts_policy_map(self, key: str, policies: list[str]) -> None:
        """Map an external identity (``ldap:<user-dn>``, ``ldap:<group-dn>``
        or ``oidc:<sub>``) to canned policies — the reference's policy
        database for LDAP/OIDC STS identities (ref mc admin policy
        attach --ldap; cmd/iam.go PolicyDBSet)."""
        with self._mu:
            unknown = [p for p in policies if p not in self.policies]
            if unknown:
                raise KeyError(f"no such policy {unknown[0]!r}")
            if policies:
                self.sts_policy_map[key] = list(policies)
            else:
                self.sts_policy_map.pop(key, None)
            self.store.save(f"{IAM_PREFIX}/sts-policy-map.json",
                            self.sts_policy_map)
        self._notify_peers()

    def assume_role_ldap_identity(self, user_dn: str, groups: list[str],
                                  duration_seconds: int = 3600,
                                  ) -> UserIdentity:
        """Temp credentials for an LDAP-authenticated identity; policies
        come from the policy map over the user DN and group DNs (ref
        AssumeRoleWithLDAPIdentity, cmd/sts-handlers.go:78-93). No
        mapped policy = refused, like the reference."""
        with self._mu:
            names: list[str] = []
            for key in [f"ldap:{user_dn}"] + [f"ldap:{g}" for g in groups]:
                for p in self.sts_policy_map.get(key, []):
                    if p not in names:
                        names.append(p)
        if not names:
            raise KeyError(f"no policy mapped for {user_dn!r}")
        return self._mint_temp_credentials(
            {"ldapUser": user_dn}, f"ldap:{user_dn}", duration_seconds,
            policies=names)

    def _sign_token(self, claims: dict) -> str:
        body = base64.urlsafe_b64encode(
            json.dumps(claims, sort_keys=True).encode()).decode()
        sig = hmac.new(self._sts_key, body.encode(),
                       hashlib.sha256).hexdigest()
        return f"{body}.{sig}"

    def verify_token(self, token: str) -> dict | None:
        body, _, sig = token.rpartition(".")
        want = hmac.new(self._sts_key, body.encode(),
                        hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, sig):
            return None
        claims = json.loads(base64.urlsafe_b64decode(body))
        if time.time() > claims.get("exp", 0):
            return None
        return claims

    # -- auth + authz ---------------------------------------------------

    def lookup_secret(self, access_key: str) -> str | None:
        """SigV4 secret lookup (ref checkRequestAuthType)."""
        if access_key == self.root_access:
            return self.root_secret
        with self._mu:
            u = self.users.get(access_key)
        if u is None:
            self._maybe_reload()
            with self._mu:
                u = self.users.get(access_key)
        if u is None or u.status != "enabled" or u.expired:
            return None
        return u.secret_key

    def get_user(self, access_key: str):
        with self._mu:
            return self.users.get(access_key)

    def is_allowed(self, access_key: str, action: str, resource: str,
                   context: dict | None = None) -> bool:
        """Policy check (ref IAMSys.IsAllowed, cmd/iam.go:1612)."""
        if access_key == self.root_access:
            return True
        with self._mu:
            u = self.users.get(access_key)
        if u is None:
            self._maybe_reload()
        with self._mu:
            u = self.users.get(access_key)
            if u is None or u.status != "enabled" or u.expired:
                return False
            names = list(u.policies)
            for g in u.groups:
                names.extend(self.groups.get(g, {}).get("policies", []))
            if u.parent:
                # STS/service creds inherit the parent's policies,
                # intersected with any session policy.
                parent = self.users.get(u.parent)
                if u.parent == self.root_access:
                    names = ["readwrite"]
                elif parent:
                    names.extend(parent.policies)
            pols = [self.policies[n] for n in names
                    if n in self.policies]
        if not pols:
            return False
        allowed = any(
            p.is_allowed(action, resource, context=context or {})
            for p in pols)
        # A session policy can only restrict further (AWS semantics:
        # effective perms = identity ∩ session policy).
        if allowed and u.session_policy:
            sp = Policy.from_dict(u.session_policy)
            allowed = sp.is_allowed(action, resource,
                                    context=context or {})
        return allowed
