"""Minimal LDAPv3 wire client for the LDAP identity backend.

The reference ships an LDAP identity provider (ref
cmd/config/identity/ldap/config.go, lookup-bind mode) backing
AssumeRoleWithLDAPIdentity (ref cmd/sts-handlers.go:78-93). It uses the
go-ldap client; this build implements the two operations STS needs —
simple bind and subtree search — directly at the BER/wire level, the
same pattern as the broker sinks (event/brokers.py): no client
libraries, tested against an in-process fake server speaking the same
frames (tests/test_ldap_sts.py).

Wire format (RFC 4511): every LDAPMessage is a BER SEQUENCE of
{messageID INTEGER, protocolOp [APPLICATION n]}. Only definite lengths
are emitted; both short and long-form lengths are parsed.
"""

from __future__ import annotations

import socket
import ssl
import threading

# -- BER primitives -----------------------------------------------------------


def ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def ber(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + ber_len(len(payload)) + payload


def ber_int(v: int, tag: int = 0x02) -> bytes:
    if v == 0:
        return ber(tag, b"\x00")
    body = v.to_bytes((v.bit_length() // 8) + 1, "big", signed=True)
    return ber(tag, body)


def ber_str(s: str | bytes, tag: int = 0x04) -> bytes:
    return ber(tag, s if isinstance(s, bytes) else s.encode())


def ber_seq(*parts: bytes) -> bytes:
    return ber(0x30, b"".join(parts))


def ber_read(buf: bytes, off: int) -> tuple[int, bytes, int]:
    """Parse one TLV at off -> (tag, value, next_off)."""
    if off + 2 > len(buf):
        raise ValueError("short BER element")
    tag = buf[off]
    l0 = buf[off + 1]
    off += 2
    if l0 < 0x80:
        length = l0
    else:
        nlen = l0 & 0x7F
        if nlen == 0 or off + nlen > len(buf):
            raise ValueError("bad BER length")
        length = int.from_bytes(buf[off:off + nlen], "big")
        off += nlen
    if off + length > len(buf):
        raise ValueError("truncated BER value")
    return tag, buf[off:off + length], off + length


def ber_read_all(payload: bytes) -> list[tuple[int, bytes]]:
    out, off = [], 0
    while off < len(payload):
        tag, val, off = ber_read(payload, off)
        out.append((tag, val))
    return out


# -- protocol ops -------------------------------------------------------------

_APP_BIND_REQ = 0x60
_APP_BIND_RESP = 0x61
_APP_SEARCH_REQ = 0x63
_APP_SEARCH_ENTRY = 0x64
_APP_SEARCH_DONE = 0x65
_APP_UNBIND = 0x42
_CTX_SIMPLE_AUTH = 0x80
_CTX_FILTER_EQ = 0xA3
_CTX_FILTER_AND = 0xA0
_CTX_FILTER_PRESENT = 0x87


class LDAPError(Exception):
    pass


def insecure_context() -> ssl.SSLContext:
    """No-verify TLS context for the explicit skip-verify opt-out."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return ctx


def filter_eq(attr: str, value: str) -> bytes:
    return ber(_CTX_FILTER_EQ, ber_str(attr) + ber_str(value))


def filter_and(*filters: bytes) -> bytes:
    return ber(_CTX_FILTER_AND, b"".join(filters))


def filter_present(attr: str) -> bytes:
    return ber(_CTX_FILTER_PRESENT, attr.encode())


class LDAPClient:
    """One LDAP connection: bind + subtree search (RFC 4511 subset)."""

    def __init__(self, host: str, port: int = 389, timeout: float = 10.0,
                 tls: bool = False, tls_context: ssl.SSLContext | None = None):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)
        if tls:
            ctx = tls_context
            if ctx is None:
                # VERIFYING by default: LDAPS carries the directory
                # password, so certificate validation is the floor.
                # Directories with private CAs opt out explicitly via
                # MINIO_IDENTITY_LDAP_TLS_SKIP_VERIFY (insecure_context
                # below), matching the reference's tls_skip_verify.
                ctx = ssl.create_default_context()
            self._sock = ctx.wrap_socket(self._sock, server_hostname=host)
        self._msg_id = 0
        self._mu = threading.Lock()
        self._buf = b""

    def close(self) -> None:
        try:
            with self._mu:
                self._msg_id += 1
                self._sock.sendall(ber_seq(ber_int(self._msg_id),
                                           ber(_APP_UNBIND, b"")))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- transport ------------------------------------------------------

    def _recv_message(self) -> tuple[int, int, bytes]:
        """-> (message_id, op_tag, op_value)."""
        while True:
            try:
                _tag, val, consumed = ber_read(self._buf, 0)
                self._buf = self._buf[consumed:]
                parts = ber_read_all(val)
                if len(parts) < 2 or parts[0][0] != 0x02:
                    raise LDAPError("malformed LDAPMessage")
                msg_id = int.from_bytes(parts[0][1], "big")
                return msg_id, parts[1][0], parts[1][1]
            except ValueError:
                chunk = self._sock.recv(65536)
                if not chunk:
                    raise LDAPError("connection closed")
                self._buf += chunk

    def _send(self, op: bytes) -> int:
        self._msg_id += 1
        self._sock.sendall(ber_seq(ber_int(self._msg_id), op))
        return self._msg_id

    # -- operations -----------------------------------------------------

    def simple_bind(self, dn: str, password: str) -> None:
        """BindRequest with simple auth; raises LDAPError unless the
        server answers resultCode 0 (ref ldap.Conn.Bind)."""
        with self._mu:
            mid = self._send(ber(_APP_BIND_REQ,
                                 ber_int(3) + ber_str(dn)
                                 + ber_str(password, _CTX_SIMPLE_AUTH)))
            rid, tag, val = self._recv_message()
        if rid != mid or tag != _APP_BIND_RESP:
            raise LDAPError("unexpected bind response")
        parts = ber_read_all(val)
        code = int.from_bytes(parts[0][1], "big") if parts else 255
        if code != 0:
            raise LDAPError(f"bind failed: resultCode={code}")

    def search(self, base: str, flt: bytes,
               attrs: list[str] | None = None,
               ) -> list[tuple[str, dict[str, list[str]]]]:
        """Whole-subtree search -> [(dn, {attr: [values]})]."""
        attr_seq = ber_seq(*[ber_str(a) for a in (attrs or [])])
        req = ber(_APP_SEARCH_REQ,
                  ber_str(base) + ber_int(2, 0x0A) + ber_int(0, 0x0A)
                  + ber_int(0) + ber_int(0) + ber(0x01, b"\x00")
                  + flt + attr_seq)
        entries: list[tuple[str, dict[str, list[str]]]] = []
        with self._mu:
            mid = self._send(req)
            while True:
                rid, tag, val = self._recv_message()
                if rid != mid:
                    continue
                if tag == _APP_SEARCH_ENTRY:
                    parts = ber_read_all(val)
                    dn = parts[0][1].decode("utf-8", "replace")
                    attrs_out: dict[str, list[str]] = {}
                    if len(parts) > 1:
                        for _t, pa in ber_read_all(parts[1][1]):
                            kv = ber_read_all(pa)
                            name = kv[0][1].decode()
                            vals = [v.decode("utf-8", "replace")
                                    for _vt, v in ber_read_all(kv[1][1])]
                            attrs_out[name] = vals
                    entries.append((dn, attrs_out))
                elif tag == _APP_SEARCH_DONE:
                    parts = ber_read_all(val)
                    code = (int.from_bytes(parts[0][1], "big")
                            if parts else 255)
                    if code != 0:
                        raise LDAPError(
                            f"search failed: resultCode={code}")
                    return entries
                else:
                    raise LDAPError(f"unexpected op 0x{tag:02x}")


# -- identity backend ---------------------------------------------------------


class LDAPIdentity:
    """Lookup-bind LDAP identity (ref ldap/config.go LookupBind mode):
    a service account searches the user's DN from a username filter,
    the user's password is verified by binding as that DN, and group
    memberships come from a group filter over the member DN.

    Config (env, matching the reference's MINIO_IDENTITY_LDAP_*):
      SERVER_ADDR           host:port
      LOOKUP_BIND_DN        service account DN
      LOOKUP_BIND_PASSWORD
      USER_DN_SEARCH_BASE_DN
      USER_DN_SEARCH_FILTER   e.g. (uid=%s)   (%s = username)
      GROUP_SEARCH_BASE_DN
      GROUP_SEARCH_FILTER     e.g. (member=%d) (%d = user DN)
      TLS                     "on" to wrap the socket
    """

    def __init__(self, server_addr: str, lookup_bind_dn: str,
                 lookup_bind_password: str, user_base_dn: str,
                 user_filter: str = "(uid=%s)", group_base_dn: str = "",
                 group_filter: str = "(member=%d)", tls: bool = False,
                 tls_skip_verify: bool = False, client_factory=None):
        self.server_addr = server_addr
        self.lookup_bind_dn = lookup_bind_dn
        self.lookup_bind_password = lookup_bind_password
        self.user_base_dn = user_base_dn
        self.user_filter = user_filter
        self.group_base_dn = group_base_dn
        self.group_filter = group_filter
        self.tls = tls
        self.tls_skip_verify = tls_skip_verify
        self._client_factory = client_factory or self._connect

    @classmethod
    def from_env(cls, env) -> "LDAPIdentity | None":
        addr = env.get("MINIO_IDENTITY_LDAP_SERVER_ADDR", "")
        if not addr:
            return None
        return cls(
            addr,
            env.get("MINIO_IDENTITY_LDAP_LOOKUP_BIND_DN", ""),
            env.get("MINIO_IDENTITY_LDAP_LOOKUP_BIND_PASSWORD", ""),
            env.get("MINIO_IDENTITY_LDAP_USER_DN_SEARCH_BASE_DN", ""),
            env.get("MINIO_IDENTITY_LDAP_USER_DN_SEARCH_FILTER",
                    "(uid=%s)"),
            env.get("MINIO_IDENTITY_LDAP_GROUP_SEARCH_BASE_DN", ""),
            env.get("MINIO_IDENTITY_LDAP_GROUP_SEARCH_FILTER",
                    "(member=%d)"),
            env.get("MINIO_IDENTITY_LDAP_TLS", "") == "on",
            env.get("MINIO_IDENTITY_LDAP_TLS_SKIP_VERIFY", "") == "on")

    def _connect(self) -> LDAPClient:
        host, _, port = self.server_addr.rpartition(":")
        ctx = insecure_context() if (self.tls and self.tls_skip_verify) \
            else None
        return LDAPClient(host or self.server_addr,
                          int(port) if port else 389, tls=self.tls,
                          tls_context=ctx)

    @staticmethod
    def _parse_filter(template: str, value: str) -> bytes:
        """Compile the reference's filter syntax subset: an optional
        (&(...)(...)) conjunction of (attr=%s|%d|literal|*) terms."""
        t = template.strip()
        if t.startswith("(&") and t.endswith(")"):
            inner = t[2:-1]
            parts, depth, start = [], 0, 0
            for i, ch in enumerate(inner):
                if ch == "(":
                    if depth == 0:
                        start = i
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        parts.append(inner[start:i + 1])
            return filter_and(*[LDAPIdentity._parse_filter(p, value)
                                for p in parts])
        if not (t.startswith("(") and t.endswith(")")):
            raise LDAPError(f"unsupported filter {template!r}")
        attr, _, rhs = t[1:-1].partition("=")
        if rhs == "*":
            return filter_present(attr)
        rhs = rhs.replace("%s", value).replace("%d", value)
        return filter_eq(attr, rhs)

    def authenticate(self, username: str, password: str,
                     ) -> tuple[str, list[str]]:
        """-> (user_dn, group_dns); raises LDAPError on bad creds.

        Anonymous/empty passwords are rejected up front: an LDAP simple
        bind with an empty password SUCCEEDS as anonymous on most
        servers, which would turn 'forgot the password field' into a
        login (the go-ldap client guards identically)."""
        if not username or not password:
            raise LDAPError("empty username or password")
        with self._client_factory() as lookup:
            lookup.simple_bind(self.lookup_bind_dn,
                               self.lookup_bind_password)
            hits = lookup.search(
                self.user_base_dn,
                self._parse_filter(self.user_filter, username), ["dn"])
            if len(hits) != 1:
                raise LDAPError(
                    f"user search matched {len(hits)} entries")
            user_dn = hits[0][0]
            # Password check on a SEPARATE connection: the user bind
            # must not downgrade the lookup connection's authorization.
            with self._client_factory() as conn:
                conn.simple_bind(user_dn, password)
            # Group search stays on the SERVICE ACCOUNT connection:
            # directories commonly deny regular users read access to
            # the group subtree, which would silently yield groups=[]
            # and lose group-mapped policies (the reference's
            # lookup-bind mode searches as the service account too).
            groups: list[str] = []
            if self.group_base_dn:
                for dn, _attrs in lookup.search(
                        self.group_base_dn,
                        self._parse_filter(self.group_filter, user_dn),
                        ["dn"]):
                    groups.append(dn)
        return user_dn, groups
