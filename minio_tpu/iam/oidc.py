"""OpenID Connect token validation for STS WebIdentity.

The reference validates WebIdentity JWTs against the provider's
published JWKS (ref cmd/config/identity/openid/jwks.go:30 DecodePublicKey,
cmd/config/identity/openid/jwt.go Validate). This build does the same
with zero dependencies: RSASSA-PKCS1-v1_5/SHA-256 verification is pure
bignum math over the JWK's (n, e), and the JWKS document is fetched
from a configurable URL (a test fixture server stands in for the
provider — this environment has no egress).

HS256 against a shared secret remains available as an explicit DEV mode
(the round-4 scheme), but is only honored when no JWKS URL is
configured: a deployment that points at a provider never silently
accepts symmetric tokens.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import threading
import time
import urllib.request


class OIDCError(ValueError):
    """Token failed validation (malformed, bad signature, expired...)."""


def _b64u(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


# DER DigestInfo prefix for SHA-256 (RFC 8017 section 9.2 note 1).
_SHA256_PREFIX = bytes.fromhex(
    "3031300d060960864801650304020105000420")


def emsa_pkcs1_sha256(message: bytes, em_len: int) -> bytes:
    """EMSA-PKCS1-v1_5 encoding of SHA-256(message) into em_len bytes
    (RFC 8017 section 9.2): 00 01 FF..FF 00 || DigestInfo || H."""
    t = _SHA256_PREFIX + hashlib.sha256(message).digest()
    if em_len < len(t) + 11:
        raise OIDCError("RSA modulus too small")
    ps = b"\xff" * (em_len - len(t) - 3)
    return b"\x00\x01" + ps + b"\x00" + t


def rs256_verify(n: int, e: int, message: bytes, signature: bytes) -> bool:
    """RSASSA-PKCS1-v1_5 verify with SHA-256 over a JWK (n, e) pair —
    pure bignum: EM' = sig^e mod n, compared against the canonical
    encoding (ref jwks.go builds an rsa.PublicKey the same way)."""
    k = (n.bit_length() + 7) // 8
    if len(signature) != k:
        return False
    s = int.from_bytes(signature, "big")
    if s >= n:
        return False
    em = pow(s, e, n).to_bytes(k, "big")
    return hmac.compare_digest(em, emsa_pkcs1_sha256(message, k))


class Jwks:
    """A parsed JWKS document: kid -> (n, e) for RSA keys."""

    def __init__(self, keys: dict[str, tuple[int, int]]):
        self.keys = keys

    @classmethod
    def from_dict(cls, doc: dict) -> "Jwks":
        keys: dict[str, tuple[int, int]] = {}
        for jwk in doc.get("keys", []):
            if jwk.get("kty") != "RSA" or "n" not in jwk or "e" not in jwk:
                continue
            n = int.from_bytes(_b64u(jwk["n"]), "big")
            e = int.from_bytes(_b64u(jwk["e"]), "big")
            keys[jwk.get("kid", "")] = (n, e)
        return cls(keys)

    def candidates(self, kid: str | None) -> list[tuple[int, int]]:
        """Keys to try: the kid's key, or every key when the token
        carries no kid (providers may rotate without kids)."""
        if kid is not None and kid in self.keys:
            return [self.keys[kid]]
        if kid is None:
            return list(self.keys.values())
        return []


class OpenIDValidator:
    """Validates WebIdentity bearer tokens.

    RS256 against a JWKS fetched from `jwks_url` (refreshed on unknown
    kid, rate-limited); HS256 against `hs256_secret` only when no JWKS
    URL is configured (dev mode). Enforces exp/nbf and, when
    `client_id` is set, the aud claim (ref openid/jwt.go Validate).
    """

    def __init__(self, jwks_url: str = "", client_id: str = "",
                 hs256_secret: str = "", claim_name: str = "policy",
                 fetch_timeout: float = 5.0):
        self.jwks_url = jwks_url
        self.client_id = client_id
        self.hs256_secret = hs256_secret
        self.claim_name = claim_name
        self.fetch_timeout = fetch_timeout
        self._jwks: Jwks | None = None
        self._fetched_at = 0.0
        self._mu = threading.Lock()

    @classmethod
    def from_env(cls, env=os.environ) -> "OpenIDValidator | None":
        jwks_url = env.get("MINIO_IDENTITY_OPENID_JWKS_URL", "")
        secret = env.get("MINIO_IDENTITY_OPENID_SECRET", "")
        if not jwks_url and not secret:
            return None
        return cls(jwks_url=jwks_url,
                   client_id=env.get(
                       "MINIO_IDENTITY_OPENID_CLIENT_ID", ""),
                   hs256_secret=secret,
                   claim_name=env.get(
                       "MINIO_IDENTITY_OPENID_CLAIM_NAME", "policy"))

    # -- JWKS cache -----------------------------------------------------

    def _fetch_jwks(self, force: bool = False) -> Jwks:
        # Cache hit without the lock (attribute read is atomic): a slow
        # JWKS endpoint must never stall validations that don't fetch.
        cached = self._jwks
        if cached is not None and not force:
            return cached
        with self._mu:
            now = time.monotonic()
            if self._jwks is not None and (
                    not force or now - self._fetched_at < 30):
                return self._jwks  # fetched meanwhile / rate-limited
            req = urllib.request.Request(
                self.jwks_url, headers={"User-Agent": "minio-tpu"})
            with urllib.request.urlopen(
                    req, timeout=self.fetch_timeout) as resp:
                doc = json.loads(resp.read())
            self._jwks = Jwks.from_dict(doc)
            self._fetched_at = time.monotonic()
            return self._jwks

    # -- validation -----------------------------------------------------

    def validate(self, token: str) -> dict:
        try:
            header_b64, payload_b64, sig_b64 = token.split(".")
            header = json.loads(_b64u(header_b64))
            claims = json.loads(_b64u(payload_b64))
            sig = _b64u(sig_b64)
        except Exception:
            raise OIDCError("malformed token")
        if not isinstance(header, dict) or not isinstance(claims, dict):
            raise OIDCError("malformed token")
        alg = header.get("alg", "")
        signing_input = f"{header_b64}.{payload_b64}".encode()

        if alg == "RS256" and self.jwks_url:
            jwks = self._fetch_jwks()
            kid = header.get("kid")
            cands = jwks.candidates(kid)
            ok = any(rs256_verify(n, e, signing_input, sig)
                     for n, e in cands)
            if not ok:
                # Unknown kid OR a no-kid token that no cached key
                # verifies: the provider may have rotated its keys.
                # One rate-limited refresh (30s) covers both shapes.
                jwks = self._fetch_jwks(force=True)
                ok = any(rs256_verify(n, e, signing_input, sig)
                         for n, e in jwks.candidates(kid))
            if not ok:
                raise OIDCError("invalid RS256 signature")
        elif alg == "HS256" and self.hs256_secret and not self.jwks_url:
            want = hmac.new(self.hs256_secret.encode(), signing_input,
                            hashlib.sha256).digest()
            if not hmac.compare_digest(want, sig):
                raise OIDCError("invalid HS256 signature")
        else:
            raise OIDCError(f"unsupported or unconfigured alg {alg!r}")

        now = time.time()
        exp = claims.get("exp")
        if not isinstance(exp, (int, float)) or now > exp:
            raise OIDCError("token expired")
        nbf = claims.get("nbf")
        if isinstance(nbf, (int, float)) and now < nbf:
            raise OIDCError("token not yet valid")
        if self.client_id:
            aud = claims.get("aud", "")
            auds = aud if isinstance(aud, list) else [aud]
            if self.client_id not in auds:
                raise OIDCError("aud mismatch")
        return claims
