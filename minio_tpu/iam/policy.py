"""AWS-compatible policy documents and evaluation
(ref pkg/iam/policy: Policy.IsAllowed, pkg/bucket/policy,
pkg/wildcard for * / ? matching).

Supported: Version/Statement with Effect, Action (s3:* wildcards),
Resource (arn:aws:s3:::bucket/key wildcards), Principal (bucket
policies), and the common Condition operators (StringEquals,
StringLike, IpAddress is accepted but not evaluated without a source).
Explicit Deny overrides Allow, default deny — AWS semantics.
"""

from __future__ import annotations

import fnmatch
import json
from dataclasses import dataclass, field

ARN_PREFIX = "arn:aws:s3:::"

# Action names (subset mirroring pkg/iam/policy/action.go).
ALL_ACTIONS = "s3:*"


def wildcard_match(pattern: str, s: str) -> bool:
    """S3 wildcard semantics: '*' matches any sequence (including '/'),
    '?' any single char (ref pkg/wildcard/match.go MatchSimple)."""
    # fnmatch's [] classes are not part of S3 wildcards; escape them.
    pattern = pattern.replace("[", "[[]")
    return fnmatch.fnmatchcase(s, pattern)


@dataclass
class Statement:
    effect: str                      # "Allow" | "Deny"
    actions: list[str]
    resources: list[str]
    principals: list[str] = field(default_factory=list)  # bucket policies
    conditions: dict = field(default_factory=dict)
    not_actions: list[str] = field(default_factory=list)

    def matches_action(self, action: str) -> bool:
        if self.not_actions:
            return not any(wildcard_match(p, action)
                           for p in self.not_actions)
        return any(wildcard_match(p, action) for p in self.actions)

    def matches_resource(self, resource: str) -> bool:
        if not self.resources:
            return True
        for r in self.resources:
            pat = r[len(ARN_PREFIX):] if r.startswith(ARN_PREFIX) else r
            if wildcard_match(pat, resource) or pat == "*":
                return True
        return False

    def matches_principal(self, principal: str) -> bool:
        if not self.principals:
            return True
        return any(p == "*" or wildcard_match(p, principal)
                   for p in self.principals)

    def matches_conditions(self, context: dict) -> bool:
        for op, clauses in self.conditions.items():
            op_l = op.lower()
            for key, want in clauses.items():
                got = context.get(key.lower())
                wants = want if isinstance(want, list) else [want]
                if op_l == "stringequals":
                    if got is None or got not in wants:
                        return False
                elif op_l == "stringnotequals":
                    if got is not None and got in wants:
                        return False
                elif op_l == "stringlike":
                    if got is None or not any(
                            wildcard_match(w, got) for w in wants):
                        return False
                # Unknown operators: conservatively no-match for Allow
                # is risky; the reference fails closed too.
                elif op_l in ("ipaddress", "notipaddress"):
                    continue
                else:
                    return False
        return True


@dataclass
class Policy:
    statements: list[Statement]
    version: str = "2012-10-17"

    @classmethod
    def from_dict(cls, doc: dict) -> "Policy":
        stmts = []
        raw = doc.get("Statement", [])
        if isinstance(raw, dict):
            raw = [raw]
        for s in raw:
            actions = s.get("Action", [])
            if isinstance(actions, str):
                actions = [actions]
            not_actions = s.get("NotAction", [])
            if isinstance(not_actions, str):
                not_actions = [not_actions]
            resources = s.get("Resource", [])
            if isinstance(resources, str):
                resources = [resources]
            principal = s.get("Principal", {})
            principals: list[str] = []
            if principal == "*":
                principals = ["*"]
            elif isinstance(principal, dict):
                aws = principal.get("AWS", [])
                principals = [aws] if isinstance(aws, str) else list(aws)
            stmts.append(Statement(
                effect=s.get("Effect", "Deny"),
                actions=actions, not_actions=not_actions,
                resources=resources, principals=principals,
                conditions=s.get("Condition", {}) or {},
            ))
        return cls(stmts, doc.get("Version", "2012-10-17"))

    @classmethod
    def from_json(cls, raw: str | bytes) -> "Policy":
        return cls.from_dict(json.loads(raw))

    def is_allowed(self, action: str, resource: str,
                   principal: str = "", context: dict | None = None,
                   ) -> bool:
        """Explicit Deny wins; else any Allow; else deny
        (ref iampolicy.Policy.IsAllowed)."""
        context = context or {}
        allowed = False
        for st in self.statements:
            if not (st.matches_action(action)
                    and st.matches_resource(resource)
                    and st.matches_principal(principal)
                    and st.matches_conditions(context)):
                continue
            if st.effect == "Deny":
                return False
            allowed = True
        return allowed


# --- canned policies (ref pkg/iam/policy default policies) -------------------

READ_WRITE = Policy.from_dict({
    "Version": "2012-10-17",
    "Statement": [{"Effect": "Allow", "Action": ["s3:*"],
                   "Resource": ["arn:aws:s3:::*"]}],
})

READ_ONLY = Policy.from_dict({
    "Version": "2012-10-17",
    "Statement": [{"Effect": "Allow",
                   "Action": ["s3:GetBucketLocation", "s3:GetObject",
                              "s3:ListBucket", "s3:ListAllMyBuckets",
                              "s3:GetObjectVersion"],
                   "Resource": ["arn:aws:s3:::*"]}],
})

WRITE_ONLY = Policy.from_dict({
    "Version": "2012-10-17",
    "Statement": [{"Effect": "Allow", "Action": ["s3:PutObject"],
                   "Resource": ["arn:aws:s3:::*"]}],
})

DEFAULT_POLICIES = {
    "readwrite": READ_WRITE,
    "readonly": READ_ONLY,
    "writeonly": WRITE_ONLY,
}
