from .metacache import MetacacheManager

__all__ = ["MetacacheManager"]
