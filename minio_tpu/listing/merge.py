"""K-way merge + per-entry quorum resolve of per-disk walk streams
(ref cmd/metacache-entries.go: metaCacheEntries.resolve, and the sorted
merge in listPathRaw, cmd/metacache-set.go)."""

from __future__ import annotations

import heapq


def merge_resolve(disk_entries: list[list[dict] | None],
                  quorum: int) -> list[dict]:
    """Merge sorted per-disk entry streams into one sorted stream.

    Each input is one disk's `walk_dir` output (or None for an offline
    disk). A version of an object survives when at least `quorum` disks
    agree on it (same version-id + mod-time — the FileInfo quorum key of
    the metadata path); an object survives when at least one of its
    versions does. Versions are returned newest-first per object.
    """
    streams = [s for s in disk_entries
               if s is not None and not isinstance(s, BaseException)]
    if not streams:
        return []

    heap: list[tuple[str, int, int]] = []  # (name, stream_idx, pos)
    for si, s in enumerate(streams):
        if s:
            heapq.heappush(heap, (s[0]["name"], si, 0))

    out: list[dict] = []
    while heap:
        name = heap[0][0]
        per_disk: list[list[dict]] = []
        while heap and heap[0][0] == name:
            _, si, pos = heapq.heappop(heap)
            per_disk.append(streams[si][pos]["versions"])
            if pos + 1 < len(streams[si]):
                heapq.heappush(
                    heap, (streams[si][pos + 1]["name"], si, pos + 1))
        resolved = _resolve_versions(per_disk, quorum)
        if resolved:
            out.append({"name": name, "versions": resolved})
    return out


def _vkey(v: dict) -> tuple:
    """Mirror of FileInfo.quorum_key (storage/metadata.py): version id,
    kind, data dir, size, mod time, erasure geometry and part layout
    must ALL agree for two disks' views to pool into one quorum vote —
    divergent racing null-version writes must not merge."""
    er = v.get("erasure", {}) or {}
    return (v.get("versionId", ""),
            v.get("type") == "delete-marker",
            v.get("dataDir", ""),
            v.get("size", 0),
            round(v.get("modTime", 0.0), 6),
            er.get("data", 0), er.get("parity", 0),
            er.get("blockSize", 0), tuple(er.get("distribution", []) or []),
            tuple((p.get("number", 0), p.get("size", 0))
                  for p in v.get("parts", []) or []))


def _resolve_versions(per_disk: list[list[dict]], quorum: int,
                      ) -> list[dict]:
    counts: dict[tuple, int] = {}
    best: dict[tuple, dict] = {}
    for versions in per_disk:
        for v in versions:
            key = _vkey(v)
            counts[key] = counts.get(key, 0) + 1
            best[key] = v
    alive = [v for key, v in best.items() if counts[key] >= quorum]
    alive.sort(key=lambda v: (-v.get("modTime", 0.0),
                              v.get("versionId", "")))
    return alive
