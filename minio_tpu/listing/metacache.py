"""Metacache: cached, quorum-resolved bucket listings (ref the metacache
engine, cmd/metacache.go:54, cmd/metacache-server-pool.go:38 listPath,
cmd/metacache-set.go streamMetadataParts, cmd/metacache-stream.go block
persistence).

One listing scan = parallel `walk_dir` over the set's disks → k-way
merge with per-version quorum resolve → entry stream, kept in memory and
persisted as compressed block objects under
`.minio.sys/buckets/<bucket>/.metacache/<id>/block-<n>` (5000 entries
per block like the reference, s2-analog LZ block compression).

Invalidation is tracker-first: every mutation on this node bumps the
bucket's DataUpdateTracker counter, and a cache whose counter snapshot
is stale is rescanned — giving read-after-write listings on the serving
node. A TTL backstop bounds staleness for writes arriving via other
nodes (ref metacache's seconds-level eventual consistency window).
"""

from __future__ import annotations

import json
import threading
import time
import uuid

from ..parallel.quorum import parallel_map, read_quorum
from ..storage.metadata import FileInfo
from ..utils.compress import compress_stream, decompress_stream
from .merge import merge_resolve

BLOCK_ENTRIES = 5000          # ref metacacheBlockSize, cmd/metacache.go:42
DEFAULT_TTL = 10.0            # backstop for cross-node writes
CACHE_PREFIX = "buckets"      # under .minio.sys


class _Cache:
    __slots__ = ("cache_id", "bucket", "root", "entries", "created",
                 "counter", "cycle")

    def __init__(self, cache_id, bucket, root, entries, created, counter,
                 cycle):
        self.cache_id = cache_id
        self.bucket = bucket
        self.root = root            # prefix the scan covered
        self.entries = entries      # [{"name","versions"}...] sorted
        self.created = created
        self.counter = counter      # tracker counter at scan time
        self.cycle = cycle          # tracker bloom cycle at scan time


class MetacacheManager:
    """Per-engine listing cache over one erasure set's disks."""

    def __init__(self, engine, ttl: float = DEFAULT_TTL):
        self.engine = engine
        self.ttl = ttl
        self._mu = threading.Lock()
        self._caches: dict[tuple[str, str], _Cache] = {}
        self.scans = 0  # observability: number of real disk scans
        self.last_persist: threading.Thread | None = None
        # Cluster sharing (ref updateMetacacheListing routing,
        # cmd/metacache-set.go:247, cmd/metacache-bucket.go): in
        # distributed mode the cluster wiring installs a
        # rpc.peer.MetacacheShare here plus this manager's (pool, set)
        # address; every (bucket, root) then has ONE owning node whose
        # scan all nodes reuse, instead of N nodes doing N walks.
        self.peer_share = None
        self.share_id: tuple[int, int] = (0, 0)
        self.peer_serves = 0  # served-from-peer counter (tests/metrics)
        # (bucket, root) -> OUR tracker counter at the last owner
        # fetch; a moved counter means this node wrote since then and
        # the next fetch must force the owner to rescan.
        self._peer_fetch_counters: dict[tuple[str, str], int] = {}

    # -- scan -------------------------------------------------------------

    def _scan(self, bucket: str, root: str) -> list[dict]:
        eng = self.engine
        results, _errs = parallel_map(
            [lambda d=d: d.walk_dir(bucket, root) for d in eng.disks])
        self.scans += 1
        return merge_resolve(list(results), read_quorum(eng.k))

    def _persist(self, cache: _Cache, old_id: str | None) -> None:
        """Write entry blocks back as compressed objects in .minio.sys
        and retire the replaced cache's blocks (best effort — the cache
        is advisory; ref metacache block objects persisted through the
        object layer + manager GC, cmd/metacache-manager.go). Runs off
        the listing hot path in a daemon thread."""
        if old_id:
            old = (f"{CACHE_PREFIX}/{cache.bucket}/.metacache/{old_id}")
            for d in self.engine.disks:
                try:
                    d.delete(".minio.sys", old, recursive=True)
                except Exception:
                    continue
        base = (f"{CACHE_PREFIX}/{cache.bucket}/.metacache/"
                f"{cache.cache_id}")
        info = {"id": cache.cache_id, "bucket": cache.bucket,
                "root": cache.root, "created": cache.created,
                "entries": len(cache.entries),
                "blocks": (len(cache.entries) + BLOCK_ENTRIES - 1)
                // BLOCK_ENTRIES}
        try:
            for n in range(info["blocks"]):
                blk = cache.entries[n * BLOCK_ENTRIES:
                                    (n + 1) * BLOCK_ENTRIES]
                raw = "\n".join(json.dumps(e, sort_keys=True)
                                for e in blk).encode()
                blob = compress_stream(raw)
                for d in self.engine.disks:
                    try:
                        d.write_all(".minio.sys", f"{base}/block-{n}",
                                    blob)
                        break  # one copy is enough for an advisory cache
                    except Exception:
                        continue
            for d in self.engine.disks:
                try:
                    d.write_all(".minio.sys", f"{base}/info.json",
                                json.dumps(info).encode())
                    break
                except Exception:
                    continue
        except Exception:
            pass

    @staticmethod
    def load_persisted(disk, bucket: str, cache_id: str) -> list[dict]:
        """Read a persisted cache back from one disk (resume/debug path;
        ref metacache-stream block reader)."""
        base = f"{CACHE_PREFIX}/{bucket}/.metacache/{cache_id}"
        info = json.loads(disk.read_all(".minio.sys", f"{base}/info.json"))
        entries: list[dict] = []
        for n in range(info["blocks"]):
            raw = decompress_stream(
                disk.read_all(".minio.sys", f"{base}/block-{n}"))
            entries.extend(json.loads(line)
                           for line in raw.decode().splitlines() if line)
        return entries

    # -- cache lookup -----------------------------------------------------

    def _fresh(self, c: _Cache, tracker, counter: int,
               now: float) -> bool:
        if self.ttl and now - c.created > self.ttl:
            return False            # bound staleness from remote writers
        if c.counter == counter:
            return True
        # The bucket changed — but a rooted cache survives when the
        # bloom says nothing changed under ITS prefix root (false
        # positives only cost a rescan).
        if c.root and tracker is not None:
            # completed bloom cycles since the scan; current is always
            # consulted too
            back = max(0, tracker.cycle - c.cycle)
            return not tracker.changed_under(c.bucket, c.root, back)
        return False

    def _entries_for(self, bucket: str, prefix: str, after: str = ""):
        """Entries covering `prefix`, name > `after` when peer-served
        (iterable, sorted by name): local cache/scan when this node
        owns the (bucket, root), a paged peer stream when another node
        does. `after` (the caller's pagination marker) seeds the
        owner-side cursor so page k of a paginated listing pulls one
        page over the wire, not k pages."""
        root = prefix.split("/", 1)[0] if "/" in prefix else ""
        share = self.peer_share
        if share is not None:
            owner = share.owner_key(bucket, root)
            if owner is not None:
                # Read-after-write THROUGH THIS NODE survives sharing:
                # the owner's tracker never sees writes done via other
                # nodes, so when OUR tracker moved since our last fetch
                # of this root, the first page asks the owner to drop
                # its cache and rescan (write-then-list costs one scan,
                # exactly like the unshared design; read-mostly listing
                # stays shared).
                tracker = getattr(self.engine, "update_tracker", None)
                counter = (tracker.bucket_counter(bucket) if tracker
                           else -1)
                key = (bucket, root)
                force = self._peer_fetch_counters.get(key) != counter
                # The counter snapshot is recorded only after the
                # owner actually SERVES the first forced page
                # (_peer_then_local) — recording it here would let a
                # never-iterated or transport-failed listing swallow
                # the owner-cache invalidation and serve stale
                # read-after-write results (ADVICE r5). A concurrent
                # stale overwrite can only force one extra rescan,
                # never skip one.
                return self._peer_then_local(share, owner, bucket,
                                             root, after, force,
                                             key, counter)
        return self._entries_local(bucket, root)

    def _mark_peer_fetched(self, key, counter) -> None:
        """A forced owner fetch completed: writes up to `counter` are
        now reflected in the owner's cache."""
        if key is not None:
            self._peer_fetch_counters[key] = counter

    def _peer_then_local(self, share, owner: str, bucket: str,
                         root: str, after: str, force: bool = False,
                         key=None, counter=None):
        """Stream the owner's entries; on ANY transport failure —
        first page or mid-stream — continue from a local scan at the
        last yielded name, so an owner crash degrades a listing to a
        local walk instead of failing it (availability beats the
        shared-scan optimization). The fetch-counter snapshot commits
        only once the owner has actually served the first page (an
        empty-but-successful listing counts) — a failed or abandoned
        forced fetch keeps the force sticky for the next listing."""
        last = after
        it = share.fetch_entries(owner, self.share_id, bucket, root,
                                 after=after, force=force)
        served = False
        while True:
            try:
                e = next(it)
            except StopIteration:
                if not served:
                    # Owner answered (empty page): the force was
                    # delivered; commit the snapshot.
                    self._mark_peer_fetched(key, counter)
                return
            except Exception:
                for e2 in self._entries_local(bucket, root):
                    if e2["name"] > last:
                        yield e2
                return
            if not served:
                served = True
                self.peer_serves += 1
                self._mark_peer_fetched(key, counter)
            last = e["name"]
            yield e

    def _entries_local(self, bucket: str, root: str) -> list[dict]:
        """Serve entries from this node's cache, scanning if stale.
        Caches are registered per prefix-root (first path segment, like
        the reference's per-prefix metacache id selection). This is
        also what the peer RPC serves to non-owner nodes — it must
        never delegate back out."""
        key = (bucket, root)
        tracker = getattr(self.engine, "update_tracker", None)
        counter = tracker.bucket_counter(bucket) if tracker else -1
        now = time.time()
        with self._mu:
            c = self._caches.get(key)
            if c is not None and self._fresh(c, tracker, counter, now):
                return c.entries
            old_id = c.cache_id if c is not None else None
        entries = self._scan(bucket, root)
        c = _Cache(uuid.uuid4().hex, bucket, root, entries, now, counter,
                   tracker.cycle if tracker else 0)
        with self._mu:
            self._caches[key] = c
        # mtpu-lint: disable=R1 -- write-behind persist is deliberately decoupled: the listing answered already
        t = threading.Thread(target=self._persist, args=(c, old_id),
                             daemon=True)
        self.last_persist = t       # joinable by tests/shutdown
        t.start()
        return entries

    def drop_bucket(self, bucket: str) -> None:
        with self._mu:
            dropped = [self._caches.pop(k)
                       for k in [k for k in self._caches
                                 if k[0] == bucket]]
        for d in self.engine.disks:  # retire persisted blocks too
            try:
                d.delete(".minio.sys",
                         f"{CACHE_PREFIX}/{bucket}/.metacache",
                         recursive=True)
            except Exception:
                continue
        del dropped

    # -- public listing ---------------------------------------------------

    def list_path(self, bucket: str, prefix: str = "", marker: str = "",
                  max_keys: int = 1000) -> list[FileInfo]:
        """Latest live version per key (ListObjects view)."""
        out: list[FileInfo] = []
        for e in self._entries_for(bucket, prefix, after=marker):
            name = e["name"]
            if prefix and not name.startswith(prefix):
                continue
            if marker and name <= marker:
                continue
            if not e["versions"]:
                continue
            latest = e["versions"][0]
            if latest.get("type") == "delete-marker":
                continue
            out.append(FileInfo.from_version_dict(bucket, name, latest))
            if len(out) >= max_keys:
                break
        return out

    def list_versions(self, bucket: str, prefix: str = "",
                      marker: str = "", max_keys: int = 1000,
                      ) -> list[FileInfo]:
        """All versions newest-first per key (ListObjectVersions view).

        `marker` is a key-level marker, so truncation happens only at
        key boundaries (a key's versions are never split across pages;
        max_keys may be exceeded by the last key's version count)."""
        out: list[FileInfo] = []
        for e in self._entries_for(bucket, prefix, after=marker):
            name = e["name"]
            if prefix and not name.startswith(prefix):
                continue
            if marker and name <= marker:
                continue
            out.extend(FileInfo.from_version_dict(bucket, name, v)
                       for v in e["versions"])
            if len(out) >= max_keys:
                break
        return out
