"""Structured logging, console ring, audit webhook (ref cmd/logger/)."""

from .logger import ConsoleLogRing, LogEntry, Logger  # noqa: F401
