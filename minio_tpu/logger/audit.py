"""Audit logging: one structured entry per API request, delivered to a
webhook target from a background queue (ref cmd/logger/audit.go:128
AuditLog + cmd/logger/target/http — MINIO_AUDIT_WEBHOOK_* env).

Delivery is async and lossy-on-overflow: the data path never blocks on
the audit sink (same bounded-channel design as the reference's http
target).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import urllib.request


def audit_entry(api: str, method: str, path: str, status: int,
                duration_ms: float, rx: int, tx: int,
                access_key: str = "", request_id: str = "",
                remote: str = "", qos_class: str = "",
                blamed_layer: str = "") -> dict:
    """Entry shape follows the reference's audit.Entry fields, plus
    the join keys against this stack's observability planes: trace_id
    (= the request id every span tree is keyed by), the QoS admission
    class, and — when the request landed in the slow-request log — the
    blamed layer, so the webhook stream correlates with the slowlog
    without a second lookup."""
    return {
        "version": "1",
        "deploymentid": "minio-tpu",
        "time": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "api": {
            "name": api, "method": method, "path": path,
            "statusCode": status,
            "timeToResponseNs": int(duration_ms * 1e6),
            "rx": rx, "tx": tx,
        },
        "requestID": request_id,
        "trace_id": request_id,
        "qos_class": qos_class,
        "blamed_layer": blamed_layer,
        "accessKey": access_key,
        "remotehost": remote,
    }


class AuditWebhook:
    """Queue + worker POSTing JSON entries to the webhook endpoint."""

    def __init__(self, endpoint: str, auth_token: str = "",
                 queue_size: int = 10_000):
        self.endpoint = endpoint
        self.auth_token = auth_token
        self._q: queue.Queue[dict | None] = queue.Queue(maxsize=queue_size)
        self._stats_mu = threading.Lock()
        self.dropped = 0
        self.sent = 0
        self.failed = 0
        # mtpu-lint: disable=R1 -- audit drain daemon: entries from many requests share one worker
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="audit-webhook")
        self._worker.start()

    @classmethod
    def from_env(cls, env=os.environ) -> "AuditWebhook | None":
        ep = env.get("MINIO_AUDIT_WEBHOOK_ENDPOINT", "")
        if not ep:
            return None
        return cls(ep, env.get("MINIO_AUDIT_WEBHOOK_AUTH_TOKEN", ""))

    def send(self, entry: dict) -> None:
        try:
            self._q.put_nowait(entry)
        except queue.Full:
            with self._stats_mu:
                self.dropped += 1

    def queued(self) -> int:
        """Entries waiting for the delivery worker (status surface —
        admin audit-status must not reach into the private queue)."""
        return self._q.qsize()

    def _run(self) -> None:
        while True:
            entry = self._q.get()
            if entry is None:
                return
            try:
                req = urllib.request.Request(
                    self.endpoint, data=json.dumps(entry).encode(),
                    headers={"Content-Type": "application/json",
                             **({"Authorization":
                                 f"Bearer {self.auth_token}"}
                                if self.auth_token else {})})
                urllib.request.urlopen(req, timeout=5).read()
                with self._stats_mu:
                    self.sent += 1
            except Exception:
                with self._stats_mu:
                    self.failed += 1

    def close(self) -> None:
        self._q.put(None)
