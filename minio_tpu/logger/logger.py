"""Structured logger with console ring buffer (ref cmd/logger/logger.go,
cmd/consolelogger.go — the ring feeds `mc admin console`).

Opt-in JSON mode (`MINIO_LOG_JSON=1` or config-KV ``logger json=on``):
every console line becomes one JSON object, and callers may attach
structured join-key fields (``Logger.warn(msg, src, alert_id=...,
rule=...)``) — the same way PR-4 audit entries carry ``trace_id`` —
so alert/transition/quarantine lines are machine-parseable instead of
regex fodder.  In text mode the fields render as a trailing
``[k=v ...]`` suffix; the ring keeps them structured either way.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field


@dataclass
class LogEntry:
    level: str = "INFO"
    time: float = 0.0
    message: str = ""
    source: str = ""
    trace: list = field(default_factory=list)
    # Structured join keys (alert_id, rule, ...): first-class in the
    # JSON output, suffix-rendered in text mode.
    fields: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self))


class ConsoleLogRing:
    """Last-N log entries, served to `admin console-log` (ref
    cmd/consolelogger.go HTTPConsoleLoggerSys ring)."""

    def __init__(self, size: int = 10_000):
        self._mu = threading.Lock()
        self._ring: deque[LogEntry] = deque(maxlen=size)

    def add(self, entry: LogEntry) -> None:
        with self._mu:
            self._ring.append(entry)

    def tail(self, n: int = 100) -> list[LogEntry]:
        if n <= 0:
            return []
        with self._mu:
            items = list(self._ring)
        return items[-n:]


def _env_json(env=os.environ) -> bool:
    return env.get("MINIO_LOG_JSON", "").lower() in ("1", "on", "true",
                                                     "yes")


class Logger:
    """Process-wide logger: console stderr + ring; one-time dedup of
    repeated messages (ref cmd/logger/logonce.go)."""

    _instance = None
    _instance_mu = threading.Lock()

    def __init__(self, json_output: bool | None = None):
        self.ring = ConsoleLogRing()
        # None = consult the env (MINIO_LOG_JSON); config-KV `logger
        # json` may flip this live via the server's apply hook, but
        # the env spelling wins there too (env-first rule).
        self.json_output = _env_json() if json_output is None \
            else json_output
        self._once_seen: set[str] = set()
        self._mu = threading.Lock()

    @classmethod
    def get(cls) -> "Logger":
        with cls._instance_mu:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def _emit(self, level: str, message: str, source: str = "",
              **fields) -> None:
        entry = LogEntry(level=level, time=time.time(), message=message,
                         source=source, fields=dict(fields))
        self.ring.add(entry)
        if self.json_output:
            print(entry.to_json(), file=sys.stderr)
        else:
            ts = time.strftime("%H:%M:%S", time.localtime(entry.time))
            suffix = ""
            if fields:
                kv = " ".join(f"{k}={v}" for k, v in
                              sorted(fields.items()))
                suffix = f"  [{kv}]"
            print(f"{ts} {level:<5} {message}{suffix}", file=sys.stderr)

    def info(self, message: str, source: str = "", **fields) -> None:
        self._emit("INFO", message, source, **fields)

    def error(self, message: str, source: str = "", **fields) -> None:
        self._emit("ERROR", message, source, **fields)

    def warn(self, message: str, source: str = "", **fields) -> None:
        self._emit("WARN", message, source, **fields)

    def log_once(self, message: str, source: str = "") -> None:
        """Errors that would repeat per-request are logged once (ref
        logger.LogOnceIf)."""
        with self._mu:
            if message in self._once_seen:
                return
            if len(self._once_seen) > 4096:
                self._once_seen.clear()
            self._once_seen.add(message)
        self._emit("ERROR", message, source)
