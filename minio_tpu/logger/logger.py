"""Structured logger with console ring buffer (ref cmd/logger/logger.go,
cmd/consolelogger.go — the ring feeds `mc admin console`).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field


@dataclass
class LogEntry:
    level: str = "INFO"
    time: float = 0.0
    message: str = ""
    source: str = ""
    trace: list = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(asdict(self))


class ConsoleLogRing:
    """Last-N log entries, served to `admin console-log` (ref
    cmd/consolelogger.go HTTPConsoleLoggerSys ring)."""

    def __init__(self, size: int = 10_000):
        self._mu = threading.Lock()
        self._ring: deque[LogEntry] = deque(maxlen=size)

    def add(self, entry: LogEntry) -> None:
        with self._mu:
            self._ring.append(entry)

    def tail(self, n: int = 100) -> list[LogEntry]:
        if n <= 0:
            return []
        with self._mu:
            items = list(self._ring)
        return items[-n:]


class Logger:
    """Process-wide logger: console stderr + ring; one-time dedup of
    repeated messages (ref cmd/logger/logonce.go)."""

    _instance = None
    _instance_mu = threading.Lock()

    def __init__(self, json_output: bool = False):
        self.ring = ConsoleLogRing()
        self.json_output = json_output
        self._once_seen: set[str] = set()
        self._mu = threading.Lock()

    @classmethod
    def get(cls) -> "Logger":
        with cls._instance_mu:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def _emit(self, level: str, message: str, source: str = "") -> None:
        entry = LogEntry(level=level, time=time.time(), message=message,
                         source=source)
        self.ring.add(entry)
        if self.json_output:
            print(entry.to_json(), file=sys.stderr)
        else:
            ts = time.strftime("%H:%M:%S", time.localtime(entry.time))
            print(f"{ts} {level:<5} {message}", file=sys.stderr)

    def info(self, message: str, source: str = "") -> None:
        self._emit("INFO", message, source)

    def error(self, message: str, source: str = "") -> None:
        self._emit("ERROR", message, source)

    def warn(self, message: str, source: str = "") -> None:
        self._emit("WARN", message, source)

    def log_once(self, message: str, source: str = "") -> None:
        """Errors that would repeat per-request are logged once (ref
        logger.LogOnceIf)."""
        with self._mu:
            if message in self._once_seen:
                return
            if len(self._once_seen) > 4096:
                self._once_seen.clear()
            self._once_seen.add(message)
        self._emit("ERROR", message, source)
