"""Declarative data-plane pipelines (the framework's "models").

A pipeline here is an erasure-coding configuration plus the jittable compute
graph that implements its hot path (encode / reconstruct / hash) on TPU.
"""
