"""The flagship data-plane pipeline: erasure-code step as a jittable graph.

This is the framework's "model": a declarative EC configuration (k data +
m parity, shard size) compiled into the TPU hot path that a PutObject /
GetObject / heal dispatches to (ref call stacks: cmd/erasure-object.go:582
encode, :240 decode, cmd/erasure-healing.go:224 heal).

forward step  = encode:      (B, k, S) data shards   -> (B, k+m, S)
reconstruct   = decode:      (B, k, S) survivors     -> (B, r, S) rebuilt
verify        = parity check reduced to one scalar per batch (psum across
                the mesh in the sharded path).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import rs_tpu
from ..ops.rs_matrix import encode_matrix
from ..utils import ceil_frac

# Reference stripe block: 10 MiB (ref cmd/object-api-common.go:32).
DEFAULT_BLOCK_SIZE = 10 * 1024 * 1024


@dataclass(frozen=True)
class ECConfig:
    data_shards: int
    parity_shards: int
    block_size: int = DEFAULT_BLOCK_SIZE

    @property
    def total_shards(self) -> int:
        return self.data_shards + self.parity_shards

    @property
    def shard_size(self) -> int:
        """Per-shard bytes of one full stripe block (ref ShardSize,
        cmd/erasure-coding.go:115)."""
        return ceil_frac(self.block_size, self.data_shards)


class ECPipeline:
    """Compiled erasure pipeline for one EC geometry."""

    def __init__(self, config: ECConfig):
        self.config = config

    @cached_property
    def parity_bitplane(self) -> jnp.ndarray:
        return jnp.asarray(
            rs_tpu.parity_bitplane(self.config.data_shards,
                                   self.config.parity_shards))

    @cached_property
    def encode_fn(self):
        """Jittable (big_m, (B, k, S) uint8) -> (B, k+m, S) uint8."""
        return rs_tpu.encode_blocks

    def encode(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(self.encode_fn(self.parity_bitplane,
                                         jnp.asarray(data)))

    def reconstruct(self, survivors: np.ndarray,
                    available: tuple[int, ...],
                    missing: tuple[int, ...]) -> np.ndarray:
        return rs_tpu.reconstruct_batch(
            survivors, self.config.data_shards, self.config.parity_shards,
            available, missing)

    def example_args(self, batch: int = 4, shard_size: int = 4096,
                     seed: int = 0) -> tuple[jnp.ndarray, jnp.ndarray]:
        rng = np.random.default_rng(seed)
        data = rng.integers(
            0, 256, (batch, self.config.data_shards, shard_size),
        ).astype(np.uint8)
        return self.parity_bitplane, jnp.asarray(data)


def full_step(big_enc: jnp.ndarray, big_dec: jnp.ndarray,
              data: jnp.ndarray, survivor_idx: jnp.ndarray) -> dict:
    """One full data-plane step, for multi-chip compilation checks:
    encode -> simulated shard loss -> reconstruct -> global verify.

    survivor_idx: (k,) int32 indices of surviving shards (static-shaped
    gather, dynamic values). Returns rebuilt shards and a global integrity
    scalar (sum over everything — reduces across the mesh).
    """
    shards = rs_tpu.encode_blocks(big_enc, data)
    survivors = jnp.take(shards, survivor_idx, axis=-2)
    rebuilt = rs_tpu.gf_apply(big_dec, survivors)
    mismatch = jnp.sum(
        (rebuilt.astype(jnp.int32) - data.astype(jnp.int32)) != 0)
    return {"shards": shards, "rebuilt": rebuilt, "mismatch": mismatch}


def make_full_step_inputs(config: ECConfig, batch: int, shard_size: int,
                          missing: tuple[int, ...], seed: int = 0):
    """Host-side prep for full_step: matrices + data + survivor indices.

    `missing` are data-shard indices knocked out; the decode matrix rebuilds
    exactly those from the first-k survivors (klauspost ReconstructData
    order — see rs_matrix.decode_matrix).
    """
    k, m = config.data_shards, config.parity_shards
    available = tuple(i for i in range(k + m) if i not in missing)
    # full_step compares rebuilt vs the full data input, so the decode
    # matrix covers every data shard (not just `missing`).
    dec_all, used = rs_tpu.decode_bitplane(k, m, available,
                                           tuple(range(k)))
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, (batch, k, shard_size)).astype(np.uint8)
    big_enc = rs_tpu.parity_bitplane(k, m)
    return (jnp.asarray(big_enc), jnp.asarray(dec_all), jnp.asarray(data),
            jnp.asarray(np.array(used, dtype=np.int32)))
