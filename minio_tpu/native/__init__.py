"""Native (C++) host-side kernels, built on demand with g++ via ctypes.

The reference delegates its host hot loops to SIMD assembly libraries
(SURVEY §2.7). Here the host fallback/cryptographic loops live in C++
compiled once into a shared object under build/; the TPU kernels remain the
primary data plane. Everything degrades gracefully to pure Python if a
compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "build")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False


def _compile(srcs: list[str], so: str) -> None:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = so + ".tmp"
    subprocess.run(
        ["g++", "-O3", "-march=native", "-pthread", "-shared", "-fPIC",
         "-o", tmp] + srcs,
        check=True, capture_output=True, timeout=120)
    os.replace(tmp, so)


def _build_and_load() -> ctypes.CDLL | None:
    srcs = [os.path.join(_HERE, "highwayhash.cc"),
            os.path.join(_HERE, "lzblock.cc"),
            os.path.join(_HERE, "rs.cc")]
    so = os.path.join(_BUILD_DIR, "libminio_tpu_native.so")
    try:
        if (not os.path.exists(so)
                or any(os.path.getmtime(so) < os.path.getmtime(s)
                       for s in srcs)):
            _compile(srcs, so)
        lib = ctypes.CDLL(so)
        if not hasattr(lib, "rs_gf_apply_mt"):  # newest symbol
            # Stale cached .so predating a source (mtime preserved by
            # tar/rsync/docker-copy): rebuild rather than silently
            # disabling EVERY native path on the missing-symbol error.
            _compile(srcs, so)
            lib = ctypes.CDLL(so)
        lib.hh256_hash.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_size_t, ctypes.c_char_p]
        lib.hh256_hash.restype = None
        lib.hh256_chunks.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                     ctypes.c_size_t, ctypes.c_size_t,
                                     ctypes.c_char_p]
        lib.hh256_chunks.restype = ctypes.c_size_t
        lib.lzb_max_compressed.argtypes = [ctypes.c_size_t]
        lib.lzb_max_compressed.restype = ctypes.c_size_t
        lib.lzb_compress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                     ctypes.c_char_p, ctypes.c_size_t]
        lib.lzb_compress.restype = ctypes.c_long
        lib.lzb_decompress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                       ctypes.c_char_p, ctypes.c_size_t]
        lib.lzb_decompress.restype = ctypes.c_long
        lib.rs_gf_apply.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                    ctypes.c_size_t, ctypes.c_void_p,
                                    ctypes.c_size_t, ctypes.c_void_p]
        lib.rs_gf_apply.restype = None
        lib.rs_gf_apply_mt.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                       ctypes.c_size_t, ctypes.c_void_p,
                                       ctypes.c_size_t, ctypes.c_void_p,
                                       ctypes.c_size_t]
        lib.rs_gf_apply_mt.restype = None
        return lib
    except Exception:
        return None


def get_lib() -> ctypes.CDLL | None:
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        with _LOCK:
            if _LIB is None and not _TRIED:
                _LIB = _build_and_load()
                _TRIED = True
    return _LIB


def _disable_native(reason: str) -> None:
    """A native kernel returned inconsistent results: distrust the
    whole library for the rest of the process (every caller degrades
    to its host/pure-Python path) and say so loudly once.  The
    kernprof backend state machine hears about it too, so the 'native'
    lane shows DEGRADED/DOWN on the health surfaces and the recovery
    probe (``probe()``) owns re-adoption."""
    global _LIB, _TRIED
    import logging
    with _LOCK:
        _LIB = None
        _TRIED = True
    logging.getLogger("minio_tpu.native").warning(
        "native kernel disabled: %s", reason)
    try:
        from ..obs.kernprof import KERNPROF, NATIVE
        KERNPROF.dispatch_failed(NATIVE, reason)
    except Exception:
        pass  # never let telemetry break the degrade path


def probe() -> bool:
    """Recovery probe for the kernprof 'native' backend: re-attempt
    build+load (a ``_disable_native`` poisons the cached handle for
    the process — this is the only path that un-poisons it) and run a
    known-answer self-check through both exported kernel families.
    True only when the library loads AND answers correctly."""
    global _TRIED
    with _LOCK:
        if _LIB is None:
            _TRIED = False  # allow get_lib() to rebuild/reload
    if get_lib() is None:
        return False
    try:
        import numpy as np

        from ..ops.gf256 import gf_mat_vec_apply
        from ..ops.hh256 import MAGIC_KEY, HighwayHash256
        data = b"minio-tpu native probe"
        want = HighwayHash256(MAGIC_KEY).update(data).digest()
        if hh256_native(data, MAGIC_KEY) != want:
            _disable_native("probe: hh256 known-answer mismatch")
            return False
        mat = np.array([[1, 2], [3, 4]], dtype=np.uint8)
        cols = np.arange(2 * 64, dtype=np.uint8).reshape(2, 64)
        got = rs_apply_native(mat, cols)
        if got is None or not (got == gf_mat_vec_apply(mat,
                                                       cols)).all():
            _disable_native("probe: rs_gf_apply known-answer mismatch")
            return False
        return True
    except Exception as exc:  # noqa: BLE001 - a probe must not raise
        _disable_native(f"probe raised: {exc!r}")
        return False


def hh256_native(data: bytes, key: bytes) -> bytes | None:
    """One-shot HighwayHash-256 via C++; None if native lib unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(32)
    lib.hh256_hash(key, bytes(data), len(data), out)
    return out.raw


def hh256_chunks_native(data: bytes, chunk_size: int,
                        key: bytes) -> list[bytes] | None:
    """Hash consecutive chunk_size chunks (streaming-bitrot pattern)."""
    lib = get_lib()
    if lib is None:
        return None
    if len(data) == 0:
        return []
    n = -(-len(data) // chunk_size)
    out = ctypes.create_string_buffer(32 * n)
    got = lib.hh256_chunks(key, bytes(data), len(data), chunk_size, out)
    if got != n:
        # A short/garbled native return must NOT surface truncated
        # digests as "valid" (a bare assert here vanishes under -O):
        # fall back to the pure-Python path by reporting unavailable.
        _disable_native(f"hh256_chunks returned {got}, expected {n}")
        return None
    return [out.raw[i * 32:(i + 1) * 32] for i in range(n)]


def hh256_rows_native(arr, key: bytes):
    """Hash each row of a CONTIGUOUS (n, chunk) uint8 array -> (n, 32)
    uint8 array, with zero input copies (the array's buffer is handed
    straight to the C kernel). None if the native lib is unavailable.
    Byte-identical to hh256_chunks_native over arr.tobytes()."""
    lib = get_lib()
    if lib is None:
        return None
    import numpy as np
    if arr.size == 0:
        return np.empty((0, 32), dtype=np.uint8)
    a = np.ascontiguousarray(arr, dtype=np.uint8)
    n, chunk = a.shape
    out = np.empty((n, 32), dtype=np.uint8)
    got = lib.hh256_chunks(
        key, ctypes.cast(a.ctypes.data, ctypes.c_char_p), a.size,
        chunk, ctypes.cast(out.ctypes.data, ctypes.c_char_p))
    if got != n:
        # Explicit check (not a bare assert — stripped under -O): a
        # wrong row count means the output buffer is untrustworthy.
        _disable_native(f"hh256_chunks returned {got}, expected {n}")
        return None
    return out


# Large host applies (heal sweeps, mask-group folds in degraded mode)
# spread column ranges across threads; small ones stay single-threaded
# so per-request latency paths and the bench baseline are unaffected.
RS_MT_THRESHOLD = 8 * 1024 * 1024


def rs_apply_native(mat, data):
    """(r, k) GF(2^8) matrix applied to (k, n) byte rows -> (r, n), via
    the C++ nibble-shuffle kernel (native/rs.cc). None when the native
    lib is unavailable. Byte-identical to gf256.gf_mat_vec_apply.
    """
    lib = get_lib()
    if lib is None:
        return None
    import numpy as np
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    r, k = mat.shape
    if data.shape[0] != k:
        raise ValueError(f"data rows {data.shape[0]} != k={k}")
    n = data.shape[1]
    out = np.empty((r, n), dtype=np.uint8)
    if data.nbytes >= RS_MT_THRESHOLD:
        nthreads = min(8, os.cpu_count() or 1)
        lib.rs_gf_apply_mt(mat.ctypes.data, r, k, data.ctypes.data, n,
                           out.ctypes.data, nthreads)
    else:
        lib.rs_gf_apply(mat.ctypes.data, r, k, data.ctypes.data, n,
                        out.ctypes.data)
    return out


def lzb_compress_native(data: bytes) -> bytes | None:
    """LZ-block compress; None when native lib unavailable OR the data
    is incompressible (caller stores raw either way)."""
    lib = get_lib()
    if lib is None or len(data) == 0:
        return None
    cap = lib.lzb_max_compressed(len(data))
    out = ctypes.create_string_buffer(cap)
    got = lib.lzb_compress(bytes(data), len(data), out, cap)
    if got <= 0:
        return None
    return out.raw[:got]


def lzb_decompress_native(blob: bytes, out_size: int) -> bytes | None:
    lib = get_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(max(out_size, 1))
    got = lib.lzb_decompress(bytes(blob), len(blob), out, out_size)
    if got < 0:
        raise ValueError("corrupt lzb block")
    return out.raw[:got]
