// HighwayHash-256 — portable scalar C++ implementation.
//
// Host-side hot loop for bitrot checksums (the reference uses
// minio/highwayhash SIMD assembly; ref cmd/bitrot.go:35-46,
// cmd/bitrot-streaming.go:46). Byte-identical output is enforced by the
// Python tests against the magic pi-key golden vector.
//
// C API (ctypes):
//   hh256_hash(key32, data, len, out32)
//   hh256_chunks(key32, data, len, chunk_size, out) — hash consecutive
//     chunk_size-byte chunks (last may be short), out = 32B per chunk.
//     This is exactly the streaming-bitrot per-shard-block pattern.

#include <cstdint>
#include <cstring>
#include <cstddef>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

struct State {
  uint64_t v0[4], v1[4], mul0[4], mul1[4];
};

const uint64_t kInit0[4] = {0xdbe6d5d5fe4cce2fULL, 0xa4093822299f31d0ULL,
                            0x13198a2e03707344ULL, 0x243f6a8885a308d3ULL};
const uint64_t kInit1[4] = {0x3bd39e10cb0ef593ULL, 0xc0acf169b5f18a8cULL,
                            0xbe5466cf34e90c6cULL, 0x452821e638d01377ULL};

inline uint64_t Read64LE(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;  // x86_64 is little-endian
}

inline void Reset(const uint64_t key[4], State* s) {
  for (int i = 0; i < 4; ++i) {
    s->mul0[i] = kInit0[i];
    s->mul1[i] = kInit1[i];
    s->v0[i] = kInit0[i] ^ key[i];
    s->v1[i] = kInit1[i] ^ ((key[i] >> 32) | (key[i] << 32));
  }
}

inline void ZipperMergeAndAdd(const uint64_t v1, const uint64_t v0,
                              uint64_t* add1, uint64_t* add0) {
  *add0 += (((v0 & 0xff000000ULL) | (v1 & 0xff00000000ULL)) >> 24) |
           (((v0 & 0xff0000000000ULL) | (v1 & 0xff000000000000ULL)) >> 16) |
           (v0 & 0xff0000ULL) | ((v0 & 0xff00ULL) << 32) |
           ((v1 & 0xff00000000000000ULL) >> 8) | (v0 << 56);
  *add1 += (((v1 & 0xff000000ULL) | (v0 & 0xff00000000ULL)) >> 24) |
           (v1 & 0xff0000ULL) | ((v1 & 0xff0000000000ULL) >> 16) |
           ((v1 & 0xff00ULL) << 24) | ((v0 & 0xff000000000000ULL) >> 8) |
           ((v1 & 0xffULL) << 48) | (v0 & 0xff00000000000000ULL);
}

inline void UpdateLanes(const uint64_t lanes[4], State* s) {
  for (int i = 0; i < 4; ++i) {
    s->v1[i] += s->mul0[i] + lanes[i];
    s->mul0[i] ^= (s->v1[i] & 0xffffffff) * (s->v0[i] >> 32);
    s->v0[i] += s->mul1[i];
    s->mul1[i] ^= (s->v0[i] & 0xffffffff) * (s->v1[i] >> 32);
  }
  ZipperMergeAndAdd(s->v1[1], s->v1[0], &s->v0[1], &s->v0[0]);
  ZipperMergeAndAdd(s->v1[3], s->v1[2], &s->v0[3], &s->v0[2]);
  ZipperMergeAndAdd(s->v0[1], s->v0[0], &s->v1[1], &s->v1[0]);
  ZipperMergeAndAdd(s->v0[3], s->v0[2], &s->v1[3], &s->v1[2]);
}

inline void UpdatePacket(const uint8_t* packet, State* s) {
  uint64_t lanes[4];
  for (int i = 0; i < 4; ++i) lanes[i] = Read64LE(packet + 8 * i);
  UpdateLanes(lanes, s);
}

inline void Rotate32By(uint64_t count, uint64_t lanes[4]) {
  for (int i = 0; i < 4; ++i) {
    uint32_t half0 = static_cast<uint32_t>(lanes[i] & 0xffffffff);
    uint32_t half1 = static_cast<uint32_t>(lanes[i] >> 32);
    uint32_t c = static_cast<uint32_t>(count) & 31;
    uint32_t r0 = c ? ((half0 << c) | (half0 >> (32 - c))) : half0;
    uint32_t r1 = c ? ((half1 << c) | (half1 >> (32 - c))) : half1;
    lanes[i] = (static_cast<uint64_t>(r1) << 32) | r0;
  }
}

inline void UpdateRemainder(const uint8_t* bytes, const size_t size_mod32,
                            State* s) {
  const size_t size_mod4 = size_mod32 & 3;
  const uint8_t* remainder = bytes + (size_mod32 & ~3);
  uint8_t packet[32] = {0};
  for (int i = 0; i < 4; ++i) {
    s->v0[i] += (static_cast<uint64_t>(size_mod32) << 32) + size_mod32;
  }
  Rotate32By(size_mod32, s->v1);
  memcpy(packet, bytes, size_mod32 & ~3);
  if (size_mod32 & 16) {
    for (int i = 0; i < 4; ++i) {
      packet[28 + i] = remainder[i + size_mod4 - 4];
    }
  } else if (size_mod4) {
    packet[16 + 0] = remainder[0];
    packet[16 + 1] = remainder[size_mod4 >> 1];
    packet[16 + 2] = remainder[size_mod4 - 1];
  }
  UpdatePacket(packet, s);
}

inline void PermuteAndUpdate(State* s) {
  uint64_t permuted[4];
  permuted[0] = (s->v0[2] >> 32) | (s->v0[2] << 32);
  permuted[1] = (s->v0[3] >> 32) | (s->v0[3] << 32);
  permuted[2] = (s->v0[0] >> 32) | (s->v0[0] << 32);
  permuted[3] = (s->v0[1] >> 32) | (s->v0[1] << 32);
  UpdateLanes(permuted, s);
}

inline void ModularReduction(uint64_t a3_unmasked, uint64_t a2, uint64_t a1,
                             uint64_t a0, uint64_t* m1, uint64_t* m0) {
  uint64_t a3 = a3_unmasked & 0x3FFFFFFFFFFFFFFFULL;
  *m1 = a1 ^ ((a3 << 1) | (a2 >> 63)) ^ ((a3 << 2) | (a2 >> 62));
  *m0 = a0 ^ (a2 << 1) ^ (a2 << 2);
}

inline void Finalize256(State* s, uint64_t hash[4]) {
  for (int i = 0; i < 10; ++i) PermuteAndUpdate(s);
  ModularReduction(s->v1[1] + s->mul1[1], s->v1[0] + s->mul1[0],
                   s->v0[1] + s->mul0[1], s->v0[0] + s->mul0[0], &hash[1],
                   &hash[0]);
  ModularReduction(s->v1[3] + s->mul1[3], s->v1[2] + s->mul1[2],
                   s->v0[3] + s->mul0[3], s->v0[2] + s->mul0[2], &hash[3],
                   &hash[2]);
}

// --- AVX2 hot loop ----------------------------------------------------------
//
// The four independent 64-bit lanes map 1:1 onto one __m256i, and the
// zipper merge is one per-128-bit-half byte shuffle (the control bytes
// below are DERIVED from ZipperMergeAndAdd's masks: output byte j of
// the low half takes input byte {3,12,2,5,14,1,15,0}[j] of the
// [v0_lane0||v0_lane1] 16-byte pair, and the high half
// {11,4,10,13,9,6,8,7} — matching the reference's SIMD shuffle
// pattern). Only the full-packet loop is vectorized; remainder and
// finalize reuse the scalar code on the stored-back state, keeping the
// tricky paths single-sourced. Byte-identity with the scalar path is
// pinned by tests/test_hh256.py's golden vectors.

#if defined(__x86_64__)
__attribute__((target("avx2")))
inline __m256i MulLo32(const __m256i a, const __m256i b_hi) {
  // (a & 0xffffffff) * (b >> 32) per 64-bit lane.
  return _mm256_mul_epu32(a, _mm256_srli_epi64(b_hi, 32));
}

__attribute__((target("avx2")))
size_t UpdatePacketsAVX2(const uint8_t* data, size_t len, State* s) {
  const __m256i zipper = _mm256_setr_epi8(
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7,
      3, 12, 2, 5, 14, 1, 15, 0, 11, 4, 10, 13, 9, 6, 8, 7);
  __m256i v0 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->v0));
  __m256i v1 = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->v1));
  __m256i mul0 =
      _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->mul0));
  __m256i mul1 =
      _mm256_loadu_si256(reinterpret_cast<const __m256i*>(s->mul1));
  size_t i = 0;
  for (; i + 32 <= len; i += 32) {
    const __m256i lanes =
        _mm256_loadu_si256(reinterpret_cast<const __m256i*>(data + i));
    v1 = _mm256_add_epi64(v1, _mm256_add_epi64(mul0, lanes));
    mul0 = _mm256_xor_si256(mul0, MulLo32(v1, v0));
    v0 = _mm256_add_epi64(v0, mul1);
    mul1 = _mm256_xor_si256(mul1, MulLo32(v0, v1));
    v0 = _mm256_add_epi64(v0, _mm256_shuffle_epi8(v1, zipper));
    v1 = _mm256_add_epi64(v1, _mm256_shuffle_epi8(v0, zipper));
  }
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->v0), v0);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->v1), v1);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->mul0), mul0);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(s->mul1), mul1);
  return i;
}

inline bool HaveAVX2() {
  static const bool have = __builtin_cpu_supports("avx2");
  return have;
}
#else
inline bool HaveAVX2() { return false; }
inline size_t UpdatePacketsAVX2(const uint8_t*, size_t, State*) { return 0; }
#endif

inline void HashOne(const uint64_t key[4], const uint8_t* data, size_t len,
                    uint8_t out[32]) {
  State s;
  Reset(key, &s);
  size_t i = 0;
  if (HaveAVX2()) {
    i = UpdatePacketsAVX2(data, len, &s);
  } else {
    for (; i + 32 <= len; i += 32) UpdatePacket(data + i, &s);
  }
  if (len & 31) UpdateRemainder(data + i, len & 31, &s);
  uint64_t hash[4];
  Finalize256(&s, hash);
  memcpy(out, hash, 32);
}

}  // namespace

extern "C" {

void hh256_hash(const uint8_t* key32, const uint8_t* data, size_t len,
                uint8_t* out32) {
  uint64_t key[4];
  memcpy(key, key32, 32);
  HashOne(key, data, len, out32);
}

// Hash consecutive chunk_size chunks of data (last chunk may be short).
// out must hold 32 * ceil(len / chunk_size) bytes. Returns chunk count.
size_t hh256_chunks(const uint8_t* key32, const uint8_t* data, size_t len,
                    size_t chunk_size, uint8_t* out) {
  uint64_t key[4];
  memcpy(key, key32, 32);
  size_t n = 0;
  for (size_t off = 0; off < len; off += chunk_size, ++n) {
    size_t this_len = len - off < chunk_size ? len - off : chunk_size;
    HashOne(key, data + off, this_len, out + 32 * n);
  }
  return n;
}

}  // extern "C"
