// LZ77 byte-oriented block codec — the native transparent-compression
// hot loop (the reference's analog is klauspost/compress/s2's assembly
// block codec, SURVEY §2.7; the TPU is not a fit for LZ-family codecs,
// so this stays on the host as C++).
//
// Block format (literals/match token stream, LZ4-block-flavored):
//   token byte: high nibble = literal run length (15 = extended),
//               low nibble  = match length - 4   (15 = extended)
//   [extended literal length bytes*] [literals]
//   [2-byte little-endian match offset] [extended match length bytes*]
//   The final sequence carries literals only (offset omitted).
// Extended lengths: 255 bytes accumulate until a byte < 255.
//
// Exposed C API (ctypes):
//   lzb_max_compressed(n)                 -> worst-case output bound
//   lzb_compress(src, n, dst, cap)        -> compressed size, or 0 if
//                                            incompressible/cap hit
//   lzb_decompress(src, n, dst, cap)      -> output size, or -1 on
//                                            malformed input

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr int MIN_MATCH = 4;
constexpr int HASH_BITS = 16;
constexpr int MAX_OFFSET = 65535;

inline uint32_t load32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
}

inline uint32_t hash4(uint32_t v) {
    return (v * 2654435761u) >> (32 - HASH_BITS);
}

inline uint8_t* put_len(uint8_t* op, size_t len) {
    while (len >= 255) { *op++ = 255; len -= 255; }
    *op++ = (uint8_t)len;
    return op;
}

}  // namespace

extern "C" {

size_t lzb_max_compressed(size_t n) {
    return n + n / 255 + 16;
}

// Greedy single-pass hash-chain-less LZ (one hash slot per bucket).
long lzb_compress(const uint8_t* src, size_t n, uint8_t* dst,
                  size_t cap) {
    if (n < 16 || cap < 16) return 0;
    uint32_t table[1 << HASH_BITS];
    std::memset(table, 0, sizeof(table));

    const uint8_t* ip = src;
    const uint8_t* iend = src + n;
    const uint8_t* match_limit = iend - 8;   // last bytes stay literals
    const uint8_t* anchor = src;
    uint8_t* op = dst;
    uint8_t* oend = dst + cap;

    while (ip < match_limit) {
        uint32_t h = hash4(load32(ip));
        size_t cand = table[h];
        table[h] = (uint32_t)(ip - src);
        const uint8_t* cp = src + cand;
        if (cand != 0 && cp < ip && (size_t)(ip - cp) <= MAX_OFFSET &&
            load32(cp) == load32(ip)) {
            // Extend the match forward.
            const uint8_t* m = cp + 4;
            const uint8_t* p = ip + 4;
            while (p < match_limit && *p == *m) { ++p; ++m; }
            size_t mlen = (size_t)(p - ip);
            if (mlen >= MIN_MATCH) {
                size_t lit = (size_t)(ip - anchor);
                // Worst-case emit size for this sequence.
                if (op + 1 + lit / 255 + 1 + lit + 2 + mlen / 255 + 1
                    > oend)
                    return 0;
                uint8_t* token = op++;
                size_t ml = mlen - MIN_MATCH;
                *token = (uint8_t)(((lit < 15 ? lit : 15) << 4) |
                                   (ml < 15 ? ml : 15));
                if (lit >= 15) op = put_len(op, lit - 15);
                std::memcpy(op, anchor, lit);
                op += lit;
                size_t off = (size_t)(ip - cp);
                *op++ = (uint8_t)(off & 0xff);
                *op++ = (uint8_t)(off >> 8);
                if (ml >= 15) op = put_len(op, ml - 15);
                ip = p;
                anchor = ip;
                continue;
            }
        }
        ++ip;
    }
    // Trailing literals-only sequence.
    size_t lit = (size_t)(iend - anchor);
    if (op + 1 + lit / 255 + 1 + lit > oend) return 0;
    uint8_t* token = op++;
    *token = (uint8_t)((lit < 15 ? lit : 15) << 4);
    if (lit >= 15) op = put_len(op, lit - 15);
    std::memcpy(op, anchor, lit);
    op += lit;

    size_t out = (size_t)(op - dst);
    if (out >= n) return 0;  // incompressible: caller stores raw
    return (long)out;
}

long lzb_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                    size_t cap) {
    const uint8_t* ip = src;
    const uint8_t* iend = src + n;
    uint8_t* op = dst;
    uint8_t* oend = dst + cap;

    while (ip < iend) {
        uint8_t token = *ip++;
        // Literals.
        size_t lit = token >> 4;
        if (lit == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                lit += b;
            } while (b == 255);
        }
        if (ip + lit > iend || op + lit > oend) return -1;
        std::memcpy(op, ip, lit);
        ip += lit;
        op += lit;
        if (ip >= iend) break;  // final literals-only sequence
        // Match.
        if (ip + 2 > iend) return -1;
        size_t off = (size_t)ip[0] | ((size_t)ip[1] << 8);
        ip += 2;
        if (off == 0 || (size_t)(op - dst) < off) return -1;
        size_t mlen = token & 0x0f;
        if (mlen == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                mlen += b;
            } while (b == 255);
        }
        mlen += MIN_MATCH;
        if (op + mlen > oend) return -1;
        const uint8_t* m = op - off;
        // Byte copy: overlapping matches (off < mlen) must replicate.
        for (size_t i = 0; i < mlen; ++i) op[i] = m[i];
        op += mlen;
    }
    return (long)(op - dst);
}

}  // extern "C"
