// GF(2^8) Reed-Solomon matrix apply — the host-side fast path.
//
// Field: x^8 + x^4 + x^3 + x^2 + 1 (0x11D), generator 2 — identical to
// ops/gf256.py, so outputs are byte-identical to the golden numpy codec.
//
// Technique: per-coefficient low/high-nibble product tables applied with
// byte shuffles ("Screaming Fast Galois Field Arithmetic", Plank et al.;
// the same published technique the reference's SIMD codec dependency
// implements in assembly — reimplemented here from the field definition,
// not ported). AVX2 when available at compile time, SSSE3 next, plain
// table loop otherwise.
//
// Exported C ABI:
//   rs_gf_apply(mat, r, k, data, n, out)
//     mat:  r*k coefficient bytes (row-major)
//     data: k rows of n bytes (row-major, contiguous)
//     out:  r rows of n bytes (written)

#include <cstdint>
#include <cstring>
#include <cstddef>
#include <thread>
#include <vector>

#if defined(__AVX2__) || defined(__SSSE3__)
#include <immintrin.h>
#endif

namespace {

struct Tables {
    uint8_t exp[512];
    uint8_t log[256];
    Tables() {
        int x = 1;
        for (int i = 0; i < 255; i++) {
            exp[i] = static_cast<uint8_t>(x);
            log[x] = static_cast<uint8_t>(i);
            x <<= 1;
            if (x & 0x100) x ^= 0x11D;
        }
        for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
        log[0] = 0;
    }
    inline uint8_t mul(uint8_t a, uint8_t b) const {
        if (a == 0 || b == 0) return 0;
        return exp[log[a] + log[b]];
    }
};

const Tables T;

// 16-entry product tables for coefficient c: lo[x] = c*x,
// hi[x] = c*(x<<4); c*b = lo[b & 15] ^ hi[b >> 4].
inline void nibble_tables(uint8_t c, uint8_t lo[16], uint8_t hi[16]) {
    for (int x = 0; x < 16; x++) {
        lo[x] = T.mul(c, static_cast<uint8_t>(x));
        hi[x] = T.mul(c, static_cast<uint8_t>(x << 4));
    }
}

// acc[0..n) ^= c * src[0..n)
void axpy_gf(uint8_t c, const uint8_t* src, uint8_t* acc, size_t n) {
    if (c == 0) return;
    uint8_t lo[16], hi[16];
    nibble_tables(c, lo, hi);
    size_t i = 0;
#if defined(__AVX2__)
    const __m128i lo128 = _mm_loadu_si128(
        reinterpret_cast<const __m128i*>(lo));
    const __m128i hi128 = _mm_loadu_si128(
        reinterpret_cast<const __m128i*>(hi));
    const __m256i tlo = _mm256_broadcastsi128_si256(lo128);
    const __m256i thi = _mm256_broadcastsi128_si256(hi128);
    const __m256i mask = _mm256_set1_epi8(0x0F);
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(src + i));
        __m256i vlo = _mm256_and_si256(v, mask);
        __m256i vhi = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(tlo, vlo),
                                     _mm256_shuffle_epi8(thi, vhi));
        __m256i a = _mm256_loadu_si256(
            reinterpret_cast<const __m256i*>(acc + i));
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(acc + i),
                            _mm256_xor_si256(a, p));
    }
#elif defined(__SSSE3__)
    const __m128i tlo = _mm_loadu_si128(
        reinterpret_cast<const __m128i*>(lo));
    const __m128i thi = _mm_loadu_si128(
        reinterpret_cast<const __m128i*>(hi));
    const __m128i mask = _mm_set1_epi8(0x0F);
    for (; i + 16 <= n; i += 16) {
        __m128i v = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(src + i));
        __m128i vlo = _mm_and_si128(v, mask);
        __m128i vhi = _mm_and_si128(_mm_srli_epi64(v, 4), mask);
        __m128i p = _mm_xor_si128(_mm_shuffle_epi8(tlo, vlo),
                                  _mm_shuffle_epi8(thi, vhi));
        __m128i a = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(acc + i));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(acc + i),
                         _mm_xor_si128(a, p));
    }
#endif
    for (; i < n; i++) acc[i] ^= lo[src[i] & 0x0F] ^ hi[src[i] >> 4];
}

}  // namespace

namespace {

void apply_cols(const uint8_t* mat, size_t r, size_t k,
                const uint8_t* data, size_t n,
                size_t col0, size_t col1, uint8_t* out) {
    for (size_t i = 0; i < r; i++) {
        uint8_t* acc = out + i * n + col0;
        std::memset(acc, 0, col1 - col0);
        for (size_t j = 0; j < k; j++) {
            axpy_gf(mat[i * k + j], data + j * n + col0, acc,
                    col1 - col0);
        }
    }
}

}  // namespace

extern "C" {

// nthreads <= 1: single-threaded. Column ranges are independent (GF
// math is per-byte-column), so threads never share output bytes.
void rs_gf_apply_mt(const uint8_t* mat, size_t r, size_t k,
                    const uint8_t* data, size_t n, uint8_t* out,
                    size_t nthreads) {
    if (nthreads <= 1 || n < 2 * nthreads) {
        apply_cols(mat, r, k, data, n, 0, n, out);
        return;
    }
    std::vector<std::thread> ts;
    ts.reserve(nthreads);
    // 64-byte-aligned chunk boundaries keep SIMD lanes off seams.
    // Ceiling division: nthreads * chunk must cover ALL n columns.
    size_t chunk = (((n + nthreads - 1) / nthreads) + 63) & ~size_t(63);
    for (size_t t = 0; t < nthreads; t++) {
        size_t c0 = t * chunk;
        if (c0 >= n) break;
        size_t c1 = c0 + chunk < n ? c0 + chunk : n;
        ts.emplace_back(apply_cols, mat, r, k, data, n, c0, c1, out);
    }
    for (auto& th : ts) th.join();
}

void rs_gf_apply(const uint8_t* mat, size_t r, size_t k,
                 const uint8_t* data, size_t n, uint8_t* out) {
    apply_cols(mat, r, k, data, n, 0, n, out);
}

}  // extern "C"
