"""Observability: span-based request tracing (span.py), the metrics-v2
registry with node/cluster Prometheus endpoints (metrics2.py), TPU
kernel accounting (kernel_stats.py), per-dispatch kernel profiling +
backend health (kernprof.py), the cluster timeline sample ring
(timeline.py), and the SLO watchdog + incident recorder
(watchdog.py, incidents.py). See docs/observability.md."""

from .incidents import INCIDENTS
from .kernel_stats import KERNEL
from .kernprof import KERNPROF
from .metrics2 import METRICS2
from .span import TRACER, current_span
from .timeline import TIMELINE
from .watchdog import WATCHDOG

__all__ = ["INCIDENTS", "KERNEL", "KERNPROF", "METRICS2", "TIMELINE",
           "TRACER", "WATCHDOG", "current_span"]
