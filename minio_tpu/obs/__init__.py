"""Observability: span-based request tracing (span.py), the metrics-v2
registry with node/cluster Prometheus endpoints (metrics2.py), TPU
kernel accounting (kernel_stats.py), per-dispatch kernel profiling +
backend health (kernprof.py), and the cluster timeline sample ring
(timeline.py). See docs/observability.md."""

from .kernel_stats import KERNEL
from .kernprof import KERNPROF
from .metrics2 import METRICS2
from .span import TRACER, current_span
from .timeline import TIMELINE

__all__ = ["KERNEL", "KERNPROF", "METRICS2", "TIMELINE", "TRACER",
           "current_span"]
