"""Observability: span-based request tracing (span.py), the metrics-v2
registry with node/cluster Prometheus endpoints (metrics2.py), and TPU
kernel accounting (kernel_stats.py). See docs/observability.md."""

from .kernel_stats import KERNEL
from .metrics2 import METRICS2
from .span import TRACER, current_span

__all__ = ["KERNEL", "METRICS2", "TRACER", "current_span"]
