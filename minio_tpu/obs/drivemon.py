"""Per-drive health monitor: rolling latency EWMAs + error tracking
with peer-relative outlier scoring.

The dominant failure mode in large erasure-coded arrays is not the dead
disk (quorum absorbs that) but the SLOW one: every quorum fan-out waits
on its laggard, so a single degraded drive silently drags the whole
set's tail (arXiv:1709.05365 measures exactly this on large SSD arrays;
the Mojette evaluation in arXiv:1504.07038 shows the same tail
sensitivity for hot data). The reference tracks per-drive health for
`mc admin obd`; this module closes the loop for the TPU stack.

Recording points (both boundaries the data plane actually crosses):
  - ``storage/xl.py`` ``_DiskOp`` — every local disk op;
  - ``rpc/storage.py`` ``RemoteStorage._call`` — every remote-disk RPC
    (wire time included, which is what the caller's quorum waits on).

Model: per (drive, op-class in read/write/stat/delete) latency EWMA,
advanced when a drive closes an evaluation window (``WINDOW_OPS`` ops).
On window close the drive is scored against its erasure-set peers
(registered by ``ErasureObjects.__init__``): a drive whose EWMA exceeds
``OUTLIER_K`` x the peer median for ``SUSPECT_WINDOWS`` consecutive
windows becomes *suspect*; a drive with a sustained window error rate
becomes *faulty*. Transitions emit a console-log line, a span event on
the active trace (if any), and metrics-v2 gauges/counters.

Cost discipline: ``record()`` is one lock + a handful of dict/float
updates; metrics and peer scoring run only on window close (1/16 ops).
"""

from __future__ import annotations

import hashlib
import statistics
import threading
import time

from ..storage import errors as serr

OP_CLASSES = ("read", "write", "stat", "delete")

# Storage-op / RPC-method name -> coarse op class. Unknown ops score
# as "stat" (cheap metadata-ish work).
_OP_CLASS = {
    "read_all": "read", "read_file": "read", "read_version": "read",
    "read_versions": "read", "read_parts": "read", "list_dir": "read",
    "list_volumes": "read", "walk_dir": "read", "verify_file": "read",
    "write_all": "write", "append_file": "write", "create_file": "write",
    "link_file": "write", "rename_file": "write", "rename_data": "write",
    "write_metadata": "write", "make_volume": "write",
    "disk_info": "stat", "stat_volume": "stat",
    "delete": "delete", "delete_version": "delete",
    "delete_volume": "delete",
}


def op_class(op: str) -> str:
    return _OP_CLASS.get(op, "stat")


# Namespace misses are the data plane working as designed (idempotent
# deletes, probes of keys that do not exist, racing bucket deletes) —
# they must never count against a drive's health. The builtin ENOENT
# family covers ops whose miss surfaces before xl.py re-types it.
_BENIGN = (serr.FileNotFound, serr.VersionNotFound, serr.VolumeNotFound,
           serr.VolumeExists, FileNotFoundError, IsADirectoryError,
           NotADirectoryError, FileExistsError)

# Connectivity loss is the TRANSPORT's failure domain, not the drive's:
# DiskNotFound is what a peer's drives surface while the peer is
# offline (rpc/transport.py health gate). Counting it as drive-fault
# evidence would quarantine every drive of a rebooting node — and
# probation (bitrot shadow probes) would then hold its WRITES off for
# whole probe windows after the peer is already back, while the
# transport gate re-opens in seconds. Media evidence only.
_CONNECTIVITY = (serr.DiskNotFound,)


def is_drive_fault(exc) -> bool:
    """True when an exception (instance or type) is evidence of a bad
    drive rather than a namespace miss or a caller-side cancel."""
    if exc is None:
        return False
    if isinstance(exc, type):
        if issubclass(exc, _BENIGN + _CONNECTIVITY):
            return False
        return exc.__name__ != "DeadlineExceeded"
    if isinstance(exc, _BENIGN + _CONNECTIVITY):
        return False
    return type(exc).__name__ != "DeadlineExceeded"


OK, SUSPECT, FAULTY = "ok", "suspect", "faulty"
_STATE_VALUE = {OK: 0, SUSPECT: 1, FAULTY: 2}


def drive_key(disk) -> str:
    """Canonical health identity for a disk object (local XLStorage,
    RemoteStorage, or a duck-typed test double): the key every
    data-plane boundary records under and every health consumer —
    read selection, quarantine gates, config stores — queries by."""
    try:
        return disk.endpoint()
    except Exception:
        return str(disk)


class _Drive:
    __slots__ = ("endpoint", "set_id", "state", "ewma", "win_lat",
                 "win_ops", "win_errs", "hot_windows", "err_windows",
                 "ops_total", "errs_total", "windows", "changed_at",
                 "last_score", "mu", "quarantined", "probation_passes")

    def __init__(self, endpoint: str, set_id: int):
        # PER-DRIVE lock: the record() hot path runs inside quorum
        # fan-outs where k+m worker threads hit k+m DIFFERENT drives
        # simultaneously — one registry-wide lock there serializes the
        # whole fan-out (measured ~1ms/PUT on a 2-core gVisor box,
        # ~10x futex cost). Per-drive locks make concurrent records
        # contention-free; the registry lock guards only topology.
        self.mu = threading.Lock()
        self.endpoint = endpoint
        self.set_id = set_id
        self.state = OK
        self.ewma: dict[str, float] = {}
        self.win_lat: dict[str, list] = {}  # class -> [sum_ms, count]
        self.win_ops = 0
        self.win_errs = 0
        self.hot_windows = 0
        self.err_windows = 0
        self.ops_total = 0
        self.errs_total = 0
        self.windows = 0
        self.changed_at = 0.0
        self.last_score = 0.0
        # Quarantine lifecycle (set on entering FAULTY when
        # AUTO_QUARANTINE): the data plane excludes this drive from
        # read selection and write fan-out; window scoring freezes
        # until probation probes reinstate it.
        self.quarantined = False
        self.probation_passes = 0


class DriveMonitor:
    """Process-wide drive-health tracker (singleton ``DRIVEMON``)."""

    # Ops per evaluation window per drive.
    WINDOW_OPS = 16
    # Suspect when EWMA > OUTLIER_K x median of erasure-set peers...
    OUTLIER_K = 3.0
    # ...for this many CONSECUTIVE windows (absorbs one-off stalls).
    SUSPECT_WINDOWS = 2
    # Floor under the peer median: sub-ms jitter between healthy
    # drives must not create outliers (ratios explode near zero).
    MEDIAN_FLOOR_MS = 0.2
    # Absolute excess a drive must ALSO show over the peer median
    # before the ratio counts: on fast local disks (tmpfs, NVMe) the
    # healthy spread is fractions of a millisecond, where scheduler
    # jitter alone produces 3x ratios — a drive that is "3x slower"
    # by 0.4ms is not dragging any quorum tail.
    MIN_EXCESS_MS = 5.0
    # A suspect must DOMINATE its set: also this factor over the WORST
    # peer. The target failure mode is the single laggard drive
    # (arXiv:1709.05365); requiring dominance means host-wide
    # starvation (every drive slow at once) and scheduler bias against
    # one healthy drive — both of which drag the median/max together —
    # cannot co-flag bystanders while a genuinely slow drive exists.
    # Known tradeoff: two drives degraded to the SAME latency flag
    # neither; the error path and operator EWMAs still surface them.
    DOMINANCE = 1.5
    # Faulty when a window's error rate stays at/above this...
    ERROR_RATE = 0.5
    # ...for this many consecutive windows.
    FAULTY_WINDOWS = 2
    # EWMA weight of each new window mean.
    ALPHA = 0.3
    # Peers needed (with data for the op class) before outlier scoring
    # engages — a lone drive has no one to be an outlier against.
    MIN_PEERS = 2
    # Entering FAULTY auto-quarantines the drive: the data plane stops
    # reading from / writing to it (erasure/engine.py consults
    # is_quarantined), and only probation probes can bring it back.
    AUTO_QUARANTINE = True
    # Consecutive probation probe rounds (shadow read + bitrot verify,
    # erasure/heal.py QuarantineProber) that must pass before a
    # quarantined drive rejoins the read/write set.
    PROBATION_PASSES = 3

    def __init__(self):
        self.enabled = True
        self._mu = threading.Lock()
        self._drives: dict[str, _Drive] = {}
        self._set_members: dict[int, list[str]] = {}
        self._next_set = 0

    # -- topology ------------------------------------------------------

    def register_set(self, endpoints: list[str]) -> int:
        """Declare one erasure set's drives as peers of each other
        (called by ErasureObjects.__init__). Re-registering an endpoint
        moves it to the new set."""
        with self._mu:
            set_id = self._next_set
            self._next_set += 1
            self._set_members[set_id] = list(endpoints)
            for ep in endpoints:
                d = self._drives.get(ep)
                if d is None:
                    self._drives[ep] = _Drive(ep, set_id)
                else:
                    old = self._set_members.get(d.set_id)
                    if old is not None and ep in old:
                        old.remove(ep)
                    d.set_id = set_id
            return set_id

    # -- recording -----------------------------------------------------

    def record(self, endpoint: str, op: str, latency_ms: float,
               error: bool = False) -> None:
        """Account one disk op (local ``_DiskOp`` or remote RPC)."""
        if not self.enabled:
            return
        cls = op_class(op)
        # Dict read without the registry lock is GIL-atomic; only the
        # first-ever record of an unknown drive takes the slow path.
        d = self._drives.get(endpoint)
        if d is None:
            with self._mu:
                d = self._drives.get(endpoint)
                if d is None:
                    # Unregistered drive (no engine): singleton group.
                    set_id = self._next_set
                    self._next_set += 1
                    self._set_members[set_id] = [endpoint]
                    d = self._drives[endpoint] = _Drive(endpoint,
                                                        set_id)
        transition = None
        with d.mu:
            acc = d.win_lat.get(cls)
            if acc is None:
                acc = d.win_lat[cls] = [0.0, 0]
            acc[0] += latency_ms
            acc[1] += 1
            d.win_ops += 1
            d.ops_total += 1
            if error:
                d.win_errs += 1
                d.errs_total += 1
            if d.win_ops >= self.WINDOW_OPS:
                transition = self._close_window(d)
        if error:
            from .metrics2 import METRICS2
            # Metric labels use the redacted identity: the metrics
            # pages are unauthenticated, and absolute disk paths must
            # not leak there (admin /drive-health maps them back).
            METRICS2.inc("minio_tpu_v2_drive_op_errors_total",
                         {"disk": redacted_endpoint(endpoint),
                          "op_class": cls})
        if transition is not None:
            self._announce(*transition)

    # -- window evaluation (caller holds the DRIVE's lock; peer EWMA
    # reads cross drives without their locks — plain float/dict reads
    # are GIL-safe and monitoring tolerates a window of staleness) ----

    def _close_window(self, d: _Drive):
        d.windows += 1
        for cls, (s, c) in d.win_lat.items():
            if c:
                mean = s / c
                prev = d.ewma.get(cls)
                d.ewma[cls] = mean if prev is None else (
                    self.ALPHA * mean + (1 - self.ALPHA) * prev)
        err_rate = d.win_errs / max(1, d.win_ops)
        d.err_windows = d.err_windows + 1 \
            if err_rate >= self.ERROR_RATE else 0
        d.last_score = self._outlier_score(d)
        d.hot_windows = d.hot_windows + 1 \
            if d.last_score >= self.OUTLIER_K else 0
        d.win_lat = {}
        d.win_ops = 0
        d.win_errs = 0
        if d.quarantined:
            # Frozen: a quarantined drive sees only probe/heal traffic,
            # and a quiet window of THAT must not silently clear the
            # state — reinstatement is the probation prober's decision
            # (bitrot-verified shadow reads), never a scoring artifact.
            return None
        new_state = OK
        if d.err_windows >= self.FAULTY_WINDOWS:
            new_state = FAULTY
        elif d.hot_windows >= self.SUSPECT_WINDOWS:
            new_state = SUSPECT
        if new_state == FAULTY and self.AUTO_QUARANTINE:
            d.quarantined = True
            d.probation_passes = 0
        if new_state == d.state:
            return None
        old, d.state = d.state, new_state
        d.changed_at = time.time()
        return d.endpoint, old, new_state, round(d.last_score, 2)

    def _outlier_score(self, d: _Drive) -> float:
        """max over op classes of ewma / median(peer ewmas)."""
        peers = [self._drives[ep]
                 for ep in self._set_members.get(d.set_id, ())
                 if ep != d.endpoint and ep in self._drives]
        worst = 0.0
        for cls, mine in d.ewma.items():
            vals = [p.ewma[cls] for p in peers if cls in p.ewma]
            if len(vals) < self.MIN_PEERS:
                continue
            med = max(statistics.median(vals), self.MEDIAN_FLOOR_MS)
            if mine - med < self.MIN_EXCESS_MS:
                continue  # jitter-scale spread, not a dragging drive
            if mine < self.DOMINANCE * max(vals):
                continue  # not the set's laggard (see DOMINANCE)
            worst = max(worst, mine / med)
        return worst

    # -- transition fan-out (outside the lock) -------------------------

    def _announce(self, endpoint: str, old: str, new: str,
                  score: float) -> None:
        from ..logger import Logger
        from .metrics2 import METRICS2
        from .span import current_span
        quarantined = self.is_quarantined(endpoint)
        note = " [quarantined]" if quarantined else ""
        red = redacted_endpoint(endpoint)
        Logger.get().info(
            f"drivemon: {endpoint} {old} -> {new}{note} "
            f"(peer-relative score {score}x)", "drivemon",
            disk=red, state=new, quarantined=quarantined)
        METRICS2.set_gauge("minio_tpu_v2_drive_state",
                           {"disk": red}, _STATE_VALUE[new])
        METRICS2.inc("minio_tpu_v2_drive_state_transitions_total",
                     {"disk": red, "state": new})
        if quarantined and new == FAULTY:
            METRICS2.inc("minio_tpu_v2_drive_quarantines_total",
                         {"disk": red})
        for cls, v in self.ewma_for(endpoint).items():
            METRICS2.set_gauge("minio_tpu_v2_drive_op_latency_ewma_ms",
                               {"disk": red, "op_class": cls}, v)
        span = current_span()
        if span is not None:
            span.add_event("drive.state", disk=endpoint, state=new,
                           score=score, quarantined=quarantined)

    # -- quarantine / probation lifecycle ------------------------------

    def is_quarantined(self, endpoint: str) -> bool:
        """Lock-free hot-path check (GIL-atomic dict/attr reads); the
        read/write selection paths call this per drive per request."""
        d = self._drives.get(endpoint)
        return d is not None and d.quarantined

    def quarantined_endpoints(self) -> list[str]:
        with self._mu:
            return [ep for ep, d in sorted(self._drives.items())
                    if d.quarantined]

    def quarantine(self, endpoint: str, reason: str = "manual") -> None:
        """Force a drive into quarantine (the FAULTY auto-path runs
        through _close_window; this is the explicit entry for admin /
        test use)."""
        with self._mu:
            d = self._drives.get(endpoint)
            if d is None or d.quarantined:
                return
            old = d.state
            with d.mu:
                d.quarantined = True
                d.probation_passes = 0
                d.state = FAULTY
                d.changed_at = time.time()
        self._announce(endpoint, old, FAULTY, 0.0)

    def probation_pass(self, endpoint: str) -> bool:
        """One successful probation probe (shadow read passed bitrot
        verification). Returns True when the drive just crossed
        PROBATION_PASSES and was reinstated."""
        from .metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_drive_probation_probes_total",
                     {"result": "pass"})
        with self._mu:
            d = self._drives.get(endpoint)
            if d is None or not d.quarantined:
                return False
            d.probation_passes += 1
            if d.probation_passes < self.PROBATION_PASSES:
                return False
        self.reinstate(endpoint)
        return True

    def probation_fail(self, endpoint: str) -> None:
        """A probation probe failed: the streak restarts."""
        from .metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_drive_probation_probes_total",
                     {"result": "fail"})
        with self._mu:
            d = self._drives.get(endpoint)
            if d is not None:
                d.probation_passes = 0

    def reinstate(self, endpoint: str) -> None:
        """Probation passed: the drive rejoins the read/write set with
        a clean slate (EWMAs kept — they decay naturally; counters
        that drive state transitions reset so one old error window
        cannot instantly re-quarantine a healthy drive)."""
        with self._mu:
            d = self._drives.get(endpoint)
            if d is None or not d.quarantined:
                return
            old = d.state
            with d.mu:
                d.quarantined = False
                d.probation_passes = 0
                d.err_windows = 0
                d.hot_windows = 0
                d.win_lat = {}
                d.win_ops = 0
                d.win_errs = 0
                d.state = OK
                d.changed_at = time.time()
        self._announce(endpoint, old, OK, 0.0)

    # -- reads ---------------------------------------------------------

    def ewma_for(self, endpoint: str) -> dict[str, float]:
        with self._mu:
            d = self._drives.get(endpoint)
            return dict(d.ewma) if d is not None else {}

    def state_of(self, endpoint: str) -> str:
        with self._mu:
            d = self._drives.get(endpoint)
            return d.state if d is not None else OK

    def endpoints(self) -> list[str]:
        """Every registered drive endpoint (the hot-object cache maps
        its disk-tier dirs onto these by path prefix for
        health-informed placement)."""
        with self._mu:
            return list(self._drives)

    def counts(self) -> tuple[int, int]:
        """(suspect, faulty) drive counts."""
        with self._mu:
            s = sum(1 for d in self._drives.values()
                    if d.state == SUSPECT)
            f = sum(1 for d in self._drives.values()
                    if d.state == FAULTY)
            return s, f

    def snapshot(self) -> dict:
        """JSON-ready node view (the `/minio-tpu/v2/health/drives`
        payload; the cluster endpoint fan-in merges these)."""
        with self._mu:
            drives = []
            for ep, d in sorted(self._drives.items()):
                drives.append({
                    "endpoint": ep,
                    "set": d.set_id,
                    "state": d.state,
                    "quarantined": d.quarantined,
                    "probationPasses": d.probation_passes,
                    "opsTotal": d.ops_total,
                    "errsTotal": d.errs_total,
                    "windows": d.windows,
                    "hotWindows": d.hot_windows,
                    "errWindows": d.err_windows,
                    "score": round(d.last_score, 3),
                    "ewmaMs": {c: round(v, 3)
                               for c, v in sorted(d.ewma.items())},
                    "changedAt": d.changed_at,
                })
            suspect = sum(1 for x in drives if x["state"] == SUSPECT)
            faulty = sum(1 for x in drives if x["state"] == FAULTY)
            quarantined = sum(1 for x in drives if x["quarantined"])
        return {"drives": drives, "suspect": suspect, "faulty": faulty,
                "quarantined": quarantined}

    def reset(self) -> None:
        with self._mu:
            self._drives.clear()
            self._set_members.clear()
            self._next_set = 0


def redacted_endpoint(ep: str) -> str:
    """Short stable drive identity for UNAUTHENTICATED surfaces: the
    last two path components plus a digest prefix — enough to tell
    drives apart and correlate with the authenticated admin view,
    without disclosing absolute server filesystem paths or full
    internal topology to anonymous probes."""
    tail = "/".join(ep.replace("\\", "/").rstrip("/").split("/")[-2:])
    return f"{tail}#{hashlib.sha256(ep.encode()).hexdigest()[:8]}"


def redact_drives(doc: dict) -> dict:
    """Copy of a drivemon snapshot (or cluster merge) with every
    drive row's endpoint redacted (see redacted_endpoint)."""
    out = dict(doc)
    out["drives"] = [
        dict(d, endpoint=redacted_endpoint(str(d.get("endpoint", ""))))
        if isinstance(d, dict) else d
        for d in doc.get("drives", [])]
    return out


# The process-wide monitor every recording boundary shares.
DRIVEMON = DriveMonitor()
