"""Incident bundles: when an alert fires, freeze the evidence.

Every observability plane in this stack is a bounded RING — traces
(256), slowlog (128), timeline (15 min), console log — which is the
right cost discipline for steady state and exactly wrong for
diagnosis: by the time a human looks at a 3am page, the rings have
rotated the incident out.  This module closes that gap: the watchdog's
pending->firing transition calls :meth:`IncidentRecorder.capture`,
which snapshots everything a diagnosis needs INTO a bundle that
survives the rings' retention:

  - the surrounding timeline window (per-class rates, backend states,
    drive census, worst-request/kernel trace exemplars);
  - the matching slowlog entries (span trees stripped; blame + QoS
    data kept) plus the WORST request's full span tree;
  - the drive-health snapshot, MRF census, kernel backend states;
  - the active fault-injection plan (an injected incident says so);
  - the effective config (webhook/secret tokens redacted) and the
    full alert census at capture time.

Bundles live in a size-bounded ring (count- and byte-capped — an
incident storm must not become its own memory incident) and are
served by admin ``/incidents`` (root-only, so drive endpoints and
config stay un-redacted except for credentials): list for the index,
``?id=`` for one full JSON bundle.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

# Ring bounds: at most MAX_BUNDLES bundles, each at most MAX_BYTES of
# JSON (oversize bundles drop their heaviest sections, biggest first).
MAX_BUNDLES = 16
MAX_BYTES = 512 * 1024
TIMELINE_SAMPLES = 180
SLOWLOG_ENTRIES = 20


def _redact_config(doc: dict) -> dict:
    """Copy of a config dump with credential-bearing values masked
    (key name contains token/secret/password); the bundle must be
    shareable with a vendor/ticket without leaking webhook creds."""
    out: dict = {}
    for sub, targets in doc.items():
        out[sub] = {}
        for tgt, kvs in targets.items():
            out[sub][tgt] = {
                k: ("REDACTED" if v and any(
                    w in k for w in ("token", "secret", "password"))
                    else v)
                for k, v in kvs.items()}
    return out


class IncidentRecorder:
    """Process-wide bundle ring (singleton ``INCIDENTS``)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=MAX_BUNDLES)
        # Extra context sources the server wires in at start():
        #   "config" -> effective (already-redacted) config dump
        #   "mrf"    -> MRF heal-queue census
        self.providers: dict[str, object] = {}
        self.captured_total = 0

    # -- capture -------------------------------------------------------

    def capture(self, transition: dict) -> dict:
        """Freeze one bundle for a firing alert (called by the
        watchdog OUTSIDE its engine lock).  Collection is best-effort
        per section: one broken source costs its section, never the
        bundle."""
        bundle: dict = {
            "id": transition.get("alertId")
            or f"incident-{int(time.time() * 1000)}",
            "rule": transition.get("rule", ""),
            "cause": transition.get("cause", ""),
            "value": transition.get("value", 0.0),
            "capturedAt": time.time(),
        }

        def section(name: str, build) -> None:
            try:
                bundle[name] = build()
            except Exception as e:  # noqa: BLE001 - best-effort evidence
                bundle.setdefault("errors", {})[name] = repr(e)

        def timeline_window() -> dict:
            from .timeline import TIMELINE
            return {"periodS": TIMELINE.period_s,
                    "samples": TIMELINE.samples(n=TIMELINE_SAMPLES)}

        def slowlog_tail() -> list[dict]:
            # Span trees stripped here — the worst one rides whole in
            # its own section; 20 full trees would blow the byte cap.
            from .slowlog import SLOWLOG
            return [{k: v for k, v in e.items() if k != "spans"}
                    for e in SLOWLOG.entries(n=SLOWLOG_ENTRIES)]

        def worst_trace() -> dict | None:
            from .slowlog import SLOWLOG
            worst = None
            for e in SLOWLOG.entries(n=SLOWLOG_ENTRIES):
                if "spans" in e and (
                        worst is None
                        or e["durationMs"] > worst["durationMs"]):
                    worst = e
            if worst is None:
                return None
            return {"requestID": worst.get("requestID", ""),
                    "durationMs": worst.get("durationMs", 0),
                    "blamedLayer": worst.get("blamedLayer", ""),
                    "spans": worst["spans"]}

        def drive_census() -> dict:
            from .drivemon import DRIVEMON
            return DRIVEMON.snapshot()

        def backend_states() -> dict:
            from .kernprof import KERNPROF
            return KERNPROF.snapshot()

        def fault_plan() -> dict:
            from ..faultinject import FAULTS
            return FAULTS.snapshot()

        def alert_census() -> dict:
            from .watchdog import WATCHDOG
            return WATCHDOG.snapshot()

        def loop_census() -> dict:
            # Event-loop health at capture time: per-loop lag/census
            # plus the stall flight-recorder ring — for a loop_stall
            # firing this is the evidence (the frozen stack captures
            # naming the frame that held the loop).
            from .loopmon import LOOPMON
            return LOOPMON.snapshot()

        def usage_census() -> dict:
            # The attribution snapshot at capture time: WHO was the
            # traffic when the alert fired — the noisy_neighbor rule's
            # evidence, and the first question for any brownout.
            from .usage import USAGE
            return USAGE.snapshot()

        section("timeline", timeline_window)
        section("slowlog", slowlog_tail)
        section("worstTrace", worst_trace)
        section("drives", drive_census)
        section("kernelBackends", backend_states)
        section("faultPlan", fault_plan)
        section("alerts", alert_census)
        section("usage", usage_census)
        section("loops", loop_census)
        for name, provider in list(self.providers.items()):
            section(name, provider)
        if isinstance(bundle.get("config"), dict):
            # Defense in depth: the server's provider already redacts,
            # but a bundle must never ship credentials even if a
            # future provider forgets.
            try:
                bundle["config"] = _redact_config(bundle["config"])
            except Exception as e:  # noqa: BLE001 - never ship un-redacted
                del bundle["config"]
                bundle.setdefault("errors", {})["config"] = repr(e)
        bundle["bytes"] = self._bound(bundle)
        with self._mu:
            self._ring.append(bundle)
            self.captured_total += 1
        from .metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_incidents_total",
                     {"rule": bundle["rule"]})
        return bundle

    @staticmethod
    def _bound(bundle: dict) -> int:
        """Enforce the per-bundle byte cap by dropping the heaviest
        sections first, recording what was dropped — a truncated
        bundle must SAY it is truncated, not silently read complete.
        Returns the bundle's serialized size (stored so the index
        never re-serializes the ring to report byte counts)."""
        size = len(json.dumps(bundle, default=str))
        for drop in ("worstTrace", "slowlog", "timeline", "usage",
                     "loops", "config"):
            if size <= MAX_BYTES:
                return size
            if drop in bundle:
                del bundle[drop]
                bundle.setdefault("truncated", []).append(drop)
                size = len(json.dumps(bundle, default=str))
        if size > MAX_BYTES:
            # Still oversize with every droppable section gone (a
            # pathological drive/alert census): keep only the headline
            # — the cap is a MEMORY bound, not a suggestion.
            keep = ("id", "rule", "cause", "value", "capturedAt",
                    "truncated", "errors")
            extra = [k for k in bundle if k not in keep]
            for k in extra:
                del bundle[k]
            bundle.setdefault("truncated", []).extend(sorted(extra))
            size = len(json.dumps(bundle, default=str))
        return size

    # -- reads ---------------------------------------------------------

    def list(self) -> list[dict]:
        """Newest-last index of captured bundles (id + headline)."""
        with self._mu:
            items = list(self._ring)
        # ``bundleId`` duplicates ``id`` on purpose: it is the JOIN
        # KEY the watchdog webhook payloads carry, so an external
        # pager can match a notification to its bundle field-for-field.
        return [{"id": b["id"], "bundleId": b["id"], "rule": b["rule"],
                 "cause": b["cause"], "capturedAt": b["capturedAt"],
                 "bytes": b.get("bytes", 0)}
                for b in items]

    def get(self, incident_id: str) -> dict:
        with self._mu:
            for b in self._ring:
                if b["id"] == incident_id:
                    return b
        raise KeyError(incident_id)

    def reset(self) -> None:
        with self._mu:
            self._ring.clear()
            self.captured_total = 0


# The process-wide recorder the watchdog captures into.
INCIDENTS = IncidentRecorder()
