"""TPU data-plane kernel accounting: the whole point of this
reproduction is the TPU codec (PAPER.md), yet until metrics-v2 it
exported zero metrics. Every kernel entry point now records through
``KERNEL`` into the v2 registry:

- ``rs_encode``  — batched Reed-Solomon encode (ops/rs_tpu.encode_batch
  on device, ops/batching.host_encode* on the host)
- ``rs_decode``  — mask-grouped reconstruction (ops/batching)
- ``hh256``      — batched HighwayHash bitrot hashing (ops/hh256_tpu /
  the host chunk path in erasure/bitrot.py)

Per kernel x device the registry carries invocations, bytes, wall
seconds, batch-occupancy blocks and coalesced request counts; the
existing ops/batching.STATS honesty counters stay untouched (they feed
the v1 page), metrics-v2 is the superset the next perf PR reads.
"""

from __future__ import annotations

import time

from .metrics2 import METRICS2

RS_ENCODE = "rs_encode"
RS_DECODE = "rs_decode"
HH256 = "hh256"
# Columnar S3 Select predicate scan (ops/select_kernels.py): the
# analytics workload's kernel identity in the dispatch profiles, the
# autotuner model and the backend health machine.
SELECT_SCAN = "select_scan"


class KernelStats:
    """Recording facade over the v2 registry's kernel counters.

    ``backend`` refines the binary device flag into the real dispatch
    lane (obs/kernprof.py BACKENDS: device / native / xla-cpu / host);
    every record also feeds the kernprof per-dispatch profile layer —
    latency histogram per (kernel, backend, batch bucket), per-backend
    byte counters, and the backend health state machine's success
    outcomes.  Callers that don't know their lane omit it and the
    coarse device flag maps to device/host."""

    @staticmethod
    def record(kernel: str, device: bool, nbytes: int,
               wall_s: float = 0.0, blocks: int = 0,
               requests: int = 1, backend: str | None = None) -> None:
        lbl = {"kernel": kernel, "device": "tpu" if device else "host"}
        METRICS2.inc("minio_tpu_v2_kernel_invocations_total", lbl)
        METRICS2.inc("minio_tpu_v2_kernel_bytes_total", lbl, nbytes)
        if wall_s:
            METRICS2.inc("minio_tpu_v2_kernel_wall_seconds_total", lbl,
                         wall_s)
        if blocks:
            METRICS2.inc("minio_tpu_v2_kernel_batch_blocks_total", lbl,
                         blocks)
        if requests > 1:
            METRICS2.inc("minio_tpu_v2_kernel_coalesced_requests_total",
                         lbl, requests)
        from .kernprof import DEVICE, HOST, KERNPROF
        if backend is None:
            backend = DEVICE if device else HOST
        KERNPROF.record_dispatch(kernel, backend, nbytes, wall_s,
                                 blocks)

    @staticmethod
    def record_coalesced(kernel: str, requests: int) -> None:
        METRICS2.inc("minio_tpu_v2_kernel_coalesced_requests_total",
                     {"kernel": kernel, "device": "tpu"}, requests)

    @staticmethod
    def snapshot() -> dict:
        """{kernel/device: {invocations, bytes, wall_seconds, blocks}}
        — the admin-info / test view of the registry's kernel series."""
        out: dict[str, dict] = {}
        snap = METRICS2.snapshot()
        for metric, field in (
                ("minio_tpu_v2_kernel_invocations_total", "invocations"),
                ("minio_tpu_v2_kernel_bytes_total", "bytes"),
                ("minio_tpu_v2_kernel_wall_seconds_total",
                 "wall_seconds"),
                ("minio_tpu_v2_kernel_batch_blocks_total", "blocks")):
            for s in snap.get(metric, {}).get("series", []):
                lb = s["labels"]
                key = f"{lb.get('kernel')}/{lb.get('device')}"
                out.setdefault(key, {})[field] = s["value"]
        return out


KERNEL = KernelStats()


class timed:
    """``with timed() as t: ...; t.s`` — wall-clock for kernel calls."""

    __slots__ = ("t0", "s")

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.s = time.perf_counter() - self.t0
        return False
