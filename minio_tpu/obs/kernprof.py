"""Kernel-dispatch profiling + per-backend dispatch health.

The TPU data plane is the whole point of this reproduction, yet until
this module kernel dispatch was its least observable layer: a binary
``device=tpu|host`` metric label and a once-per-process fallback
warning (``ops/batching._warned_fallback``).  That is exactly how the
bench trajectory silently collapsed from device runs to host-mode
stand-ins between r03 and r04 with no artifact saying so (ROADMAP
"Bench caveat").  This module is the dispatch-path brain-scan:

- **Per-dispatch profiles**: every ``KernelStats.record`` feeds a
  latency histogram keyed (kernel, backend, batch-size bucket) plus a
  per-backend byte counter — the numerator of the per-backend GiB/s
  series the timeline (obs/timeline.py) deltas each second.

- **A dispatch health state machine per backend** — ``device`` (real
  accelerator), ``native`` (C++ host kernels), ``xla-cpu`` (jit on the
  CPU platform) and ``host`` (pure numpy/python) — each tracked
  UP -> DEGRADED -> DOWN from REAL dispatch outcomes plus a cheap
  periodic probe.  Every transition emits a console line (with the
  failure cause — replacing the once-per-process warning that never
  logged a second distinct cause), a ``kernel.backend`` span event on
  the active trace, and the ``minio_tpu_v2_kernel_backend_state``
  gauge.  A DOWN backend is skipped by dispatch policy
  (``allow()``) and re-probed on an interval, so a bounced TPU relay
  is re-adopted without a process restart.

- **Coalescer queue-wait vs execute split**: ops/batching.py's
  EncodeCoalescer reports how long each request waited in the window
  (``record_queue_wait``) separately from the device-execute wall the
  dispatch histogram carries.

Cost discipline: ``record_dispatch`` runs once per KERNEL DISPATCH
(already coalesced/batched), not per request — a handful of dict
updates under one lock plus two registry recordings.
"""

from __future__ import annotations

import threading
import time

# Dispatch backends, most- to least-preferred. "device" is a real
# accelerator behind the relay; "native" the C++ host kernels
# (minio_tpu/native); "xla-cpu" the jit bit-plane path on the CPU
# platform (what a backend="tpu" pin runs when no device answers);
# "host" the pure numpy/python floor that can never go away.
DEVICE = "device"
NATIVE = "native"
XLA_CPU = "xla-cpu"
HOST = "host"
BACKENDS = (DEVICE, NATIVE, XLA_CPU, HOST)

UP, DEGRADED, DOWN = "up", "degraded", "down"
_STATE_VALUE = {UP: 0, DEGRADED: 1, DOWN: 2}

# Batch-occupancy buckets for the dispatch histogram label: block
# counts collapse to few series, not one per batch size.
_BATCH_BUCKETS = ((1, "1"), (4, "2-4"), (16, "5-16"), (64, "17-64"))


def batch_bucket(blocks: int) -> str:
    for ub, name in _BATCH_BUCKETS:
        if blocks <= ub:
            return name
    return "65+"


class _Backend:
    __slots__ = ("name", "state", "fail_streak", "ok_streak",
                 "dispatches", "bytes", "failures", "last_error",
                 "changed_at", "last_probe")

    def __init__(self, name: str):
        self.name = name
        self.state = UP  # optimistic until an outcome/probe says else
        self.fail_streak = 0
        self.ok_streak = 0
        self.dispatches = 0
        self.bytes = 0
        self.failures = 0
        self.last_error = ""
        self.changed_at = 0.0
        self.last_probe = 0.0


class KernelProfiler:
    """Process-wide dispatch profiler + backend health (``KERNPROF``)."""

    # First failure degrades; this many CONSECUTIVE failures take the
    # backend DOWN (dispatch policy skips it; only probes touch it).
    DOWN_AFTER = 3
    # Consecutive successes that clear DEGRADED back to UP (one lucky
    # dispatch amid a flapping relay must not flap the state/logs).
    RECOVER_OK = 4
    # Seconds between recovery probes of a DOWN backend.
    PROBE_INTERVAL_S = 30.0

    def __init__(self):
        self.enabled = True
        self._mu = threading.Lock()
        self._backends = {b: _Backend(b) for b in BACKENDS}
        # Transitions decided under _mu queue here and publish in FIFO
        # order under _announce_mu — two threads transitioning
        # back-to-back (sampler probe vs. dispatch failure) must not
        # publish the gauge/log/span sinks in swapped order, or the
        # gauge sticks at the older state forever.
        self._pending: list[tuple] = []
        self._announce_mu = threading.Lock()

    # -- per-dispatch profile -----------------------------------------

    def record_dispatch(self, kernel: str, backend: str, nbytes: int,
                        wall_s: float, blocks: int = 0) -> None:
        """One successful kernel dispatch (called under
        ``KernelStats.record``)."""
        if not self.enabled:
            return
        b = self._backends.get(backend)
        if b is None:
            return
        transition = None
        with self._mu:
            b.dispatches += 1
            b.bytes += nbytes
            b.fail_streak = 0
            b.ok_streak += 1
            if b.state != UP and b.ok_streak >= self.RECOVER_OK:
                # DEGRADED recovers on a success streak; DOWN normally
                # recovers via probe, but a pinned backend bypasses
                # the gate — real successes flowing through it must
                # not leave the state reported down.
                transition = self._set_state(b, UP, "recovered")
        from .metrics2 import METRICS2
        METRICS2.observe(
            "minio_tpu_v2_kernel_dispatch_ms",
            {"kernel": kernel, "backend": backend,
             "batch": batch_bucket(max(1, blocks))}, wall_s * 1e3)
        METRICS2.inc("minio_tpu_v2_kernel_backend_bytes_total",
                     {"kernel": kernel, "backend": backend}, nbytes)
        if transition is not None:
            self._flush_announcements()
        # Live sample for the codec dispatch planner: the per-dispatch
        # profile layer is exactly what a probe-and-pick autotuner
        # reads (ops/autotune.py refines its throughput model from
        # every real dispatch).
        from ..ops.autotune import AUTOTUNE
        AUTOTUNE.observe(kernel, backend, nbytes, wall_s)
        # Worst-dispatch exemplar for the current timeline window.
        from .timeline import TIMELINE
        TIMELINE.note_kernel(kernel, backend, wall_s * 1e3)

    def record_queue_wait(self, kernel: str, wait_ms: float) -> None:
        """Coalescer window wait for one request — the queue half of
        the queue-wait vs device-execute split."""
        if not self.enabled:
            return
        from .metrics2 import METRICS2
        METRICS2.observe("minio_tpu_v2_kernel_queue_wait_ms",
                         {"kernel": kernel}, wait_ms)

    # -- dispatch outcomes --------------------------------------------

    def dispatch_failed(self, backend: str,
                        exc: BaseException | str) -> None:
        """A real dispatch on `backend` raised.  Replaces
        ``ops/batching._warn_device_fallback``: the cause is logged on
        every STATE TRANSITION (not once per process), so a second
        distinct failure mode — or a failure after a recovery — is
        never swallowed."""
        b = self._backends.get(backend)
        if b is None:
            return
        cause = exc if isinstance(exc, str) else repr(exc)
        with self._mu:
            b.failures += 1
            b.fail_streak += 1
            b.ok_streak = 0
            b.last_error = cause
            if b.fail_streak >= self.DOWN_AFTER:
                self._set_state(b, DOWN, cause)
            elif b.state == UP:
                self._set_state(b, DEGRADED, cause)
        # Unconditional: even when another thread's concurrent outcome
        # won the transition, returning only after the queue drains
        # means callers observe sinks caught up to the state they just
        # fed (flush blocks on _announce_mu until in-flight publishes
        # finish).
        self._flush_announcements()

    def allow(self, backend: str) -> bool:
        """Dispatch-policy gate: False only when the backend is DOWN
        (recovery is the probe's job — real traffic stops paying the
        failure latency).  Lock-free attr read on the hot path."""
        b = self._backends.get(backend)
        return b is None or b.state != DOWN

    def state_of(self, backend: str) -> str:
        b = self._backends.get(backend)
        return b.state if b is not None else UP

    # -- state machine internals (caller holds self._mu) ---------------

    def _set_state(self, b: _Backend, new: str, cause: str):
        if b.state == new:
            return None
        old, b.state = b.state, new
        b.changed_at = time.time()
        if new == UP:
            b.fail_streak = 0
        b.ok_streak = 0
        self._pending.append((b.name, old, new, cause))
        return b.name, old, new, cause

    # -- transition fan-out (outside the state lock) -------------------

    def _flush_announcements(self) -> None:
        """Publish queued transitions in the order they were decided.
        Holding _announce_mu across the drain keeps sink order equal
        to transition order even when the flusher is not the thread
        that decided the transition (it then also carries that
        transition's span event, which is the lesser evil: a swapped
        publish leaves the state gauge wrong until the NEXT
        transition)."""
        with self._announce_mu:
            while True:
                with self._mu:
                    if not self._pending:
                        return
                    item = self._pending.pop(0)
                self._announce(*item)

    def _announce(self, backend: str, old: str, new: str,
                  cause: str) -> None:
        from ..logger import Logger
        from .metrics2 import METRICS2
        from .span import current_span
        Logger.get().info(
            f"kernprof: backend {backend} {old} -> {new} ({cause})",
            "kernprof", backend=backend, state=new)
        METRICS2.set_gauge("minio_tpu_v2_kernel_backend_state",
                           {"backend": backend}, _STATE_VALUE[new])
        METRICS2.inc("minio_tpu_v2_kernel_backend_transitions_total",
                     {"backend": backend, "state": new})
        span = current_span()
        if span is not None:
            span.add_event("kernel.backend", backend=backend,
                           old=old, new=new, cause=cause[:256])

    # -- recovery probes -----------------------------------------------

    def maybe_probe(self, now: float | None = None) -> None:
        """Rate-limited recovery probing of DOWN backends (driven by
        the timeline sampler tick; tests call ``probe()`` directly).
        A probe is a tiny real dispatch on that backend — it goes
        through the same fault-injection hook as serving dispatch, so
        an active `kernel` fault plan keeps a probed backend down."""
        now = time.monotonic() if now is None else now
        due = []
        with self._mu:
            for b in self._backends.values():
                if b.state == DOWN and \
                        now - b.last_probe >= self.PROBE_INTERVAL_S:
                    b.last_probe = now
                    due.append(b.name)
        for name in due:
            self.probe(name)

    def probe(self, backend: str) -> bool:
        """One recovery probe; success re-adopts the backend (-> UP)."""
        from .metrics2 import METRICS2
        b = self._backends.get(backend)
        failures_before = b.failures if b is not None else 0
        try:
            ok = _probe_backend(backend)
            err = "" if ok else "probe declined"
        except BaseException as exc:  # noqa: BLE001 - probe must not raise
            ok, err = False, repr(exc)
        METRICS2.inc("minio_tpu_v2_kernel_backend_probes_total",
                     {"backend": backend,
                      "result": "pass" if ok else "fail"})
        if b is None:
            return ok
        if not ok:
            # A probe IS a real dispatch on that backend — its failure
            # is state-machine evidence like any serving dispatch (an
            # explicit probe of an UP backend under an active fault
            # must degrade it, not just note an error string).  But a
            # native probe that failed INSIDE _disable_native already
            # fed dispatch_failed — feeding again would double the
            # fail streak and take native DOWN in 2 probes where every
            # other lane needs 3.
            if b.failures == failures_before:
                self.dispatch_failed(backend, err or "probe failed")
            return False
        with self._mu:
            b.fail_streak = 0
            self._set_state(b, UP, "probe passed")
        # Unconditional (see dispatch_failed): a concurrent probe may
        # have won the UP transition — this probe still returns only
        # once the sinks reflect it.
        self._flush_announcements()
        return ok

    def probe_all(self) -> dict[str, bool]:
        """One probe per backend — the admin /kernel-health?probe=true
        census (boot stays cheap: states are evidence-based, so a
        backend with zero dispatches reads as nominally up/unproven
        until outcomes or an explicit probe say otherwise)."""
        return {name: self.probe(name) for name in BACKENDS}

    # -- views ---------------------------------------------------------

    def mix_snapshot(self) -> dict[str, dict]:
        """Cumulative per-backend dispatch/byte counters — bench.py
        deltas these around each config so every BENCH_*.json records
        which backend actually did the math."""
        with self._mu:
            return {b.name: {"dispatches": b.dispatches,
                             "bytes": b.bytes,
                             "failures": b.failures}
                    for b in self._backends.values()}

    def snapshot(self) -> dict:
        """JSON-ready health view (admin /kernel-health)."""
        with self._mu:
            backends = {}
            for b in self._backends.values():
                backends[b.name] = {
                    "state": b.state,
                    "dispatches": b.dispatches,
                    "bytes": b.bytes,
                    "failures": b.failures,
                    "failStreak": b.fail_streak,
                    "lastError": b.last_error,
                    "changedAt": b.changed_at,
                }
            return {"backends": backends}

    def states(self) -> dict[str, int]:
        """{backend: 0|1|2} — the timeline's per-sample state series."""
        with self._mu:
            return {b.name: _STATE_VALUE[b.state]
                    for b in self._backends.values()}

    def reset(self) -> None:
        with self._mu:
            self._backends = {b: _Backend(b) for b in BACKENDS}
            self._pending.clear()


def _probe_backend(backend: str) -> bool:
    """A tiny real dispatch on one backend.  Byte-correctness is the
    pass criterion — a backend that answers garbage is as down as one
    that raises.  Each probe consults the fault-injection `kernel`
    hook, so injected dispatch faults hold their backend down exactly
    like PR-6 probation holds an actively-faulty drive."""
    import numpy as np

    from ..faultinject import FAULTS
    from ..ops.gf256 import gf_mat_vec_apply
    data = np.arange(2 * 64, dtype=np.uint8).reshape(2, 64)
    if backend == HOST:
        FAULTS.kernel("rs_encode")
        want = gf_mat_vec_apply(np.eye(2, dtype=np.uint8), data)
        return bool((want == data).all())
    if backend == NATIVE:
        FAULTS.kernel("rs_encode")
        from .. import native
        return native.probe()
    if backend == XLA_CPU:
        FAULTS.kernel("rs_encode")
        import jax.numpy as jnp

        from ..ops import rs_tpu
        from ..ops.gf256 import gf_matrix_to_bitplane
        bm = gf_matrix_to_bitplane(
            np.eye(2, dtype=np.uint8)).astype(np.float32)
        out = np.asarray(rs_tpu._gf_apply_xla(jnp.asarray(bm),
                                              jnp.asarray(data)))
        return bool((out == data).all())
    if backend == DEVICE:
        FAULTS.kernel("rs_encode")
        from ..ops import batching, rs_tpu
        # Fresh device census: a bounced relay re-appearing is exactly
        # what this probe exists to notice, so the cached boot-time
        # answer is re-evaluated here (and only here).
        if not batching.reprobe_device_present():
            return False
        out = rs_tpu.encode_batch(data[None, :, :], 2, 1)
        from ..ops.rs_matrix import parity_matrix
        want = gf_mat_vec_apply(parity_matrix(2, 1), data)
        return bool((out[0, :2] == data).all()
                    and (out[0, 2:] == want).all())
    return False


# The process-wide profiler every dispatch boundary shares.
KERNPROF = KernelProfiler()
