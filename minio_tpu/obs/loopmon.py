"""Event-loop health plane: per-loop lag telemetry, a stall flight
recorder, and an always-on continuous profiler.

PR 18 made the process event-loop-centric — one RPC loop carries every
peer call (rpc/aio.py) and N front-door loops carry every connection
(s3/asyncserver.py) — so a single blocked callback is a cluster-wide
stall, yet the only defense was the STATIC lint rule R8 ("no blocking
calls in async bodies").  This module is R8's runtime twin (the repo
pattern set by PR 5's locktrace for the lock rules):

- **Heartbeat** (``LoopMonitor.register``): every event loop runs a
  10Hz heartbeat coroutine measuring scheduling lag — expected vs
  actual wake of ``asyncio.sleep`` — into an EWMA + rolling-window
  p99 and the ``minio_tpu_v2_loop_lag_ms{loop}`` histogram, plus a
  per-loop census (pending tasks, ready callbacks, open transports).
  The timeline samples the census per tick (``loopLag``/``loopTasks``
  rows) and ``tools/mtpu_top.py`` renders a ``loops:`` row.

- **Stall flight recorder**: a watcher thread notices a heartbeat
  overdue by more than ``obs.loop_stall_ms`` (config-KV, default
  250ms) and snapshots the loop thread's stack via
  ``sys._current_frames()`` into a bounded ring — one capture per
  stall episode, taken WHILE the loop is blocked, so the top frame is
  the blamed code.  Each capture emits a cause-carrying console line
  and a ``loop.stall`` span event; the watchdog built-in rule
  ``loop_stall`` (obs/watchdog.py) fires on recent captures with the
  usual pending/resolve hysteresis and freezes the ring into the
  incident bundle (obs/incidents.py ``loops`` section).

- **Continuous profiler**: the SamplingProfiler's frame walk
  (utils/profiler.py ``sample_stacks``) run at ~1% duty cycle
  (one all-thread sample per 100ms) forever, aggregated into
  per-minute self-time + folded-stack profiles served at admin
  ``/profile`` — so a stall incident links lag -> blamed frame ->
  where the process actually spends time, without anyone having
  started a profiling session first.  Config-KV
  ``obs.profile_continuous`` (default on) toggles it live.

Testability rides the fault plane: ``faultinject`` grows a
``loop_block`` rule kind whose latency the heartbeat schedules as a
REAL blocking ``time.sleep`` callback onto its own loop
(``_injected_loop_block`` below), so the detect -> blame -> fire ->
resolve chain is provable end-to-end against a live server.
"""

from __future__ import annotations

import asyncio
import atexit
import sys
import threading
import time
from collections import Counter, deque

HEARTBEAT_S = 0.1          # 10Hz: lag resolution vs overhead balance
EWMA_ALPHA = 0.2
LAG_WINDOW = 300           # rolling p99 window (~30s at 10Hz)
STALL_RING = 32            # stall captures kept (newest wins)
STALL_STACK_DEPTH = 48     # frames kept per capture
WATCH_PERIOD_S = 0.05      # watcher poll; bounds blame latency
# How long a stall capture keeps the watchdog rule breaching: long
# enough to cross pending_ticks hysteresis on 1s sampler ticks even
# for a ONE-SHOT 400ms block, short enough to resolve promptly.
RECENT_STALL_S = 10.0


def _injected_loop_block(seconds: float) -> None:
    """Deliberate loop blocker (faultinject ``loop_block``): scheduled
    via ``call_soon`` so it runs ON the monitored loop — the stall
    recorder must catch exactly this frame."""
    time.sleep(seconds)  # mtpu-lint: disable=R11 -- faultinject loop_block: blocking ON the loop is this function's entire purpose (the stall recorder must blame this frame)


class _LoopState:
    __slots__ = ("name", "loop", "thread_ident", "active", "task",
                 "beats", "last_beat", "last_ms", "ewma_ms", "lags",
                 "pending", "ready", "transports", "stalls",
                 "stalled_at")

    def __init__(self, name: str, loop):
        self.name = name
        self.loop = loop
        self.thread_ident: int | None = None  # learned on first beat
        self.active = True
        self.task = None
        self.beats = 0
        self.last_beat = 0.0      # monotonic of the latest beat
        self.last_ms = 0.0
        self.ewma_ms = 0.0
        self.lags: deque = deque(maxlen=LAG_WINDOW)
        self.pending = 0          # tasks on the loop
        self.ready = 0            # ready callbacks queued
        self.transports = 0       # selector-registered fds
        self.stalls = 0
        self.stalled_at = 0.0     # monotonic; nonzero = episode open


class ContinuousProfiler:
    """Low-duty-cycle whole-process sampler: ONE ``sample_stacks``
    walk per ``PERIOD_S`` (~1% duty at typical stack depths),
    aggregated into per-minute profiles — self-time by frame plus
    folded stacks ("f1;f2;f3 N", the flamegraph input format)."""

    PERIOD_S = 0.1
    MINUTES_KEPT = 15

    def __init__(self):
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Closed per-minute profiles, oldest first; the open minute
        # rides separately so report() always has fresh data.
        self._minutes: deque = deque(maxlen=self.MINUTES_KEPT)
        self._cur: dict | None = None
        self.samples_total = 0

    @property
    def running(self) -> bool:
        return self._thread is not None

    def start(self) -> None:
        with self._mu:
            if self._thread is not None:
                return
            self._stop.clear()
            # mtpu-lint: disable=R1 -- always-on profiling daemon observes ALL threads for the process lifetime
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="loopmon-profiler")
            self._thread.start()

    def stop(self) -> None:
        with self._mu:
            t = self._thread
            self._thread = None
        if t is not None:
            self._stop.set()
            t.join(timeout=2)

    def _run(self) -> None:
        from ..utils.profiler import sample_stacks
        me = frozenset((threading.get_ident(),))
        while not self._stop.wait(self.PERIOD_S):
            stacks = sample_stacks(skip=me)
            now = time.time()
            with self._mu:
                cur = self._cur
                if cur is None or now - cur["start"] >= 60.0:
                    if cur is not None and cur["samples"]:
                        self._minutes.append(cur)
                    cur = self._cur = {"start": now, "samples": 0,
                                       "leaf": Counter(),
                                       "folded": Counter()}
                cur["samples"] += 1
                self.samples_total += 1
                for stack in stacks:
                    if not stack:
                        continue
                    cur["leaf"][stack[0]] += 1
                    # Folded key is root-first (flamegraph order),
                    # bounded so one recursive stack can't bloat it.
                    cur["folded"][tuple(
                        reversed(stack[:STALL_STACK_DEPTH]))] += 1
            from .metrics2 import METRICS2
            METRICS2.inc("minio_tpu_v2_profile_samples_total", {},
                         len(stacks))

    def _merged(self, minutes: int) -> tuple[Counter, Counter, int]:
        with self._mu:
            closed = list(self._minutes)[-max(0, minutes - 1):] \
                if minutes > 1 else []
            if self._cur is not None:
                closed = closed + [self._cur]
            leaf: Counter = Counter()
            folded: Counter = Counter()
            samples = 0
            for m in closed:
                leaf.update(m["leaf"])
                folded.update(m["folded"])
                samples += m["samples"]
            return leaf, folded, samples

    def report(self, top: int = 50, minutes: int = 5) -> dict:
        """Top-N self-time rows + folded-stack text over the last
        ``minutes`` (open minute included) — the admin ``/profile``
        payload."""
        from ..utils.profiler import frame_label
        leaf, folded, samples = self._merged(minutes)
        total = max(1, samples)
        rows = [{"function": frame_label(key), "samples": n,
                 "pct": round(100.0 * n / total, 1)}
                for key, n in leaf.most_common(top)]
        folded_lines = [
            ";".join(f"{name} {file.rsplit('/', 1)[-1]}:{line}"
                     for file, line, name in stack) + f" {n}"
            for stack, n in folded.most_common(1000)]
        return {"running": self.running, "samples": samples,
                "minutes": minutes,
                "periodMs": self.PERIOD_S * 1000.0,
                "self": rows, "folded": folded_lines}


class LoopMonitor:
    """Process-wide registry of monitored event loops (singleton
    ``LOOPMON``); owns the heartbeats, the stall watcher thread and
    the continuous profiler."""

    def __init__(self):
        self._mu = threading.Lock()
        self._loops: dict[str, _LoopState] = {}
        self.enabled = True
        self.stall_ms = 250.0
        self.profiler = ContinuousProfiler()
        self._watcher: threading.Thread | None = None
        self._watch_stop = threading.Event()
        # Stall flight-recorder ring: newest-last capture dicts.
        self._stall_ring: deque = deque(maxlen=STALL_RING)
        # Process-lifetime loops (the RPC loop) never unregister on
        # their own; cancel their heartbeats before the interpreter
        # tears daemon threads down or every exit prints "Task was
        # destroyed but it is pending!".
        atexit.register(self._shutdown)

    def _shutdown(self) -> None:
        for name in list(self._loops):
            self.unregister(name, wait_s=0.2)
        self._watch_stop.set()
        self.profiler.stop()

    # -- configuration (config-KV ``obs`` apply hook) -------------------

    def configure(self, stall_ms: float | None = None,
                  profile_continuous: bool | None = None) -> None:
        if stall_ms is not None:
            if stall_ms <= 0:
                raise ValueError("loop_stall_ms must be positive")
            self.stall_ms = float(stall_ms)
        if profile_continuous is not None:
            if profile_continuous:
                self.profiler.start()
            else:
                self.profiler.stop()

    def set_enabled(self, flag: bool) -> None:
        """Pause/resume the whole plane (paired-overhead benches):
        heartbeats keep ticking but record nothing, the watcher skips,
        and the profiler stops."""
        self.enabled = bool(flag)
        if not flag:
            self.profiler.stop()

    # -- loop registration ----------------------------------------------

    def register(self, name: str, loop) -> None:
        """Idempotent: arm a heartbeat on ``loop`` under ``name``.
        Safe from any thread (the heartbeat task is created on the
        loop itself via call_soon_threadsafe)."""
        if loop is None:
            return
        with self._mu:
            old = self._loops.get(name)
            if old is not None and old.loop is loop and old.active:
                return
            st = _LoopState(name, loop)
            self._loops[name] = st
            self._ensure_watcher()
        if old is not None:
            # Name collision (e.g. two in-process test servers both
            # calling their first loop "s3-0"): latest wins, but the
            # displaced heartbeat must die or it leaks as a
            # destroyed-pending task when ITS loop stops.
            self._cancel_heartbeat(old, wait_s=0.0)

        def _arm() -> None:
            if st.active:
                st.task = loop.create_task(self._heartbeat(st))
        try:
            loop.call_soon_threadsafe(_arm)
        except RuntimeError:
            # Loop already closed between register and arm: forget it.
            with self._mu:
                if self._loops.get(name) is st:
                    del self._loops[name]

    def unregister(self, name: str, wait_s: float = 0.5) -> None:
        with self._mu:
            st = self._loops.pop(name, None)
        if st is not None:
            self._cancel_heartbeat(st, wait_s)

    @staticmethod
    def _cancel_heartbeat(st: _LoopState, wait_s: float) -> None:
        st.active = False
        task = st.task
        if task is None:
            return
        done = threading.Event()

        def _cancel() -> None:
            task.cancel()
            # cancel() schedules the task's final step; a chained
            # call_soon lands AFTER it, so done means DONE — callers
            # about to stop the loop won't destroy a pending task.
            st.loop.call_soon(done.set)
        try:
            st.loop.call_soon_threadsafe(_cancel)
        except RuntimeError:
            return  # loop already closed; task died with it
        if wait_s > 0 and threading.get_ident() != st.thread_ident:
            done.wait(wait_s)

    def _ensure_watcher(self) -> None:
        # Caller holds self._mu.
        if self._watcher is not None:
            return
        # mtpu-lint: disable=R1 -- stall watcher daemon observes every registered loop for the process lifetime
        self._watcher = threading.Thread(
            target=self._watch, daemon=True, name="loopmon-watcher")
        self._watcher.start()

    # -- heartbeat (runs ON the monitored loop) -------------------------

    async def _heartbeat(self, st: _LoopState) -> None:
        st.thread_ident = threading.get_ident()
        # Arm counts as a beat: a block landing BEFORE the first real
        # beat (boot-time CPU storms delay it by seconds) must still
        # be capturable, not skipped as "never alive".
        st.last_beat = time.monotonic()
        try:
            while st.active:
                before = time.monotonic()
                await asyncio.sleep(HEARTBEAT_S)
                if not self.enabled:
                    st.last_beat = time.monotonic()
                    continue
                # Fault plane: a `loop_block` rule for this loop turns
                # into a REAL blocking callback on this very loop —
                # scheduled, not inlined, so the stall capture blames
                # _injected_loop_block, not the heartbeat.
                try:
                    from ..faultinject import FAULTS
                    blk = FAULTS.loop_block(st.name)
                except Exception:  # noqa: BLE001 - fault plane optional
                    blk = 0.0
                if blk > 0:
                    st.loop.call_soon(_injected_loop_block, blk)
                now = time.monotonic()
                lag_ms = max(0.0, (now - before - HEARTBEAT_S) * 1e3)
                self._record(st, lag_ms, now)
        except asyncio.CancelledError:
            pass

    def _record(self, st: _LoopState, lag_ms: float,
                now_mono: float) -> None:
        st.last_beat = now_mono
        st.beats += 1
        st.last_ms = lag_ms
        st.ewma_ms = lag_ms if st.beats == 1 else (
            EWMA_ALPHA * lag_ms + (1.0 - EWMA_ALPHA) * st.ewma_ms)
        st.lags.append(lag_ms)
        if st.stalled_at:
            st.stalled_at = 0.0  # episode over; next one recaptures
        # Census from INSIDE the loop (all_tasks is loop-thread-only
        # reliable; _ready/_selector are CPython internals, guarded).
        try:
            st.pending = len(asyncio.all_tasks(st.loop))
        except RuntimeError:
            pass
        q = getattr(st.loop, "_ready", None)
        if q is not None:
            st.ready = len(q)
        sel = getattr(st.loop, "_selector", None)
        if sel is not None:
            try:
                st.transports = len(sel.get_map())
            except (RuntimeError, AttributeError):
                pass
        from .metrics2 import METRICS2
        METRICS2.observe("minio_tpu_v2_loop_lag_ms",
                         {"loop": st.name}, lag_ms)
        # Gauges refresh at 1Hz, not per beat — they are levels.
        if st.beats % 10 == 1:
            METRICS2.set_gauge("minio_tpu_v2_loop_lag_ewma_ms",
                               {"loop": st.name},
                               round(st.ewma_ms, 3))
            METRICS2.set_gauge("minio_tpu_v2_loop_tasks",
                               {"loop": st.name}, st.pending)

    # -- stall watcher (its own thread) ---------------------------------

    def _watch(self) -> None:
        while not self._watch_stop.wait(WATCH_PERIOD_S):
            if not self.enabled:
                continue
            stall_s = self.stall_ms / 1e3
            now = time.monotonic()
            with self._mu:
                states = list(self._loops.values())
            frames = None
            for st in states:
                if (not st.active or st.thread_ident is None
                        or not st.last_beat or st.stalled_at):
                    continue
                overdue = now - st.last_beat - HEARTBEAT_S
                if overdue < stall_s:
                    continue
                st.stalled_at = now
                st.stalls += 1
                if frames is None:  # one frame walk per poll
                    frames = sys._current_frames()
                self._capture(st, overdue * 1e3,
                              frames.get(st.thread_ident))

    def _capture(self, st: _LoopState, overdue_ms: float,
                 frame) -> None:
        from ..logger import Logger
        from ..utils.profiler import frame_label
        from .metrics2 import METRICS2
        from .span import current_span
        stack: list[str] = []
        while frame is not None and len(stack) < STALL_STACK_DEPTH:
            code = frame.f_code
            stack.append(frame_label((code.co_filename,
                                      code.co_firstlineno,
                                      code.co_name)))
            frame = frame.f_back
        # Blame the first frame that is CODE, not our own
        # instrumentation: under MTPU_LOCKTRACE time.sleep itself is a
        # Python wrapper (locktrace._traced_sleep) and would otherwise
        # eat the headline that should name the caller.
        top = stack[0] if stack else "<no python frame>"
        for label in stack:
            if "locktrace.py" not in label:
                top = label
                break
        entry = {"loop": st.name, "overdueMs": round(overdue_ms, 1),
                 "at": time.time(), "topFrame": top, "stack": stack}
        with self._mu:
            self._stall_ring.append(entry)
        METRICS2.inc("minio_tpu_v2_loop_stalls_total",
                     {"loop": st.name})
        Logger.get().warn(
            f"loopmon: loop {st.name} stalled {overdue_ms:.0f}ms "
            f"in {top}", "loopmon", loop=st.name, frame=top)
        span = current_span()
        if span is not None:
            span.add_event("loop.stall", loop=st.name, frame=top,
                           overdue_ms=round(overdue_ms, 1))

    # -- reads ----------------------------------------------------------

    def lag_census(self) -> dict[str, float]:
        """{loop: EWMA lag ms} — the timeline's ``loopLag`` sample."""
        with self._mu:
            return {name: round(st.ewma_ms, 3)
                    for name, st in self._loops.items() if st.beats}

    def task_census(self) -> dict[str, int]:
        """{loop: pending tasks} — the timeline's ``loopTasks``."""
        with self._mu:
            return {name: st.pending
                    for name, st in self._loops.items() if st.beats}

    def recent_stalls(self, now: float | None = None,
                      window_s: float = RECENT_STALL_S) -> list[dict]:
        """Stall captures younger than ``window_s`` — the watchdog
        ``loop_stall`` rule's breach input (``now`` is wall-clock; the
        engine passes its tick time so tests stay deterministic)."""
        now = time.time() if now is None else now
        with self._mu:
            # Bounded BOTH ways: a capture "in the future" relative to
            # ``now`` (tests tick the watchdog at synthetic times while
            # real wall-clock captures sit in the ring) must not count
            # as recent, or one genuine stall poisons every
            # synthetic-time tick afterwards.
            return [dict(e) for e in self._stall_ring
                    if 0.0 <= now - e["at"] <= window_s]

    def snapshot(self) -> dict:
        """Full census + stall ring — the incident bundle's ``loops``
        section and the loopmon part of admin ``/profile``."""
        with self._mu:
            loops = []
            for name, st in sorted(self._loops.items()):
                lags = sorted(st.lags)
                p99 = lags[int(len(lags) * 0.99)] if lags else 0.0
                loops.append({
                    "loop": name, "beats": st.beats,
                    "lagMs": round(st.last_ms, 3),
                    "ewmaMs": round(st.ewma_ms, 3),
                    "p99Ms": round(p99, 3),
                    "pendingTasks": st.pending,
                    "readyCallbacks": st.ready,
                    "transports": st.transports,
                    "stalls": st.stalls,
                    "stalled": bool(st.stalled_at)})
            return {"enabled": self.enabled,
                    "stallMs": self.stall_ms,
                    "profilerRunning": self.profiler.running,
                    "loops": loops,
                    "stalls": [dict(e) for e in self._stall_ring]}


LOOPMON = LoopMonitor()
