"""Metrics v2: a typed, registered metric namespace with Prometheus
histograms, node/cluster split (ref the reference's cmd/metrics-v2.go
node vs cluster collectors).

Every metric name is REGISTERED up front with its type and help text;
recording to an unregistered name raises — tools/obs_lint.py enforces
the same invariant statically, so the namespace cannot drift.

The registry serializes to a JSON snapshot (`snapshot()`), snapshots
from peers MERGE (`merge()` — counters add, histogram buckets add), and
any snapshot renders to Prometheus text exposition (`render()`). The
node endpoint renders the local snapshot; the cluster endpoint fans out
an RPC (rpc/peer.py `metrics2`), merges, and renders the sum.
"""

from __future__ import annotations

import json
import threading

# Latency buckets in milliseconds (requests and phases share them; the
# +Inf bucket is implicit).
LATENCY_BUCKETS_MS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                      1000, 2500, 5000, 10000)

# Cardinality-guard overflow counter: every fold of a capped label
# value into "_other" lands here (registered in __init__ so every
# registry instance — including test-local ones — carries it).
_OVERFLOW = "minio_tpu_v2_metrics_label_overflow_total"


class MetricsV2:
    """Thread-safe registry of counters and histograms."""

    def __init__(self):
        self._mu = threading.Lock()
        # name -> (type, help, buckets|None)
        self._specs: dict[str, tuple[str, str, tuple | None]] = {}
        # name -> {labels_key: value | [bucket_counts, sum, count]}
        self._data: dict[str, dict[tuple, object]] = {}
        # labels_key -> labels dict (for rendering)
        self._labels: dict[tuple, dict] = {}
        # Cardinality guard: name -> {label: cap}; a capped label's
        # values past its cap fold into "_other" at recording time
        # (see _guard) — the fix for the latent unbounded-cardinality
        # risk of any per-bucket/per-tenant series.
        self._cap_labels: dict[str, dict[str, int]] = {}
        # (name, label) -> distinct values admitted so far
        self._cap_seen: dict[tuple[str, str], set] = {}
        self._specs[_OVERFLOW] = (
            "counter",
            "Capped-label values folded into _other by the "
            "cardinality guard, by metric and label.", None)
        self._data[_OVERFLOW] = {}

    # -- registration --------------------------------------------------

    def register(self, name: str, mtype: str, help_text: str,
                 buckets: tuple | None = None,
                 cap_labels: dict[str, int] | None = None) -> None:
        if mtype not in ("counter", "gauge", "histogram"):
            raise ValueError(f"bad metric type {mtype!r}")
        if mtype == "histogram" and buckets is None:
            buckets = LATENCY_BUCKETS_MS
        with self._mu:
            self._specs[name] = (mtype, help_text, buckets)
            self._data.setdefault(name, {})
            if cap_labels:
                self._cap_labels[name] = {
                    lbl: max(1, int(cap))
                    for lbl, cap in cap_labels.items()}

    def set_label_cap(self, name: str, label: str, cap: int) -> None:
        """Live-retune a label's cardinality cap (config-KV ``usage
        cardinality_cap``).  Already-admitted values keep their series
        (shrinking the cap only folds NEW values — re-labeling live
        counters would corrupt the deltas every scraper holds)."""
        with self._mu:
            if name not in self._specs:
                raise ValueError(f"unregistered metric {name!r}")
            self._cap_labels.setdefault(name, {})[label] = \
                max(1, int(cap))

    def registered_names(self) -> set[str]:
        with self._mu:
            return set(self._specs)

    def _key(self, labels: dict | None) -> tuple:
        """Series identity: a sorted items tuple, NOT a serialized
        string — this runs under the registry lock on every disk op /
        kernel call / request, so the critical section must stay at
        dict-key cost (the <= 5%% tracing-overhead budget)."""
        if not labels:
            return ()
        key = tuple(sorted(labels.items()))
        if key not in self._labels:
            self._labels[key] = dict(labels)
        return key

    def _spec(self, name: str, want: tuple[str, ...]):
        spec = self._specs.get(name)
        if spec is None:
            raise ValueError(f"unregistered metric {name!r} "
                             "(register it in obs/metrics2.py)")
        if spec[0] not in want:
            raise ValueError(f"{name} is a {spec[0]}, not {want}")
        return spec

    def _guard(self, name: str, labels: dict | None) -> dict | None:
        """Apply the cardinality cap (caller holds the lock): for each
        capped label, a value past the cap rewrites to "_other" and
        counts into metrics_label_overflow_total — so a hostile or
        runaway keyspace can never grow a capped series unboundedly,
        and the fold is itself observable."""
        caps = self._cap_labels.get(name)
        if not caps or not labels:
            return labels
        out = None
        for lbl, cap in caps.items():
            v = labels.get(lbl)
            if v is None or v == "_other":
                continue
            seen = self._cap_seen.setdefault((name, lbl), set())
            if v in seen:
                continue
            if len(seen) < cap:
                seen.add(v)
                continue
            if out is None:
                out = dict(labels)
            out[lbl] = "_other"
            # Direct write (we already hold the lock; inc() would
            # deadlock) — the overflow counter is registered below.
            series = self._data[_OVERFLOW]
            okey = self._key({"metric": name, "label": lbl})
            series[okey] = series.get(okey, 0) + 1
        return out if out is not None else labels

    # -- recording -----------------------------------------------------

    def inc(self, name: str, labels: dict | None = None,
            v: float = 1) -> None:
        with self._mu:
            self._spec(name, ("counter", "gauge"))
            series = self._data[name]
            key = self._key(self._guard(name, labels))
            series[key] = series.get(key, 0) + v

    def set_gauge(self, name: str, labels: dict | None = None,
                  v: float = 0) -> None:
        with self._mu:
            self._spec(name, ("gauge",))
            self._data[name][self._key(self._guard(name, labels))] = v

    def observe(self, name: str, labels: dict | None = None,
                v: float = 0.0) -> None:
        with self._mu:
            _, _, buckets = self._spec(name, ("histogram",))
            series = self._data[name]
            key = self._key(self._guard(name, labels))
            h = series.get(key)
            if h is None:
                h = series[key] = [[0] * (len(buckets) + 1), 0.0, 0]
            counts, _, _ = h
            for i, ub in enumerate(buckets):
                if v <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            h[1] += v
            h[2] += 1

    def get(self, name: str, labels: dict | None = None):
        """Current value: number (counter/gauge) or (sum, count) for a
        histogram; 0 / (0, 0) when the series has no samples yet."""
        with self._mu:
            mtype = self._spec(name, ("counter", "gauge", "histogram"))[0]
            val = self._data[name].get(self._key(labels))
            if mtype == "histogram":
                return (val[1], val[2]) if val else (0.0, 0)
            return val or 0

    # -- snapshot / merge / render ------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            out = {}
            for name, (mtype, help_text, buckets) in self._specs.items():
                series = []
                for key, val in self._data[name].items():
                    labels = self._labels.get(key, {})
                    if mtype == "histogram":
                        series.append({"labels": labels,
                                       "counts": list(val[0]),
                                       "sum": val[1], "count": val[2]})
                    else:
                        series.append({"labels": labels, "value": val})
                out[name] = {"type": mtype, "help": help_text,
                             "buckets": list(buckets) if buckets else None,
                             "series": series}
            return out

    def reset(self) -> None:
        with self._mu:
            for name in self._data:
                self._data[name] = {}
            # The cardinality guard resets with the series it guards:
            # stale seen-sets would fold post-reset traffic against
            # ghost admissions (new values denied their own series by
            # names that no longer exist in the registry).
            self._cap_seen.clear()


def merge(*snapshots: dict) -> dict:
    """Sum metric snapshots across nodes (counters add; histogram
    bucket counts, sums and counts add; gauges add — cluster totals)."""
    out: dict = {}
    for snap in snapshots:
        for name, m in snap.items():
            cur = out.get(name)
            if cur is None:
                out[name] = {
                    "type": m["type"], "help": m["help"],
                    "buckets": m.get("buckets"),
                    "series": [dict(s, labels=dict(s["labels"]),
                                    **({"counts": list(s["counts"])}
                                       if "counts" in s else {}))
                               for s in m["series"]],
                }
                continue
            index = {json.dumps(sorted(s["labels"].items())): s
                     for s in cur["series"]}
            for s in m["series"]:
                key = json.dumps(sorted(s["labels"].items()))
                hit = index.get(key)
                if hit is None:
                    add = dict(s, labels=dict(s["labels"]))
                    if "counts" in s:
                        add["counts"] = list(s["counts"])
                    cur["series"].append(add)
                    index[key] = add
                elif "counts" in s:
                    hit["counts"] = [a + b for a, b in
                                     zip(hit["counts"], s["counts"])]
                    hit["sum"] += s["sum"]
                    hit["count"] += s["count"]
                else:
                    hit["value"] += s["value"]
    return out


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _label_str(labels: dict, extra: str = "") -> str:
    parts = [f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _num(v) -> str:
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return repr(v) if not isinstance(v, int) else str(v)


def render(snapshot: dict) -> str:
    """Prometheus text exposition (format 0.0.4) of a snapshot."""
    lines: list[str] = []
    for name in sorted(snapshot):
        m = snapshot[name]
        if not m["series"]:
            continue
        lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} {m['type']}")
        for s in sorted(m["series"],
                        key=lambda s: sorted(s["labels"].items())):
            labels = s["labels"]
            if m["type"] == "histogram":
                cum = 0
                for ub, c in zip(m["buckets"], s["counts"]):
                    cum += c
                    le = 'le="%s"' % _num(ub)
                    lines.append(
                        f"{name}_bucket{_label_str(labels, le)} {cum}")
                cum += s["counts"][-1]
                inf = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket{_label_str(labels, inf)} {cum}")
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_num(round(s['sum'], 6))}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{s['count']}")
            else:
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_num(s['value'])}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The v2 metric namespace. EVERY name recorded anywhere in the codebase
# must be registered here — METRICS2 raises otherwise, and
# tools/obs_lint.py enforces it statically on the tier-1 path.

METRICS2 = MetricsV2()

METRICS2.register(
    "minio_tpu_v2_api_requests_total", "counter",
    "S3 API requests served, by api and status code.")
METRICS2.register(
    "minio_tpu_v2_api_request_duration_ms", "histogram",
    "End-to-end request latency in milliseconds, by api.")
METRICS2.register(
    "minio_tpu_v2_api_rx_bytes_total", "counter",
    "Request body bytes received.")
METRICS2.register(
    "minio_tpu_v2_api_tx_bytes_total", "counter",
    "Response body bytes sent.")
METRICS2.register(
    "minio_tpu_v2_put_phase_duration_ms", "histogram",
    "Per-phase PUT hot-path latency in milliseconds "
    "(auth, transform, encode, write, commit, post).")
METRICS2.register(
    "minio_tpu_v2_disk_op_duration_ms", "histogram",
    "Per-disk storage call latency in milliseconds, by op.")
METRICS2.register(
    "minio_tpu_v2_rpc_requests_total", "counter",
    "Peer RPC calls served, by service and method.")
METRICS2.register(
    "minio_tpu_v2_kernel_invocations_total", "counter",
    "Codec/hash kernel invocations, by kernel and device.")
METRICS2.register(
    "minio_tpu_v2_kernel_bytes_total", "counter",
    "Bytes encoded/decoded/verified by the kernels, "
    "by kernel and device.")
METRICS2.register(
    "minio_tpu_v2_kernel_wall_seconds_total", "counter",
    "Kernel wall-clock seconds, by kernel and device.")
METRICS2.register(
    "minio_tpu_v2_kernel_batch_blocks_total", "counter",
    "Blocks carried by kernel batches (occupancy numerator).")
METRICS2.register(
    "minio_tpu_v2_kernel_coalesced_requests_total", "counter",
    "Requests merged into coalesced kernel dispatches.")
METRICS2.register(
    "minio_tpu_v2_kernel_dispatch_ms", "histogram",
    "Per-dispatch kernel latency in milliseconds, by kernel, dispatch "
    "backend (device/native/xla-cpu/host) and batch-size bucket.")
METRICS2.register(
    "minio_tpu_v2_kernel_queue_wait_ms", "histogram",
    "Time a request's encode batch waited in the coalescer window "
    "before dispatch, by kernel (the queue half of the queue-wait vs "
    "execute split).")
METRICS2.register(
    "minio_tpu_v2_kernel_backend_bytes_total", "counter",
    "Bytes dispatched per kernel and dispatch backend "
    "(device/native/xla-cpu/host) — the timeline's GiB/s numerator.")
METRICS2.register(
    "minio_tpu_v2_kernel_backend_state", "gauge",
    "Dispatch backend health state (0=up, 1=degraded, 2=down), "
    "by backend.")
METRICS2.register(
    "minio_tpu_v2_kernel_backend_transitions_total", "counter",
    "Dispatch backend health-state transitions, by backend and "
    "new state.")
METRICS2.register(
    "minio_tpu_v2_kernel_backend_probes_total", "counter",
    "Recovery probes of kernel dispatch backends, by backend and "
    "result (pass/fail).")
METRICS2.register(
    "minio_tpu_v2_codec_plan_lane", "gauge",
    "Codec autotuner plan: chosen dispatch lane per (kernel, batch "
    "size bucket) as an index into kernprof BACKENDS "
    "(0=device 1=native 2=xla-cpu 3=host).")
METRICS2.register(
    "minio_tpu_v2_codec_plan_transitions_total", "counter",
    "Codec autotuner plan flips, by kernel, bucket and new lane "
    "(every flip also logs its cause and lands a codec.plan span "
    "event).")
METRICS2.register(
    "minio_tpu_v2_codec_plan_probes_total", "counter",
    "Codec autotuner probe-ladder dispatches, by lane and result "
    "(pass/fail).")
METRICS2.register(
    "minio_tpu_v2_codec_plan_fanout_total", "counter",
    "Coalesced encode windows fanned out as parallel per-device "
    "dispatches, by device count.")
METRICS2.register(
    "minio_tpu_v2_traces_completed_total", "counter",
    "Completed request traces.")
METRICS2.register(
    "minio_tpu_v2_cluster_nodes", "gauge",
    "Nodes contributing to a cluster metrics scrape.")
METRICS2.register(
    "minio_tpu_v2_qos_admission_inflight", "gauge",
    "In-flight admitted requests, by API class.")
METRICS2.register(
    "minio_tpu_v2_qos_admission_queue_depth", "gauge",
    "Requests waiting in the admission queue, by API class.")
METRICS2.register(
    "minio_tpu_v2_qos_admission_wait_ms", "histogram",
    "Admission wait time in milliseconds, by API class "
    "(shed waits included).")
METRICS2.register(
    "minio_tpu_v2_qos_shed_total", "counter",
    "Requests shed with 503 SlowDown, by API class and reason.")
METRICS2.register(
    "minio_tpu_v2_qos_deadline_expired_total", "counter",
    "Request deadline expiries, by where the budget ran out.")
METRICS2.register(
    "minio_tpu_v2_qos_dispatch_total", "counter",
    "Batching-layer dispatches, by priority lane (fg/bg).")
METRICS2.register(
    "minio_tpu_v2_qos_bg_deferrals_total", "counter",
    "Background dispatch deferral slices yielded to foreground work.")
METRICS2.register(
    "minio_tpu_v2_qos_bg_promotions_total", "counter",
    "Background dispatches promoted past busy foreground (aging).")
METRICS2.register(
    "minio_tpu_v2_pipeline_depth", "gauge",
    "Configured depth of the data-plane pipelines, by pipeline.")
METRICS2.register(
    "minio_tpu_v2_pipeline_stall_seconds_total", "counter",
    "Seconds a data-plane pipeline stage spent blocked on the other "
    "side, by pipeline and stage (produce=worker waited on a full "
    "queue, consume=consumer waited on an empty one).")
METRICS2.register(
    "minio_tpu_v2_drive_state", "gauge",
    "Drive health state by disk endpoint "
    "(0=ok, 1=suspect, 2=faulty).")
METRICS2.register(
    "minio_tpu_v2_drive_state_transitions_total", "counter",
    "Drive health state transitions, by disk endpoint and new state.")
METRICS2.register(
    "minio_tpu_v2_drive_op_latency_ewma_ms", "gauge",
    "Rolling per-drive op-class latency EWMA in milliseconds "
    "(published on health-state transitions).")
METRICS2.register(
    "minio_tpu_v2_drive_op_errors_total", "counter",
    "Drive op errors (real disk faults, not namespace misses), "
    "by disk endpoint and op class.")
METRICS2.register(
    "minio_tpu_v2_drive_quarantines_total", "counter",
    "Drives auto-quarantined by the health monitor, by disk endpoint.")
METRICS2.register(
    "minio_tpu_v2_drive_probation_probes_total", "counter",
    "Probation probe rounds on quarantined drives (shadow read + "
    "bitrot verify), by result (pass/fail).")
METRICS2.register(
    "minio_tpu_v2_hedged_reads_total", "counter",
    "Hedged shard reads, by result: fired (backup read launched past "
    "the straggler budget), won (the hedge substituted a straggler), "
    "wasted (the primary answered anyway).")
METRICS2.register(
    "minio_tpu_v2_hedge_budget_ms", "gauge",
    "Current adaptive straggler budget for hedged shard reads.")
METRICS2.register(
    "minio_tpu_v2_mrf_drops_total", "counter",
    "Heal requests dropped because the MRF queue was full.")
METRICS2.register(
    "minio_tpu_v2_mrf_queue_depth", "gauge",
    "Objects waiting in the most-recently-failed heal queue.")
METRICS2.register(
    "minio_tpu_v2_heal_repair_bytes_total", "counter",
    "Repair traffic moved by object heals, by mode (rs = conventional "
    "k-survivor decode, regen = minimum-bandwidth REGEN repair) and "
    "src (disk = bytes helpers read from media, net = bytes shipped "
    "in helper responses) — the observable form of the regenerating "
    "code's repair-bandwidth claim.")
METRICS2.register(
    "minio_tpu_v2_fault_injections_total", "counter",
    "Faults injected by the runtime fault-injection subsystem, "
    "by kind.")
METRICS2.register(
    "minio_tpu_v2_mrf_journal_backlog", "gauge",
    "Live entries in the durable MRF journal (.minio.sys/mrf.log): "
    "queued repairs that survive a crash and replay at boot.")
METRICS2.register(
    "minio_tpu_v2_mrf_journal_drops_total", "counter",
    "Repairs whose journal append was dropped at the size cap — "
    "queued in memory but NOT crash-durable.")
METRICS2.register(
    "minio_tpu_v2_recovery_swept_total", "counter",
    "Boot-time recovery sweep results, by what (found/cleaned/"
    "stage_files/requeued/journal_replayed).")
METRICS2.register(
    "minio_tpu_v2_cache_hits_total", "counter",
    "Hot-object cache hits, by tier (mem/disk).")
METRICS2.register(
    "minio_tpu_v2_cache_misses_total", "counter",
    "Hot-object cache lookups that missed both tiers.")
METRICS2.register(
    "minio_tpu_v2_cache_fills_total", "counter",
    "Single-flight cache fills settled, by result (cached/uncached/"
    "invalidated/short/error/abandoned/waiter_fallback).")
METRICS2.register(
    "minio_tpu_v2_cache_coalesced_waits_total", "counter",
    "GETs that coalesced onto another request's in-flight fill "
    "instead of paying their own erasure read.")
METRICS2.register(
    "minio_tpu_v2_cache_evictions_total", "counter",
    "Hot-object cache evictions, by tier and reason "
    "(capacity/invalidate).")
METRICS2.register(
    "minio_tpu_v2_cache_stale_total", "counter",
    "Cache hits rejected by ETag revalidation (a lost invalidation "
    "caught before serving stale bytes), by tier.")
METRICS2.register(
    "minio_tpu_v2_cache_invalidations_total", "counter",
    "Cache invalidation events that dropped entries or poisoned "
    "in-flight fills, by source (local/peer/stale/bucket).")
METRICS2.register(
    "minio_tpu_v2_cache_bytes", "gauge",
    "Bytes resident in the hot-object cache, by tier.")
METRICS2.register(
    "minio_tpu_v2_cache_entries", "gauge",
    "Objects resident in the hot-object cache, by tier.")
METRICS2.register(
    "minio_tpu_v2_slow_requests_total", "counter",
    "Requests captured by the slow-request log, by API class and "
    "blamed layer.")
METRICS2.register(
    "minio_tpu_v2_slow_request_duration_ms", "histogram",
    "Latency of slowlog-captured requests in milliseconds, by API "
    "class and blamed layer.")
METRICS2.register(
    "minio_tpu_v2_profile_bursts_total", "counter",
    "Profile-on-slow sampling bursts triggered by slow-rate spikes.")
METRICS2.register(
    "minio_tpu_v2_api_class_errors_total", "counter",
    "Requests answered 5xx, by API class (the error-burn numerator; "
    "per-API status detail lives on api_requests_total).")
METRICS2.register(
    "minio_tpu_v2_alerts_firing", "gauge",
    "Watchdog alert state by rule (1 = firing, 0 = not).")
METRICS2.register(
    "minio_tpu_v2_alert_transitions_total", "counter",
    "Watchdog alert lifecycle transitions, by rule and new state "
    "(pending/firing/resolved).")
METRICS2.register(
    "minio_tpu_v2_alert_webhook_total", "counter",
    "Alert webhook delivery outcomes, by result "
    "(sent/failed/dropped).")
METRICS2.register(
    "minio_tpu_v2_incidents_total", "counter",
    "Incident bundles frozen by firing alerts, by rule.")
METRICS2.register(
    "minio_tpu_v2_open_connections", "gauge",
    "Client connections currently held by the front door "
    "(keep-alive sockets, idle or active).")
METRICS2.register(
    "minio_tpu_v2_accept_queue_depth", "gauge",
    "Connections accepted but not yet established (TLS handshake / "
    "loop handoff in flight).")
METRICS2.register(
    "minio_tpu_v2_rpc_inflight", "gauge",
    "Internal peer RPCs currently in flight on this node (client "
    "side, both fabrics) — pair with the process thread count to "
    "verify the async fabric's zero-thread-per-call claim.")
METRICS2.register(
    "minio_tpu_v2_connections_accepted_total", "counter",
    "Client connections accepted by the front door.")
METRICS2.register(
    "minio_tpu_v2_conn_parse_errors_total", "counter",
    "Connections rejected at the HTTP framing layer (malformed head, "
    "oversized head, bad Content-Length, failed TLS handshake).")
METRICS2.register(
    "minio_tpu_v2_select_scanned_bytes_total", "counter",
    "Object bytes read by SelectObjectContent scans "
    "(the BytesScanned the Progress/Stats events report).")
METRICS2.register(
    "minio_tpu_v2_select_processed_bytes_total", "counter",
    "Bytes the select scan actually decoded (columnar Parquet scans "
    "prune to the referenced columns' uncompressed pages) — the "
    "BytesProcessed numerator and the timeline's scan GiB/s source.")
METRICS2.register(
    "minio_tpu_v2_select_returned_bytes_total", "counter",
    "Payload bytes returned in select Records events.")
METRICS2.register(
    "minio_tpu_v2_select_requests_total", "counter",
    "SelectObjectContent queries executed, by engine "
    "(columnar/row/error).")
METRICS2.register(
    "minio_tpu_v2_select_fallback_rows_total", "counter",
    "Rows the columnar scan routed through the row-engine fallback "
    "(division by zero, exact-integer overflow, complex LIKE, "
    "row-tier batches) — exactness escapes, not errors.")
# Tenant/workload attribution (obs/usage.py). Every dynamic label
# (bucket, tenant) is CAPPED: values past the cap fold into "_other"
# and count into metrics_label_overflow_total — the cap follows the
# usage subsystem's cardinality_cap on live reload (set_label_cap).
_USAGE_CAP = 64
METRICS2.register(
    "minio_tpu_v2_usage_requests_total", "counter",
    "S3 requests attributed per bucket and QoS class "
    "(cardinality-capped; overflow folds into _other).",
    cap_labels={"bucket": _USAGE_CAP})
METRICS2.register(
    "minio_tpu_v2_usage_rx_bytes_total", "counter",
    "Request body bytes received, per bucket (capped).",
    cap_labels={"bucket": _USAGE_CAP})
METRICS2.register(
    "minio_tpu_v2_usage_tx_bytes_total", "counter",
    "Response body bytes sent, per bucket (capped).",
    cap_labels={"bucket": _USAGE_CAP})
METRICS2.register(
    "minio_tpu_v2_usage_errors_total", "counter",
    "Non-shed 5xx answers, per bucket (capped).",
    cap_labels={"bucket": _USAGE_CAP})
METRICS2.register(
    "minio_tpu_v2_usage_shed_total", "counter",
    "503 SlowDown sheds / burnt deadlines, per bucket (capped) — "
    "the noisy_neighbor rule's per-tenant shed numerator.",
    cap_labels={"bucket": _USAGE_CAP})
METRICS2.register(
    "minio_tpu_v2_usage_tenant_requests_total", "counter",
    "S3 requests attributed per access key and QoS class (capped; "
    "tenant ids ride REDACTED — the registry renders on the "
    "unauthenticated metrics pages).",
    cap_labels={"tenant": _USAGE_CAP})
METRICS2.register(
    _OVERFLOW, "counter",
    "Capped-label values folded into _other by the cardinality "
    "guard, by metric and label.")
# Event-loop health plane (obs/loopmon.py): per-loop scheduling lag,
# stall flight recorder, pool census and the continuous profiler.
METRICS2.register(
    "minio_tpu_v2_loop_lag_ms", "histogram",
    "Event-loop heartbeat scheduling lag in milliseconds, by loop "
    "(expected vs actual wake of the 10Hz loopmon heartbeat — the "
    "runtime twin of lint rule R8).")
METRICS2.register(
    "minio_tpu_v2_loop_lag_ewma_ms", "gauge",
    "EWMA of event-loop scheduling lag in milliseconds, by loop.")
METRICS2.register(
    "minio_tpu_v2_loop_tasks", "gauge",
    "Pending asyncio tasks on each monitored event loop.")
METRICS2.register(
    "minio_tpu_v2_loop_stalls_total", "counter",
    "Stall episodes the loopmon flight recorder captured (heartbeat "
    "overdue past obs.loop_stall_ms), by loop.")
METRICS2.register(
    "minio_tpu_v2_pool_threads", "gauge",
    "Executor pool size, by pool (worker/rpc/stream) — splits the "
    "flat process thread count so a stalled loop and an exhausted "
    "pool are distinguishable.")
METRICS2.register(
    "minio_tpu_v2_pool_threads_busy", "gauge",
    "Executor pool threads currently running work, by pool.")
METRICS2.register(
    "minio_tpu_v2_profile_samples_total", "counter",
    "Thread stack samples taken by the continuous profiler "
    "(obs/loopmon.py, ~1% duty cycle).")
