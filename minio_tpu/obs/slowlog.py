"""Always-on slow-request capture with per-layer latency attribution.

Any request whose wall time exceeds its API class's live-reloadable SLO
threshold (config-KV ``obs.slow_ms[_read|_write|_list|_admin]``), or
that answers 5xx, gets its full PR-1 span tree plus its QoS
admission/deadline data persisted into a bounded ring — annotated with
a computed **blamed layer** so "why was this request slow?" is answered
from the entry itself, not by replaying load. Deliberate backpressure
(admission sheds, burnt deadlines) is EXEMPT: a 503 SlowDown is the
QoS layer working, and letting sheds flood the ring/blame histogram
would bury the real tail (bench.py's qos_brownout asserts this).

Blame is derived from child-span SELF-times (duration minus children):
  admission-wait  QoS queue wait before the handler ran
  encode-kernel   RS/bitrot kernel work (kernel.*, ec.encode)
  disk            local disk ops + shard fan-out (disk.*, ec.shard_*)
  rpc             peer wire + remote server time (rpc.*)
  client-stream   root self-time: reading the client's body / writing
                  the response (plus auth and handler glue)
  other           anything unattributable (no trace, unknown spans)

Entries land as a metrics-v2 histogram labeled by class and blamed
layer, so dashboards see WHERE tail latency lives without scraping the
ring. An optional profile-on-slow mode (``obs.profile_on_slow``)
triggers a short SamplingProfiler burst when the slow rate spikes.
"""

from __future__ import annotations

import threading
import time
from collections import deque

BLAME_ADMISSION = "admission-wait"
BLAME_ENCODE = "encode-kernel"
BLAME_SCAN = "scan-kernel"
BLAME_DISK = "disk"
BLAME_RPC = "rpc"
BLAME_CLIENT = "client-stream"
BLAME_OTHER = "other"

BLAME_LAYERS = (BLAME_ADMISSION, BLAME_ENCODE, BLAME_SCAN, BLAME_DISK,
                BLAME_RPC, BLAME_CLIENT, BLAME_OTHER)

API_CLASSES = ("read", "write", "list", "admin", "select")


def _bucket_for(name: str) -> str | None:
    """Span name -> blame bucket; None = inherit the parent's bucket."""
    if name.startswith("disk.") or name.startswith("ec.shard_"):
        return BLAME_DISK
    if name.startswith("rpc."):
        return BLAME_RPC
    if name.startswith("select."):
        # Columnar S3 Select scan work (s3select/engine.py): a
        # scan-bound SelectObjectContent blames its kernel time, not
        # client-stream — the disk/decode spans BELOW select.scan
        # still re-bucket to their own layers.
        return BLAME_SCAN
    if (name.startswith("kernel.") or name == "ec.encode"
            or name.startswith("bitrot")):
        return BLAME_ENCODE
    return None


def blame_layers(tree: dict | None,
                 admission_wait_ms: float = 0.0) -> dict[str, float]:
    """Attribute a span tree's wall time to blame buckets by self-time.

    Parallel fan-out children may sum past their parent's duration (six
    disks writing at once); self-time clamps at zero and the children
    keep their full durations — over-attribution to a bucket is exactly
    the signal wanted (the quorum waited on that layer)."""
    totals = dict.fromkeys(BLAME_LAYERS, 0.0)
    totals[BLAME_ADMISSION] = max(0.0, admission_wait_ms)

    def walk(node: dict, inherited: str, deduct: float = 0.0) -> None:
        if not isinstance(node, dict):
            return
        dur = float(node.get("durationMs", 0.0) or 0.0)
        kids = [c for c in node.get("children", ())
                if isinstance(c, dict)]
        child_sum = sum(float(c.get("durationMs", 0.0) or 0.0)
                        for c in kids)
        bucket = _bucket_for(str(node.get("name", ""))) or inherited
        totals[bucket] += max(0.0, dur - child_sum - deduct)
        for c in kids:
            walk(c, bucket)

    if tree is not None:
        # Root self-time is the handler reading/writing the client
        # stream (plus auth/glue) — everything below it re-buckets.
        # The admission wait elapsed INSIDE the root span (route_qos
        # blocks under it with no child span), so deduct it from the
        # root's self-time: without this, client-stream >= admission
        # always and a QoS-queuing-dominated request misblames.
        walk(tree, BLAME_CLIENT, deduct=totals[BLAME_ADMISSION])
    return totals


def blamed_layer(totals: dict[str, float]) -> str:
    worst = max(totals, key=lambda b: totals[b])
    return worst if totals[worst] > 0.0 else BLAME_OTHER


class SlowLog:
    """Bounded ring of annotated slow/5xx request captures
    (singleton ``SLOWLOG``; served by admin ``/slowlog``)."""

    RING_SIZE = 128
    # Profile-on-slow: a burst fires when this many captures land
    # within TRIGGER_WINDOW_S, at most once per COOLDOWN_S.
    PROFILE_TRIGGER = 5
    TRIGGER_WINDOW_S = 10.0
    PROFILE_BURST_S = 2.0
    PROFILE_COOLDOWN_S = 60.0

    def __init__(self):
        self.enabled = True
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=self.RING_SIZE)
        self.total = 0
        # Requests excluded as deliberate backpressure (admission
        # sheds, burnt deadlines): the direct evidence the exemption
        # engaged — bench's brownout asserts every shed incremented
        # this instead of guessing from 503 status codes (a quorum
        # 503 is a capture we WANT, not a leak).
        self.exempted = 0
        self.slow_ms = 1000.0
        self._class_ms: dict[str, float | None] = {}
        self.profile_on_slow = False
        self.last_profile: dict | None = None
        self._slow_times: deque = deque(maxlen=self.PROFILE_TRIGGER)
        self._profiling = False
        self._last_burst = 0.0

    # -- live configuration (config-KV apply hook) ---------------------

    def configure(self, slow_ms: float,
                  per_class: dict[str, float | None] | None = None,
                  profile_on_slow: bool = False) -> None:
        """slow_ms <= 0 disables the latency trigger (5xx capture
        stays on); per-class values override the global threshold."""
        with self._mu:
            self.slow_ms = float(slow_ms)
            self._class_ms = dict(per_class or {})
            self.profile_on_slow = bool(profile_on_slow)

    def threshold_ms(self, api_class: str) -> float:
        override = self._class_ms.get(api_class)
        return self.slow_ms if override is None else float(override)

    def thresholds(self) -> dict:
        return {"default": self.slow_ms,
                **{c: v for c, v in sorted(self._class_ms.items())
                   if v is not None}}

    # -- capture -------------------------------------------------------

    def record(self, *, api: str, api_class: str, method: str,
               path: str, status: int, duration_ms: float,
               request_id: str = "", trace: dict | None = None,
               qos: dict | None = None,
               exempt: bool = False) -> dict | None:
        """Called once per finished S3 request; returns the captured
        entry, or None on the (overwhelmingly common) fast path."""
        if not self.enabled:
            return None
        if exempt:
            with self._mu:
                self.exempted += 1
            return None
        thr = self.threshold_ms(api_class or "read")
        slow = thr > 0 and duration_ms >= thr
        if not slow and status < 500:
            return None
        wait_ms = float((qos or {}).get("waitMs", 0.0) or 0.0)
        totals = blame_layers(trace, admission_wait_ms=wait_ms)
        blamed = blamed_layer(totals)
        entry = {
            "time": time.time(),
            "api": api, "apiClass": api_class,
            "method": method, "path": path,
            "statusCode": status,
            "durationMs": round(duration_ms, 3),
            "thresholdMs": thr,
            "requestID": request_id,
            "blamedLayer": blamed,
            "blameMs": {b: round(v, 3) for b, v in totals.items()
                        if v > 0.0},
            "slow": slow,
        }
        if qos:
            entry["qos"] = dict(qos)
        if trace is not None:
            entry["spans"] = trace
        with self._mu:
            self._ring.append(entry)
            self.total += 1
        from .metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_slow_requests_total",
                     {"class": api_class or "read", "blame": blamed})
        METRICS2.observe("minio_tpu_v2_slow_request_duration_ms",
                         {"class": api_class or "read",
                          "blame": blamed}, duration_ms)
        self._maybe_profile()
        return entry

    # -- profile-on-slow -----------------------------------------------

    def _maybe_profile(self) -> None:
        if not self.profile_on_slow:
            return
        now = time.monotonic()
        with self._mu:
            self._slow_times.append(now)
            if (self._profiling
                    or len(self._slow_times) < self.PROFILE_TRIGGER
                    or now - self._slow_times[0] > self.TRIGGER_WINDOW_S
                    or now - self._last_burst < self.PROFILE_COOLDOWN_S):
                return
            self._profiling = True
            self._last_burst = now
        # mtpu-lint: disable=R1 -- the 2s profile burst runs past the slow request that tripped it, by design
        threading.Thread(target=self._burst, daemon=True,
                         name="slowlog-profile-burst").start()

    def _burst(self) -> None:
        from ..utils.profiler import SamplingProfiler
        try:
            prof = SamplingProfiler(interval=0.005)
            prof.start()
            time.sleep(self.PROFILE_BURST_S)
            report = prof.stop()
            with self._mu:
                self.last_profile = {"at": time.time(),
                                     "report": report}
            from .metrics2 import METRICS2
            METRICS2.inc("minio_tpu_v2_profile_bursts_total")
        finally:
            with self._mu:
                self._profiling = False

    # -- reads ---------------------------------------------------------

    def entries(self, n: int = 50, blame: str = "",
                api: str = "") -> list[dict]:
        """Newest-last tail of the ring, filtered by blamed layer
        and/or api-class/api-name substring."""
        with self._mu:
            items = list(self._ring)
        if blame:
            items = [e for e in items if e["blamedLayer"] == blame]
        if api:
            items = [e for e in items
                     if api in (e["apiClass"], e["api"])]
        return items[-n:]

    def reset(self) -> None:
        with self._mu:
            self._ring.clear()
            self.total = 0
            self.exempted = 0
            self._slow_times.clear()
            self.last_profile = None


# The process-wide slow-request log the S3 front end records into.
SLOWLOG = SlowLog()
