"""Span-based request tracing: one tree per request, keyed by the
request id the S3 front end already mints (x-amz-request-id).

The reference traces per-handler wall time only (httpTrace,
cmd/handler-utils.go:349); measurement-first EC papers (arXiv:1709.05365,
arXiv:1504.07038) show per-phase, per-node attribution is what turns EC
tuning into engineering — so every layer here opens child spans: the S3
handler (root), erasure engine phases, TPU kernel invocations, and each
per-disk storage call (local and RPC). The trace id crosses the peer RPC
boundary in a header (rpc/transport.py) and server-side spans come back
in the response, so a distributed PUT stitches into ONE tree.

Cost discipline (acceptance: <= 5% on the bench PUT path):
- no active trace -> ``TRACER.span()`` returns a shared no-op context
  manager after one contextvar read;
- spans are plain objects, two perf_counter() calls each;
- children per span are capped (dropped tail is counted, never grown);
- completed traces land in a bounded ring, oldest evicted.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "minio_tpu_span", default=None)

# Per-span child cap: a streamed multi-GiB PUT must not grow its trace
# without bound — the tail is dropped and counted in `dropped`.
MAX_CHILDREN = 64

# Per-span event cap (QoS shed/deadline markers): same bounding rule.
MAX_EVENTS = 16


class _Noop:
    """Shared do-nothing span context (the untraced fast path)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class Span:
    """One timed operation in a trace tree.

    Also a context manager: entering makes it the thread's current span
    (children attach via the contextvar), exiting records the duration
    and restores the previous current span.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "duration_ms", "tags", "children", "events", "dropped",
                 "_t0", "_token", "_tracer", "_done")

    _seq = 0
    _seq_mu = threading.Lock()

    def __init__(self, name: str, trace_id: str, parent_id: str = "",
                 tags: dict | None = None, tracer: "Tracer | None" = None):
        with Span._seq_mu:
            Span._seq += 1
            seq = Span._seq
        self.span_id = f"{seq:x}"
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self.duration_ms = 0.0
        self.tags = tags or {}
        self.children: list = []  # Span | dict (grafted remote spans)
        self.events: list = []    # point-in-time markers (QoS shed, ...)
        self.dropped = 0
        self._t0 = time.perf_counter()
        self._token = None
        self._tracer = tracer
        self._done = False

    # -- tree assembly -------------------------------------------------

    def add_child(self, child) -> None:
        """Attach a Span or an already-serialized span dict (remote).
        list.append is GIL-atomic, safe from parallel_map workers; the
        length check here is advisory under concurrency (two workers
        may both pass it) — to_dict() enforces the cap exactly."""
        if len(self.children) >= MAX_CHILDREN:
            self.dropped += 1
            return
        self.children.append(child)

    def add_event(self, name: str, **attrs) -> None:
        """Record a point-in-time marker on this span (admission shed,
        deadline expiry). Bounded like children; append is GIL-atomic."""
        if len(self.events) >= MAX_EVENTS:
            self.dropped += 1
            return
        ev = {"name": name, "time": time.time()}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def to_dict(self) -> dict:
        d = {
            "traceId": self.trace_id, "spanId": self.span_id,
            "parentId": self.parent_id, "name": self.name,
            "start": self.start,
            "durationMs": round(self.duration_ms, 3),
        }
        if self.tags:
            d["tags"] = dict(self.tags)
        if self.events:
            d["events"] = [dict(e) for e in self.events[:MAX_EVENTS]]
        kids = self.children
        dropped = self.dropped
        if len(kids) > MAX_CHILDREN:  # racy appends past the cap
            dropped += len(kids) - MAX_CHILDREN
            kids = kids[:MAX_CHILDREN]
        if kids:
            d["children"] = [c if isinstance(c, dict) else c.to_dict()
                             for c in kids]
        if dropped:
            d["droppedChildren"] = dropped
        return d

    # -- context management --------------------------------------------

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, *exc) -> bool:
        self.finish()
        return False

    def detach_context(self) -> None:
        """Reset the contextvar token WITHOUT finishing the span — for
        handoff points where the entering thread returns to a pool
        while the span stays open (the async front door's streaming
        responses: the drain task carries the span in a copied context
        and calls finish() later, from a context where resetting the
        original token would be illegal)."""
        if self._token is not None:
            _current.reset(self._token)
            self._token = None

    def finish(self) -> dict | None:
        """Close the span; for a ROOT span returns the completed trace
        tree (and lands it in the tracer's ring)."""
        if self._done:
            return None
        self._done = True
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        self.duration_ms = (time.perf_counter() - self._t0) * 1e3
        if not self.parent_id and self._tracer is not None:
            return self._tracer._complete(self)
        return None


class Tracer:
    """Process-wide span factory + bounded ring of completed traces."""

    RING_SIZE = 256

    def __init__(self):
        self.enabled = os.environ.get("MINIO_TPU_TRACE", "on") != "off"
        self._ring: deque = deque(maxlen=self.RING_SIZE)
        self._mu = threading.Lock()

    # -- span creation -------------------------------------------------

    @staticmethod
    def current() -> Span | None:
        return _current.get()

    def begin(self, name: str, trace_id: str, **tags) -> Span | None:
        """Open a ROOT span (no context entered yet; pair with
        Span.__enter__/finish). None when tracing is disabled."""
        if not self.enabled:
            return None
        return Span(name, trace_id, tags=tags or None, tracer=self)

    def span(self, name: str, parent: Span | None = None, **tags):
        """Child span context manager. Attaches to `parent` when given
        (cross-thread: parallel_map workers), else to the thread's
        current span; a shared no-op when neither exists."""
        if parent is None:
            parent = _current.get()
            if parent is None:
                return _NOOP
        child = Span(name, parent.trace_id, parent.span_id,
                     tags=tags or None)
        parent.add_child(child)
        return child

    # -- completed traces ----------------------------------------------

    def _complete(self, root: Span) -> dict:
        tree = root.to_dict()
        with self._mu:
            self._ring.append(tree)
        from .metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_traces_completed_total")
        return tree

    def recent(self, n: int = 32) -> list[dict]:
        with self._mu:
            items = list(self._ring)
        return items[-n:]

    def reset(self) -> None:
        with self._mu:
            self._ring.clear()


# Bounds for span trees GRAFTED from peer RPC responses: a remote
# subtree bypasses the local add_child cap (dicts pass through
# to_dict verbatim), and the RPC response body is not covered by the
# request HMAC — so prune depth/fan-out/node count at ingestion.
MAX_REMOTE_DEPTH = 8
MAX_REMOTE_NODES = 256

_SPAN_KEYS = ("traceId", "spanId", "parentId", "name", "start",
              "durationMs", "tags", "droppedChildren")


def sanitize_remote(node, _depth: int = 0,
                    _budget: list | None = None) -> dict | None:
    """Prune an untrusted remote span dict to the same bounds local
    trees obey; None when it isn't a dict or the node budget is spent."""
    if not isinstance(node, dict):
        return None
    if _budget is None:
        _budget = [MAX_REMOTE_NODES]
    if _budget[0] <= 0:
        return None
    _budget[0] -= 1
    out = {k: node[k] for k in _SPAN_KEYS if k in node}
    if isinstance(out.get("name"), str):
        out["name"] = out["name"][:128]
    tags = out.get("tags")
    if isinstance(tags, dict):
        out["tags"] = {
            str(k)[:64]: (v if isinstance(v, (int, float, bool))
                          else str(v)[:256])
            for k, v in list(tags.items())[:16]}
    elif "tags" in out:
        del out["tags"]
    events = node.get("events")
    if isinstance(events, list):
        kept_ev = []
        for e in events[:MAX_EVENTS]:
            if isinstance(e, dict):
                kept_ev.append({
                    str(k)[:64]: (v if isinstance(v, (int, float, bool))
                                  else str(v)[:256])
                    for k, v in list(e.items())[:8]})
        if kept_ev:
            out["events"] = kept_ev
    kids = node.get("children")
    if isinstance(kids, list) and _depth < MAX_REMOTE_DEPTH:
        kept = []
        for c in kids[:MAX_CHILDREN]:
            sc = sanitize_remote(c, _depth + 1, _budget)
            if sc is not None:
                kept.append(sc)
        if kept:
            out["children"] = kept
        if len(kids) > MAX_CHILDREN:
            out["droppedChildren"] = (out.get("droppedChildren", 0)
                                      + len(kids) - MAX_CHILDREN)
    return out


# The process-wide tracer every layer shares.
TRACER = Tracer()


def current_span() -> Span | None:
    return _current.get()
