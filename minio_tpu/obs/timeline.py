"""Cluster timeline: a fixed-memory ring of 1-second samples over the
key serving series.

Everything metrics-v2 exports is a cumulative counter — perfect for
Prometheus, useless for the question the SSD-array EC study
(arXiv:1709.05365) shows matters most: WHERE the bottleneck is *right
now*, because it migrates between codec, disk and queueing as load
shifts.  This module adds the time dimension in-process: a sampler
thread deltas the registry once per ``period_s`` into a bounded ring
(>= 15 min retention at fixed memory), so ``/minio-tpu/v2/timeline``
(node) and its cluster fan-in always have history to serve — no
external scraper required, and `tools/mtpu_top.py` renders it live.

Per sample: per-class QPS / inflight / shed, rx/tx bytes, kernel
bytes + GiB/s per dispatch backend (obs/kernprof.py), admission queue
depth, drive-state census, hedge fires, MRF depth, kernel backend
states — and an EXEMPLAR: the trace id of the window's worst request
(and worst kernel dispatch), so a spike in the timeline links straight
to its PR-1 trace tree / PR-4 slowlog entry instead of dead-ending in
an aggregate.

Counter-reset discipline: a delta that goes negative (registry reset,
process restart behind a proxy) re-bases on the current value instead
of emitting garbage negatives.

The sampler tick also drives kernprof's rate-limited recovery probes
and the watchdog's alert evaluation (obs/watchdog.py) — one thread
owns all periodic observability work.  Samples additionally carry the
watchdog's burn-rate numerators (per-class 5xx/slowlog deltas), the
count of counter re-bases this window, and the alert census.
"""

from __future__ import annotations

import threading
import time
from collections import deque

# Bounds the ring regardless of config: retention/period is clamped so
# a bad KV write can never grow the ring past ~10 hours of seconds.
MIN_PERIOD_S = 0.05
MAX_SAMPLES = 36000
DEFAULT_PERIOD_S = 1.0
DEFAULT_RETENTION_S = 15 * 60.0

_CLASSES = ("read", "write", "list", "admin", "select")


def _series_sum(metric: dict, by: str | None = None,
                field: str = "value") -> dict | float:
    """Sum a snapshot metric's series — total, or keyed by one label."""
    if by is None:
        return sum(s.get(field, 0) or 0 for s in metric.get("series", []))
    out: dict = {}
    for s in metric.get("series", []):
        key = s.get("labels", {}).get(by, "")
        out[key] = out.get(key, 0) + (s.get(field, 0) or 0)
    return out


class Timeline:
    """Process-wide sample ring + sampler thread (``TIMELINE``)."""

    def __init__(self, period_s: float = DEFAULT_PERIOD_S,
                 retention_s: float = DEFAULT_RETENTION_S):
        # Hot-path kill switch for the request/kernel exemplar hooks
        # (the paired on/off overhead measurement toggles this).
        self.enabled = True
        self._mu = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop_ev = threading.Event()
        self._refs = 0
        self._prev: dict | None = None
        self._worst_req: tuple | None = None   # (ms, trace_id, class)
        self._worst_kern: tuple | None = None  # (ms, trace_id, k, b)
        self.configure(period_s, retention_s)

    # -- config ---------------------------------------------------------

    def configure(self, period_s: float, retention_s: float) -> None:
        """(Re)shape the ring; existing samples are kept up to the new
        capacity.  Live-reloadable via config-KV ``obs
        timeline_sample`` / ``timeline_retention``."""
        period_s = max(float(period_s), MIN_PERIOD_S)
        retention_s = max(float(retention_s), period_s)
        cap = min(int(round(retention_s / period_s)) + 2, MAX_SAMPLES)
        with self._mu:
            old = list(getattr(self, "_ring", ()))
            self.period_s = period_s
            self.retention_s = retention_s
            self._ring: deque = deque(old[-cap:], maxlen=cap)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Refcounted: every running server holds one reference; the
        sampler thread stops when the last one stops."""
        with self._mu:
            self._refs += 1
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop_ev = threading.Event()
            # mtpu-lint: disable=R1 -- process-wide sampler daemon; it serves no single request's context
            self._thread = threading.Thread(
                target=self._run, args=(self._stop_ev,), daemon=True,
                name="timeline-sampler")
            self._thread.start()

    def stop(self) -> None:
        with self._mu:
            self._refs = max(0, self._refs - 1)
            if self._refs > 0:
                return
            t, self._thread = self._thread, None
            self._stop_ev.set()
        if t is not None:
            t.join(timeout=5)

    @property
    def active(self) -> bool:
        return self._thread is not None

    def _run(self, stop_ev: threading.Event) -> None:
        # The thread owns the SPECIFIC event it was started with:
        # re-reading self._stop_ev would race a stop()/start() pair —
        # a new start() swaps in a fresh event before the old thread
        # observed the set of its own, leaving two samplers ticking
        # the same ring (half-period deltas) forever.
        probe_thread: threading.Thread | None = None
        while not stop_ev.wait(self.period_s):
            try:
                self.tick()
                # Watchdog evaluation rides the sampler tick — the
                # rules read their burn windows from the ring this
                # tick just appended to, so alerting needs no thread
                # of its own and stops exactly when sampling stops.
                from .watchdog import WATCHDOG
                WATCHDOG.tick()
                # Recovery probes ride the sampler tick but run on
                # their own short-lived thread: a native probe can
                # REBUILD the C++ lib (g++, up to ~2 min) and xla/
                # device probes pay jit compiles — the sample ring
                # must keep filling exactly when a backend incident
                # is in progress. maybe_probe itself stays sync for
                # tests; rate limiting bounds thread churn.
                if probe_thread is None or not probe_thread.is_alive():
                    from .kernprof import KERNPROF
                    # mtpu-lint: disable=R1 -- process-wide probe worker; it serves no single request's context
                    probe_thread = threading.Thread(
                        target=KERNPROF.maybe_probe, daemon=True,
                        name="kernprof-probe")
                    probe_thread.start()
            except Exception:  # noqa: BLE001 - sampler must survive
                from ..logger import Logger
                Logger.get().log_once("timeline: tick failed",
                                      "timeline")

    # -- exemplars ------------------------------------------------------

    def note_request(self, api_class: str, duration_ms: float,
                     trace_id: str) -> None:
        """Candidate worst-request exemplar for the current window
        (called by the S3 front end per request; cheap compare+swap
        under the lock)."""
        if not self.enabled:
            return
        with self._mu:
            if self._worst_req is None or \
                    duration_ms > self._worst_req[0]:
                self._worst_req = (duration_ms, trace_id, api_class)

    def note_kernel(self, kernel: str, backend: str, wall_ms: float,
                    trace_id: str | None = None) -> None:
        if not self.enabled:
            return
        if trace_id is None:
            from .span import current_span
            span = current_span()
            trace_id = span.trace_id if span is not None else ""
        with self._mu:
            if self._worst_kern is None or \
                    wall_ms > self._worst_kern[0]:
                self._worst_kern = (wall_ms, trace_id, kernel, backend)

    # -- sampling -------------------------------------------------------

    def _read_raw(self) -> dict:
        """Raw cumulative values this tick deltas.  Split out so tests
        can feed synthetic counters (reset behavior, merge shapes)."""
        from .drivemon import DRIVEMON
        from .kernprof import KERNPROF
        from .loopmon import LOOPMON
        from .metrics2 import METRICS2
        snap = METRICS2.snapshot()

        def m(name: str) -> dict:
            return snap.get(name, {})

        hedge = _series_sum(m("minio_tpu_v2_hedged_reads_total"),
                            by="result")
        suspect, faulty = DRIVEMON.counts()
        from .watchdog import WATCHDOG
        firing, pending, worst_rule = WATCHDOG.counts()
        return {
            "qps": _series_sum(m("minio_tpu_v2_qos_admission_wait_ms"),
                               by="class", field="count"),
            "shed": _series_sum(m("minio_tpu_v2_qos_shed_total"),
                                by="class"),
            # Watchdog numerators: 5xx + slowlog captures per class
            # (shed above completes the trio of burn-rate signals).
            "errors": _series_sum(
                m("minio_tpu_v2_api_class_errors_total"), by="class"),
            "slow": _series_sum(m("minio_tpu_v2_slow_requests_total"),
                                by="class"),
            # Alert census at sample time (gauge-like, not delta'd):
            # rendered by mtpu_top and summed by the cluster merge.
            "alerts": {"firing": firing, "pending": pending,
                       "worst": worst_rule},
            "inflight": _series_sum(
                m("minio_tpu_v2_qos_admission_inflight"), by="class"),
            "queueDepth": _series_sum(
                m("minio_tpu_v2_qos_admission_queue_depth")),
            "rx": _series_sum(m("minio_tpu_v2_api_rx_bytes_total")),
            "tx": _series_sum(m("minio_tpu_v2_api_tx_bytes_total")),
            "kernelBytes": _series_sum(
                m("minio_tpu_v2_kernel_backend_bytes_total"),
                by="backend"),
            "hedgeFired": hedge.get("fired", 0),
            "cacheHits": _series_sum(m("minio_tpu_v2_cache_hits_total")),
            "cacheMisses": _series_sum(
                m("minio_tpu_v2_cache_misses_total")),
            "cacheFills": _series_sum(
                m("minio_tpu_v2_cache_fills_total")),
            "cacheBytes": _series_sum(m("minio_tpu_v2_cache_bytes")),
            # Connection plane (s3/asyncserver.py): open keep-alive
            # sockets + accept backlog are gauges, parse rejections a
            # counter the tick deltas.
            "conns": _series_sum(m("minio_tpu_v2_open_connections")),
            "acceptQueue": _series_sum(
                m("minio_tpu_v2_accept_queue_depth")),
            "parseErrors": _series_sum(
                m("minio_tpu_v2_conn_parse_errors_total")),
            # Internal RPC fabric (rpc/aio.py): client-side peer calls
            # in flight paired with the PROCESS thread count — flat
            # threads under a fan-out spike is the async fabric's
            # zero-thread-per-call claim, visible per node.
            "rpcInflight": _series_sum(m("minio_tpu_v2_rpc_inflight")),
            "threads": threading.active_count(),
            # Event-loop health census (obs/loopmon.py): per-loop EWMA
            # scheduling lag + pending tasks, and the flat thread
            # count split per executor pool — a stalled loop and an
            # exhausted pool must be distinguishable on the timeline.
            "loopLag": LOOPMON.lag_census(),
            "loopTasks": LOOPMON.task_census(),
            "poolThreads": _series_sum(
                m("minio_tpu_v2_pool_threads"), by="pool"),
            "poolBusy": _series_sum(
                m("minio_tpu_v2_pool_threads_busy"), by="pool"),
            # Analytics scan volume (s3select): decoded bytes +
            # queries, delta'd into a select GiB/s row in mtpu_top.
            "selectProcessed": _series_sum(
                m("minio_tpu_v2_select_processed_bytes_total")),
            "selectRequests": _series_sum(
                m("minio_tpu_v2_select_requests_total")),
            "mrfDepth": _series_sum(m("minio_tpu_v2_mrf_queue_depth")),
            # Durable-queue twin of mrfDepth: live entries in the
            # per-set MRF journal (watchdog recovery_backlog watches
            # its growth).
            "mrfJournal": _series_sum(
                m("minio_tpu_v2_mrf_journal_backlog")),
            "drives": {"suspect": suspect, "faulty": faulty,
                       "quarantined":
                           len(DRIVEMON.quarantined_endpoints())},
            "backendState": KERNPROF.states(),
            "codecPlan": _codec_plan(),
            # Attribution census (obs/usage.py): the fast window's top
            # bucket per QoS class — gauge-like, not delta'd, so a
            # timeline spike names WHO drove it without a /usage call.
            "usageTop": _usage_top(),
        }

    def tick(self, now: float | None = None) -> dict | None:
        """Take one sample (sampler thread; tests call directly).
        The first tick only establishes the baseline."""
        now = time.time() if now is None else now
        raw = self._read_raw()
        # The read time rides in the baseline so rate math uses the
        # REAL inter-tick interval, not the nominal period (the
        # sampler drifts under load; GiB/s must not).
        raw["_t"] = now
        with self._mu:
            prev, self._prev = self._prev, raw
            worst_req, self._worst_req = self._worst_req, None
            worst_kern, self._worst_kern = self._worst_kern, None
            if prev is None:
                return None
            # Counter delta, reset-safe: a counter that went DOWN was
            # reset — re-base on its current value, never emit a
            # negative. Re-bases are COUNTED into the sample: a storm
            # of them is itself a signal (watchdog counter_resets).
            resets = 0

            def _d(cur: float, prev_v: float) -> float:
                nonlocal resets
                d = cur - prev_v
                if d < 0:
                    resets += 1
                    return cur
                return d

            dt = max(now - prev.get("_t", now - self.period_s), 1e-9)
            sample: dict = {
                "t": round(now, 3),
                # Real inter-tick interval the deltas cover: rate
                # consumers (mtpu_top) must divide by THIS, not the
                # nominal period — the sampler drifts under load,
                # which is exactly when an operator is watching.
                "dt": round(dt, 3),
                "qps": {c: _d(raw["qps"].get(c, 0),
                              prev["qps"].get(c, 0))
                        for c in _CLASSES},
                "shed": {c: _d(raw["shed"].get(c, 0),
                               prev["shed"].get(c, 0))
                         for c in _CLASSES},
                # Burn-rate numerators (watchdog): 5xx + slowlog
                # captures, same per-class delta discipline as qps.
                "errors": {c: _d((raw.get("errors") or {}).get(c, 0),
                                 (prev.get("errors") or {}).get(c, 0))
                           for c in _CLASSES},
                "slow": {c: _d((raw.get("slow") or {}).get(c, 0),
                               (prev.get("slow") or {}).get(c, 0))
                         for c in _CLASSES},
                "inflight": {c: raw["inflight"].get(c, 0)
                             for c in _CLASSES},
                "queueDepth": raw["queueDepth"],
                "rx": _d(raw["rx"], prev["rx"]),
                "tx": _d(raw["tx"], prev["tx"]),
                "kernelBytes": {
                    b: _d(v, prev["kernelBytes"].get(b, 0))
                    for b, v in raw["kernelBytes"].items()},
                "hedgeFired": _d(raw["hedgeFired"],
                                 prev["hedgeFired"]),
                # Cache row (hot-object serving tier): hit/miss/fill
                # deltas + resident bytes, rendered by mtpu_top.
                "cacheHits": _d(raw.get("cacheHits", 0),
                                prev.get("cacheHits", 0)),
                "cacheMisses": _d(raw.get("cacheMisses", 0),
                                  prev.get("cacheMisses", 0)),
                "cacheFills": _d(raw.get("cacheFills", 0),
                                 prev.get("cacheFills", 0)),
                "cacheBytes": raw.get("cacheBytes", 0),
                "conns": raw.get("conns", 0),
                "acceptQueue": raw.get("acceptQueue", 0),
                "parseErrors": _d(raw.get("parseErrors", 0),
                                  prev.get("parseErrors", 0)),
                "rpcInflight": raw.get("rpcInflight", 0),
                "threads": raw.get("threads", 0),
                # Event-loop / pool census (gauge-like, not delta'd):
                # per-loop EWMA lag ms + pending tasks, per-pool
                # thread size and busy count.
                "loopLag": dict(raw.get("loopLag") or {}),
                "loopTasks": dict(raw.get("loopTasks") or {}),
                "poolThreads": dict(raw.get("poolThreads") or {}),
                "poolBusy": dict(raw.get("poolBusy") or {}),
                "selectProcessed": _d(raw.get("selectProcessed", 0),
                                      prev.get("selectProcessed", 0)),
                "selectRequests": _d(raw.get("selectRequests", 0),
                                     prev.get("selectRequests", 0)),
                "mrfDepth": raw["mrfDepth"],
                "mrfJournal": raw.get("mrfJournal", 0),
                "drives": dict(raw["drives"]),
                "backendState": dict(raw["backendState"]),
                # Codec dispatch plan census (gauge-like): flat
                # {"kernel/bucket": lane index} from ops/autotune.py,
                # so a plan flip is visible in the same ring as the
                # backend-state flip that usually caused it.
                "codecPlan": dict(raw.get("codecPlan") or {}),
                # Attribution census (gauge-like, like alerts): the
                # fast window's top bucket per class at sample time.
                "usageTop": dict(raw.get("usageTop") or {}),
                # Alert census at sample time (the watchdog evaluates
                # AFTER each tick, so this reflects the previous
                # evaluation — one period of honest lag).
                "alerts": dict(raw.get("alerts")
                               or {"firing": 0, "pending": 0,
                                   "worst": ""}),
                "nodes": 1,
            }
            sample["resets"] = resets
            sample["kernelGiBs"] = {
                b: round(v / dt / (1 << 30), 6)
                for b, v in sample["kernelBytes"].items()}
            if worst_req is not None:
                sample["worstRequest"] = {
                    "durationMs": round(worst_req[0], 3),
                    "traceId": worst_req[1], "class": worst_req[2]}
            if worst_kern is not None:
                sample["worstKernel"] = {
                    "wallMs": round(worst_kern[0], 3),
                    "traceId": worst_kern[1], "kernel": worst_kern[2],
                    "backend": worst_kern[3]}
            self._ring.append(sample)
            return sample

    # -- views ----------------------------------------------------------

    def samples(self, n: int | None = None,
                since: float | None = None) -> list[dict]:
        with self._mu:
            items = list(self._ring)
        return slice_samples(items, n=n, since=since)

    def snapshot(self, n: int | None = None,
                 since: float | None = None) -> dict:
        return {"periodS": self.period_s,
                "retentionS": self.retention_s,
                "samples": self.samples(n=n, since=since)}

    def reset(self) -> None:
        with self._mu:
            self._ring.clear()
            self._prev = None
            self._worst_req = None
            self._worst_kern = None


def slice_samples(items: list[dict], n: int | None = None,
                  since: float | None = None) -> list[dict]:
    """The one ?n=/?since= slicing semantic, shared by the node ring
    and the cluster merge.  n=0 means none: a bare [-0:] slice would
    be the WHOLE ring, the exact opposite of what ?n=0 asks for."""
    if since is not None:
        items = [s for s in items if s.get("t", 0) > since]
    if n is not None:
        items = items[-n:] if n > 0 else []
    return items


def _codec_plan() -> dict[str, int]:
    from ..ops.autotune import AUTOTUNE
    return AUTOTUNE.plan_indices()


def _usage_top() -> dict:
    from .usage import USAGE
    return USAGE.class_top_shares()


def _bucket(t: float, period_s: float) -> float:
    return round(int(t / period_s) * period_s, 3)


def _collapse_node(snap: dict, period_s: float) -> list[dict]:
    """One node's samples collapsed to at most one per merge bucket.

    A node sampling FASTER than the merge period (per-node live-reload
    of ``obs timeline_sample``) would otherwise land several samples in
    one bucket and be summed as several nodes — inflating `nodes`,
    gauges, and GiB/s by the period ratio.  Within a bucket: counters
    (qps/shed/rx/tx/kernel bytes/hedges) sum, gauges (inflight, queue,
    MRF, drive census) take the bucket's LATEST sample, backend states
    take the worst seen, exemplars the max, and GiB/s is recomputed
    from the summed bytes over the merge period."""
    groups: dict[float, list[dict]] = {}
    for s in snap.get("samples", []):
        groups.setdefault(_bucket(s.get("t", 0.0), period_s),
                          []).append(s)
    out: list[dict] = []
    for key in sorted(groups):
        group = sorted(groups[key], key=lambda s: s.get("t", 0.0))
        if len(group) == 1:
            out.append(group[0])
            continue
        last = group[-1]
        c: dict = {
            "t": key, "nodes": 1,
            "qps": {}, "shed": {}, "errors": {}, "slow": {},
            "kernelBytes": {},
            "inflight": dict(last.get("inflight") or {}),
            "queueDepth": last.get("queueDepth", 0),
            "rx": 0, "tx": 0, "hedgeFired": 0, "resets": 0,
            "selectProcessed": 0, "selectRequests": 0,
            "cacheHits": 0, "cacheMisses": 0, "cacheFills": 0,
            "cacheBytes": last.get("cacheBytes", 0),
            "conns": last.get("conns", 0),
            "acceptQueue": last.get("acceptQueue", 0),
            "parseErrors": 0,
            "rpcInflight": last.get("rpcInflight", 0),
            "threads": last.get("threads", 0),
            # Census like alerts: the bucket's latest loop/pool state.
            "loopLag": dict(last.get("loopLag") or {}),
            "loopTasks": dict(last.get("loopTasks") or {}),
            "poolThreads": dict(last.get("poolThreads") or {}),
            "poolBusy": dict(last.get("poolBusy") or {}),
            "mrfDepth": last.get("mrfDepth", 0),
            "mrfJournal": last.get("mrfJournal", 0),
            "drives": dict(last.get("drives") or {}),
            # Census, not a counter: the node's LATEST alert state.
            "alerts": dict(last.get("alerts") or {}),
            # Census like alerts: the bucket's latest codec plan.
            "codecPlan": dict(last.get("codecPlan") or {}),
            # Census: the bucket's latest attribution shares.
            "usageTop": dict(last.get("usageTop") or {}),
            "backendState": {},
        }
        for s in group:
            for fld in ("qps", "shed", "errors", "slow",
                        "kernelBytes"):
                for k, v in (s.get(fld) or {}).items():
                    c[fld][k] = c[fld].get(k, 0) + v
            for fld in ("rx", "tx", "hedgeFired", "cacheHits",
                        "cacheMisses", "cacheFills", "resets",
                        "parseErrors", "selectProcessed",
                        "selectRequests"):
                c[fld] += s.get(fld, 0)
            for k, v in (s.get("backendState") or {}).items():
                c["backendState"][k] = max(c["backendState"].get(k, 0),
                                           v)
            for wf, metric in (("worstRequest", "durationMs"),
                               ("worstKernel", "wallMs")):
                w = s.get(wf)
                if w and w.get(metric, 0) > c.get(wf, {}).get(
                        metric, -1):
                    c[wf] = dict(w)
        c["kernelGiBs"] = {k: round(v / period_s / (1 << 30), 6)
                           for k, v in c["kernelBytes"].items()}
        out.append(c)
    return out


def merge_timelines(snapshots: list[dict],
                    period_s: float | None = None) -> dict:
    """Merge node timeline snapshots into one cluster view.

    Samples align on floor(t / period) buckets, so a LAGGING peer
    (clock a little behind, or a scrape that raced its sampler) still
    lands its samples in the right windows; buckets only some nodes
    reported carry their true ``nodes`` count rather than faking a
    cluster-wide zero.  Sums: qps/shed/rx/tx/kernel bytes/hedges/drive
    census; gauges (inflight, queue, MRF) add across nodes; backend
    states take the per-backend WORST (a cluster where any node's
    device is down should say so); the worst-request exemplar is the
    max across nodes — the whole point of carrying trace ids."""
    if period_s is None:
        period_s = max([s.get("periodS", DEFAULT_PERIOD_S)
                        for s in snapshots] or [DEFAULT_PERIOD_S])
    buckets: dict[float, dict] = {}
    for snap in snapshots:
        for s in _collapse_node(snap, period_s):
            key = _bucket(s.get("t", 0.0), period_s)
            cur = buckets.get(key)
            if cur is None:
                cur = buckets[key] = {
                    "t": key, "nodes": 0,
                    "qps": {}, "shed": {}, "errors": {}, "slow": {},
                    "inflight": {},
                    "queueDepth": 0, "rx": 0, "tx": 0,
                    "kernelBytes": {}, "kernelGiBs": {},
                    "hedgeFired": 0, "mrfDepth": 0, "mrfJournal": 0,
                    "conns": 0, "acceptQueue": 0, "parseErrors": 0,
                    "rpcInflight": 0, "threads": 0,
                    "loopLag": {}, "loopTasks": {},
                    "poolThreads": {}, "poolBusy": {},
                    "resets": 0,
                    "selectProcessed": 0, "selectRequests": 0,
                    "cacheHits": 0, "cacheMisses": 0,
                    "cacheFills": 0, "cacheBytes": 0,
                    "drives": {"suspect": 0, "faulty": 0,
                               "quarantined": 0},
                    "alerts": {"firing": 0, "pending": 0,
                               "worst": ""},
                    "codecPlan": {},
                    "usageTop": {},
                    "backendState": {},
                }
            cur["nodes"] += int(s.get("nodes", 1))
            for fld in ("qps", "shed", "errors", "slow", "inflight",
                        "kernelBytes", "kernelGiBs"):
                for k, v in (s.get(fld) or {}).items():
                    cur[fld][k] = cur[fld].get(k, 0) + v
            for fld in ("queueDepth", "rx", "tx", "hedgeFired",
                        "mrfDepth", "mrfJournal", "cacheHits",
                        "cacheMisses", "cacheFills", "cacheBytes",
                        "conns", "acceptQueue", "parseErrors",
                        "rpcInflight", "threads",
                        "resets", "selectProcessed",
                        "selectRequests"):
                cur[fld] += s.get(fld, 0)
            for k, v in (s.get("drives") or {}).items():
                cur["drives"][k] = cur["drives"].get(k, 0) + v
            al = s.get("alerts") or {}
            cal = cur["alerts"]
            cal["firing"] += al.get("firing", 0)
            cal["pending"] += al.get("pending", 0)
            # Worst rule: keep the first firing node's headline (any
            # one is a valid entry point into /v2/alerts/cluster).
            if al.get("worst") and (not cal["worst"]
                                    or al.get("firing", 0) > 0):
                cal["worst"] = al["worst"]
            for k, v in (s.get("backendState") or {}).items():
                cur["backendState"][k] = max(
                    cur["backendState"].get(k, 0), v)
            # Loop names are per node but may collide across nodes
            # (every node has an "rpc" loop): lag takes the WORST
            # node's EWMA (the cluster row answers "is any loop
            # lagging"), tasks/pool counts sum like threads.
            for k, v in (s.get("loopLag") or {}).items():
                cur["loopLag"][k] = max(cur["loopLag"].get(k, 0), v)
            for fld in ("loopTasks", "poolThreads", "poolBusy"):
                for k, v in (s.get(fld) or {}).items():
                    cur[fld][k] = cur[fld].get(k, 0) + v
            # Per-(kernel/bucket) WORST lane across nodes (highest
            # index = furthest from the device), same rule as backend
            # states: a cluster where any node fell back should say so.
            for k, v in (s.get("codecPlan") or {}).items():
                cur["codecPlan"][k] = max(cur["codecPlan"].get(k, 0),
                                          v)
            # Per-class WORST concentration across nodes: the cluster
            # row names the bucket with the highest single-node share
            # (an exact cross-node merge lives on /usage/cluster; the
            # timeline census is the headline, like alerts.worst).
            for cls, top in (s.get("usageTop") or {}).items():
                cur_top = cur["usageTop"].get(cls)
                if cur_top is None or top.get("share", 0) > \
                        cur_top.get("share", 0):
                    cur["usageTop"][cls] = dict(top)
            w = s.get("worstRequest")
            if w and w.get("durationMs", 0) > cur.get(
                    "worstRequest", {}).get("durationMs", -1):
                cur["worstRequest"] = dict(w)
            wk = s.get("worstKernel")
            if wk and wk.get("wallMs", 0) > cur.get(
                    "worstKernel", {}).get("wallMs", -1):
                cur["worstKernel"] = dict(wk)
    return {"periodS": period_s,
            "nodes": len(snapshots),
            "samples": [buckets[k] for k in sorted(buckets)]}


# The process-wide timeline every sink shares.
TIMELINE = Timeline()
