"""Tenant & workload attribution: who is the traffic?

The signal planes built so far answer *what* is slow (spans, slowlog
blame), *which component* is failing (drivemon, kernprof) and *when*
SLOs burn (watchdog) — but nothing attributes load to a bucket, an
access key, an object key or a client.  That is the first question an
operator asks when a shared deployment browns out, and the reference
ships exactly this surface (the data-usage census, ``mc admin top``,
per-bucket bandwidth).  The workload reality motivating it is the same
Zipfian skew behind the ``hot_get`` bench and the hot-data placement
literature (Pertin et al., arXiv:1504.07038): a handful of tenants and
keys carry most of the bytes, and the plane that names them must cost
O(K), not O(keyspace).

Two tiers, both fixed-memory, fed from ``S3Server._finish_request``
(both front doors share that core):

- **Exact rolling accounts** per bucket and per access key over a
  fast and a slow window (requests, rx/tx bytes, error and shed
  counts), kept in a ring of coarse time slots.  Cardinality is
  bounded: past ``cardinality_cap`` distinct names per slot, new names
  fold into ``_other`` and the fold is counted — the same guard the
  metrics2 registry applies to the ``usage_*`` label values.

- **Space-bounded heavy-hitter sketches** (SpaceSaving top-K with a
  count-min backing on deterministic seeds) over object keys and
  client addresses, one per QoS class, so "which 10 keys are 80% of
  GET traffic" is answerable at O(K) memory regardless of keyspace.
  Sketches MERGE across peers: absent keys substitute the peer's
  count-min estimate (clamped by its SpaceSaving floor), so the merged
  count error stays <= N/K.

Surfaces: ``/minio-tpu/v2/usage`` (node) + ``/usage/cluster`` (peer
RPC fan-in, honest node counts), admin ``/top`` (full detail, joined
with the crawler's stored-bytes census and worst-request trace-id
exemplars that resolve in the PR-4 slowlog), ``usage_*`` metrics2
series, per-class top-bucket shares in every timeline sample, a
``tenants:`` row in ``tools/mtpu_top.py``, and the watchdog's
``noisy_neighbor`` built-in rule (obs/watchdog.py), which turns
attribution into the input the QoS caps act on.

Unauthenticated surfaces redact access keys and client addresses the
way drivemon redacts drive endpoints; admin ``/top`` is root-only and
serves them whole.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque

# Account array layout (one list per bucket/tenant per slot).
_REQ, _RX, _TX, _ERR, _SHED = range(5)

OTHER = "_other"

# Count-min geometry: depth rows sliced out of ONE blake2b digest per
# key, so a sketch offer costs a single short hash.  Width is a power
# of two (the digest slices index by mask).
CM_DEPTH = 4
CM_WIDTH = 512

def claimed_access_key(auth_header: str,
                       params: dict | None = None) -> str:
    """The access key the request CLAIMS (SigV4 `Credential=AK/...`,
    legacy `AWS AK:sig`, or a presigned URL's `X-Amz-Credential`
    query parameter), for attribution of requests that never reached
    authentication — admission sheds happen before SigV4
    verification, and a noisy tenant's sheds are exactly the signal
    that must not degrade to anonymous.  Attribution-only: nothing
    trusts this value, and the cardinality cap bounds what a spoofer
    can pollute."""
    if auth_header:
        i = auth_header.find("Credential=")
        if i >= 0:
            return auth_header[i + len("Credential="):].split("/",
                                                              1)[0]
        if auth_header.startswith("AWS "):
            return auth_header[4:].split(":", 1)[0]
    if params:
        cred = params.get("X-Amz-Credential", "")
        if cred:
            return cred.split("/", 1)[0]
    return ""


def _digest_indices(key: str) -> list[int]:
    """CM_DEPTH deterministic row indices for one key — same on every
    node (seedless digest), which is what makes sketches merge-able."""
    d = hashlib.blake2b(key.encode("utf-8", "replace"),
                        digest_size=2 * CM_DEPTH).digest()
    return [int.from_bytes(d[2 * i:2 * i + 2], "big") % CM_WIDTH
            for i in range(CM_DEPTH)]


class TopKSketch:
    """SpaceSaving top-K with a count-min backing.

    SpaceSaving keeps exactly ``k`` counters; the canonical guarantees
    hold per node: every key with true count > N/k is tracked, and a
    tracked key's count overestimates its true count by at most its
    recorded ``err`` (<= N/k).  The count-min rows (deterministic
    seeds, element-wise merge-able) refine CROSS-NODE estimates for
    keys one node tracked and another did not."""

    def __init__(self, k: int = 10):
        self.k = max(1, int(k))
        self.total = 0
        self._counters: dict[str, list] = {}   # key -> [count, err]
        self._cm = [[0] * CM_WIDTH for _ in range(CM_DEPTH)]

    def offer(self, key: str, weight: int = 1) -> None:
        self.total += weight
        for row, idx in zip(self._cm, _digest_indices(key)):
            row[idx] += weight
        c = self._counters.get(key)
        if c is not None:
            c[0] += weight
            return
        if len(self._counters) < self.k:
            self._counters[key] = [weight, 0]
            return
        # Evict the minimum counter; the newcomer inherits its count
        # as both floor and error (the SpaceSaving replacement rule).
        mk = min(self._counters, key=lambda x: self._counters[x][0])
        mc = self._counters.pop(mk)[0]
        self._counters[key] = [mc + weight, mc]

    def cm_estimate(self, key: str) -> int:
        return min(row[idx] for row, idx
                   in zip(self._cm, _digest_indices(key)))

    def min_count(self) -> int:
        """The SpaceSaving floor: an UNtracked key's true count cannot
        exceed this (else it would have displaced the minimum)."""
        if len(self._counters) < self.k:
            return 0
        return min(c[0] for c in self._counters.values())

    def top(self, n: int | None = None) -> list[dict]:
        rows = sorted(((key, c[0], c[1])
                       for key, c in self._counters.items()),
                      key=lambda r: (-r[1], r[0]))
        total = self.total or 1
        return [{"key": key, "count": count, "err": err,
                 "share": round(count / total, 4)}
                for key, count, err in rows[:n or self.k]]

    def snapshot(self) -> dict:
        return {"k": self.k, "total": self.total,
                "counters": self.top(self.k),
                "cm": [list(row) for row in self._cm]}


def merge_topk(snapshots: list[dict], k: int | None = None) -> dict:
    """Merge per-node sketch snapshots into one cluster top-K.

    Candidates are the union of every node's tracked keys.  A node
    that tracked the key contributes its SpaceSaving count (err rides
    along); a node that did not contributes min(count-min estimate,
    SpaceSaving floor) — both are overestimates of the true count and
    the floor is <= N_node/k, so the merged count error stays
    <= sum(N_node)/k = N/k."""
    snaps = [s for s in snapshots if isinstance(s, dict)]
    if not snaps:
        return {"k": k or 0, "total": 0, "counters": [], "cm": []}
    k = k or max(s.get("k", 0) for s in snaps) or 1
    total = sum(s.get("total", 0) for s in snaps)
    candidates: set[str] = set()
    for s in snaps:
        candidates.update(c["key"] for c in s.get("counters", []))
    merged: list[tuple[str, int, int]] = []
    for key in candidates:
        count = err = 0
        idx = _digest_indices(key)
        for s in snaps:
            tracked = {c["key"]: c for c in s.get("counters", [])}
            hit = tracked.get(key)
            if hit is not None:
                count += hit["count"]
                err += hit.get("err", 0)
                continue
            if len(tracked) < s.get("k", 1):
                # A not-full SpaceSaving sketch tracks EVERY key the
                # node saw: absent means true count 0 — substituting
                # the (collision-inflated) cm estimate here would add
                # phantom counts and break the <= N/k bound.
                continue
            floor = min(c["count"] for c in tracked.values())
            cm = s.get("cm") or []
            if cm:
                floor = min(floor,
                            min(row[i] for row, i in zip(cm, idx)))
            count += floor
            err += floor
        merged.append((key, count, err))
    merged.sort(key=lambda r: (-r[1], r[0]))
    out_total = total or 1
    cm_rows: list[list[int]] = []
    for s in snaps:
        for i, row in enumerate(s.get("cm") or []):
            if i >= len(cm_rows):
                cm_rows.append(list(row))
            else:
                cm_rows[i] = [a + b for a, b in zip(cm_rows[i], row)]
    return {"k": k, "total": total,
            "counters": [{"key": key, "count": count, "err": err,
                          "share": round(count / out_total, 4)}
                         for key, count, err in merged[:k]],
            "cm": cm_rows}


class _Slot:
    """One coarse time window of exact accounts."""

    __slots__ = ("t0", "buckets", "tenants", "classes", "worst")

    def __init__(self, t0: float):
        self.t0 = t0
        self.buckets: dict[str, list] = {}
        self.tenants: dict[str, list] = {}
        # class -> prefixed name ("b:<bucket>" / "t:<tenant>") ->
        # [admitted, shed] — the noisy-neighbor numerators.
        self.classes: dict[str, dict[str, list]] = {}
        # bucket -> (duration_ms, trace_id): the window's worst
        # request per bucket, admin /top's slowlog join key.
        self.worst: dict[str, tuple] = {}


class UsageAccountant:
    """Process-wide attribution plane (singleton ``USAGE``)."""

    def __init__(self):
        self.enabled = True
        self._mu = threading.Lock()
        self.top_k = 10
        self.cardinality_cap = 64
        self.fast_s = 60.0
        self.slow_s = 900.0
        # noisy_neighbor thresholds (read by the watchdog rule).
        self.noisy_share = 0.5
        self.noisy_min_requests = 20
        self.folded_total = 0
        self._gran = 5.0
        self._slots: deque = deque()
        self._sketches: dict[tuple[str, str], TopKSketch] = {}
        self._totals = [0, 0, 0, 0, 0]

    # -- configuration (config-KV ``usage`` apply hook) -----------------

    def configure(self, enable: bool = True, top_k: int = 10,
                  cardinality_cap: int = 64, fast_s: float = 60.0,
                  slow_s: float = 900.0, noisy_share: float = 0.5,
                  noisy_min_requests: int = 20) -> None:
        with self._mu:
            self.enabled = bool(enable)
            rebuild = int(top_k) != self.top_k
            self.top_k = max(1, int(top_k))
            self.cardinality_cap = max(1, int(cardinality_cap))
            self.fast_s = max(0.25, float(fast_s))
            self.slow_s = max(self.fast_s, float(slow_s))
            self.noisy_share = min(1.0, max(1e-6, float(noisy_share)))
            self.noisy_min_requests = max(1, int(noisy_min_requests))
            # Slot granularity scales with the fast window so short
            # test/bench windows still resolve; the ring stays bounded
            # at ~(slow/gran) slots regardless of config.
            self._gran = min(5.0, max(0.25, self.fast_s / 4.0))
            if rebuild:
                self._sketches = {}
        # The usage_* label guard follows the SAME cap (metrics2
        # folds what this plane folds).
        from .metrics2 import METRICS2
        for name, label in (
                ("minio_tpu_v2_usage_requests_total", "bucket"),
                ("minio_tpu_v2_usage_rx_bytes_total", "bucket"),
                ("minio_tpu_v2_usage_tx_bytes_total", "bucket"),
                ("minio_tpu_v2_usage_errors_total", "bucket"),
                ("minio_tpu_v2_usage_shed_total", "bucket"),
                ("minio_tpu_v2_usage_tenant_requests_total", "tenant")):
            METRICS2.set_label_cap(name, label, self.cardinality_cap)

    # -- recording (one call per finished S3 request) -------------------

    def _slot(self, now: float) -> _Slot:
        """Current slot, rotating the ring (caller holds the lock)."""
        t0 = int(now / self._gran) * self._gran
        if not self._slots or self._slots[-1].t0 < t0:
            self._slots.append(_Slot(t0))
            lo = now - self.slow_s - self._gran
            while self._slots and self._slots[0].t0 < lo:
                self._slots.popleft()
        return self._slots[-1]

    def _fold(self, table: dict, name: str) -> str:
        if name in table or len(table) < self.cardinality_cap:
            return name
        self.folded_total += 1
        return OTHER

    def record(self, *, bucket: str, access_key: str, qos_class: str,
               rx: int, tx: int, status: int, shed: bool,
               key: str = "", client: str = "",
               duration_ms: float = 0.0, trace_id: str = "",
               now: float | None = None) -> None:
        if not self.enabled:
            return
        now = time.time() if now is None else now
        bucket = bucket or "-"
        tenant = access_key or "-"
        cls = qos_class or "read"
        err = status >= 500 and not shed
        with self._mu:
            slot = self._slot(now)
            bname = self._fold(slot.buckets, bucket)
            tname = self._fold(slot.tenants, tenant)
            for table, name in ((slot.buckets, bname),
                                (slot.tenants, tname)):
                row = table.get(name)
                if row is None:
                    row = table[name] = [0, 0, 0, 0, 0]
                row[_REQ] += 1
                row[_RX] += rx
                row[_TX] += tx
                if err:
                    row[_ERR] += 1
                if shed:
                    row[_SHED] += 1
            ctab = slot.classes.setdefault(cls, {})
            # bname/tname are post-fold, so this table is bounded at
            # 2 * cardinality_cap (+2 folds) entries by construction.
            for pref, name in (("b:", bname), ("t:", tname)):
                crow = ctab.get(pref + name)
                if crow is None:
                    crow = ctab[pref + name] = [0, 0]
                crow[0 if not shed else 1] += 1
            if trace_id and bname != OTHER:
                w = slot.worst.get(bname)
                if w is None or duration_ms > w[0]:
                    slot.worst[bname] = (duration_ms, trace_id)
            self._totals[_REQ] += 1
            self._totals[_RX] += rx
            self._totals[_TX] += tx
            if err:
                self._totals[_ERR] += 1
            if shed:
                self._totals[_SHED] += 1
            if key:
                sk = self._sketch("key", cls)
                sk.offer(f"{bucket}/{key}")
            if client:
                self._sketch("client", cls).offer(client)
        from .metrics2 import METRICS2
        METRICS2.inc("minio_tpu_v2_usage_requests_total",
                     {"bucket": bucket, "class": cls})
        # Tenant label REDACTED: the whole registry renders on the
        # unauthenticated /v2/metrics/node page, and raw access-key
        # ids must not be enumerable there (same policy as the /usage
        # endpoint; admin /top has the real names).
        METRICS2.inc("minio_tpu_v2_usage_tenant_requests_total",
                     {"tenant": _redact_name(tenant), "class": cls})
        if rx:
            METRICS2.inc("minio_tpu_v2_usage_rx_bytes_total",
                         {"bucket": bucket}, rx)
        if tx:
            METRICS2.inc("minio_tpu_v2_usage_tx_bytes_total",
                         {"bucket": bucket}, tx)
        if err:
            METRICS2.inc("minio_tpu_v2_usage_errors_total",
                         {"bucket": bucket})
        if shed:
            METRICS2.inc("minio_tpu_v2_usage_shed_total",
                         {"bucket": bucket})

    def _sketch(self, dim: str, cls: str) -> TopKSketch:
        sk = self._sketches.get((dim, cls))
        if sk is None:
            sk = self._sketches[(dim, cls)] = TopKSketch(self.top_k)
        return sk

    # -- window reads ---------------------------------------------------

    def _window_slots(self, window_s: float,
                      now: float) -> list[_Slot]:
        lo = now - window_s
        # A slot straddling the window edge counts whole: exactness at
        # slot granularity, the documented resolution of the accounts.
        return [s for s in self._slots if s.t0 + self._gran > lo
                and s.t0 <= now]

    def window_accounts(self, kind: str, window_s: float,
                        now: float | None = None) -> dict[str, dict]:
        """{name: {requests, rxBytes, txBytes, errors, shed}} for
        ``kind`` in ("buckets", "tenants") over the trailing window."""
        now = time.time() if now is None else now
        out: dict[str, list] = {}
        with self._mu:
            for slot in self._window_slots(window_s, now):
                for name, row in getattr(slot, kind).items():
                    acc = out.get(name)
                    if acc is None:
                        acc = out[name] = [0, 0, 0, 0, 0]
                    for i in range(5):
                        acc[i] += row[i]
        return {name: {"requests": a[_REQ], "rxBytes": a[_RX],
                       "txBytes": a[_TX], "errors": a[_ERR],
                       "shed": a[_SHED]}
                for name, a in out.items()}

    def class_shares(self, window_s: float,
                     now: float | None = None) -> dict[str, dict]:
        """Per QoS class over the window: total admitted/shed counts
        and the top bucket/tenant by each — the noisy-neighbor
        numerators.  ``_other`` never tops (a fold is not a tenant)."""
        now = time.time() if now is None else now
        agg: dict[str, dict[str, list]] = {}
        with self._mu:
            for slot in self._window_slots(window_s, now):
                for cls, tab in slot.classes.items():
                    cagg = agg.setdefault(cls, {})
                    for name, row in tab.items():
                        cur = cagg.get(name)
                        if cur is None:
                            cur = cagg[name] = [0, 0]
                        cur[0] += row[0]
                        cur[1] += row[1]
        out: dict[str, dict] = {}
        for cls, tab in agg.items():
            doc: dict = {"admitted": 0, "shed": 0}
            for pref, akey, skey in (("b:", "topBucket", "topShedBucket"),
                                     ("t:", "topTenant", "topShedTenant")):
                rows = [(name[len(pref):], row) for name, row
                        in tab.items() if name.startswith(pref)]
                adm = sum(r[0] for _, r in rows)
                shed = sum(r[1] for _, r in rows)
                if pref == "b:":
                    doc["admitted"], doc["shed"] = adm, shed
                # Distinct entities of this kind (a fold into _other
                # proves there were more): the noisy_neighbor rule
                # needs a NEIGHBOR before a dominant share means harm.
                # "-" (anonymous / bucket-less service requests) is
                # not an entity — counting it would let a genuinely
                # single-tenant box satisfy the >=2 gate.
                doc["bucketCount" if pref == "b:"
                    else "tenantCount"] = sum(
                    1 for n, _ in rows if n != "-")
                # _other (a fold) and "-" (anonymous / no credential)
                # are not NAMEABLE entities — a top rank must name
                # someone an operator can act on.
                named = [(n, r) for n, r in rows
                         if n not in (OTHER, "-")]
                if named and adm:
                    top = max(named, key=lambda x: x[1][0])
                    if top[1][0]:
                        doc[akey] = {"name": top[0],
                                     "count": top[1][0],
                                     "share": round(top[1][0] / adm, 4)}
                if named and shed:
                    stop = max(named, key=lambda x: x[1][1])
                    if stop[1][1]:
                        doc[skey] = {"name": stop[0],
                                     "count": stop[1][1],
                                     "share": round(stop[1][1] / shed,
                                                    4)}
            out[cls] = doc
        return out

    def class_top_shares(self, now: float | None = None) -> dict:
        """The timeline's per-sample census: {class: {name, share,
        kind}} for the fast window's top bucket per class."""
        out: dict = {}
        for cls, doc in self.class_shares(self.fast_s, now).items():
            top = doc.get("topBucket")
            if top is not None:
                out[cls] = {"kind": "bucket", "name": top["name"],
                            "share": top["share"]}
        return out

    # -- views ----------------------------------------------------------

    def snapshot(self) -> dict:
        now = time.time()
        with self._mu:
            sketches: dict[str, dict] = {}
            for (dim, cls), sk in self._sketches.items():
                sketches.setdefault(dim, {})[cls] = sk.snapshot()
            totals = list(self._totals)
            folded = self.folded_total
        return {
            "enabled": self.enabled,
            "nodes": 1,
            "topK": self.top_k,
            "cardinalityCap": self.cardinality_cap,
            "windows": {"fastS": self.fast_s, "slowS": self.slow_s},
            "totals": {"requests": totals[_REQ], "rxBytes": totals[_RX],
                       "txBytes": totals[_TX], "errors": totals[_ERR],
                       "shed": totals[_SHED]},
            "folded": folded,
            "buckets": {
                "fast": self.window_accounts("buckets", self.fast_s,
                                             now),
                "slow": self.window_accounts("buckets", self.slow_s,
                                             now)},
            "tenants": {
                "fast": self.window_accounts("tenants", self.fast_s,
                                             now),
                "slow": self.window_accounts("tenants", self.slow_s,
                                             now)},
            "classes": self.class_shares(self.fast_s, now),
            "sketches": sketches,
        }

    def top(self, n: int | None = None) -> dict:
        """Admin ``/top`` document: ranked buckets/tenants over the
        slow window with fast-window rates, per-class top-K keys and
        clients, worst-request trace-id exemplars per bucket."""
        now = time.time()
        n = n or self.top_k

        def ranked(kind: str) -> list[dict]:
            slow = self.window_accounts(kind, self.slow_s, now)
            fast = self.window_accounts(kind, self.fast_s, now)
            total = sum(v["requests"] for v in slow.values()) or 1
            rows = []
            for name, acc in slow.items():
                row = {"name": name, "share":
                       round(acc["requests"] / total, 4), **acc}
                f = fast.get(name)
                if f:
                    row["fastRequests"] = f["requests"]
                rows.append(row)
            rows.sort(key=lambda r: (-r["requests"], r["name"]))
            return rows[:n]

        buckets = ranked("buckets")
        with self._mu:
            worst: dict[str, tuple] = {}
            lo = now - self.slow_s - self._gran
            for slot in self._slots:
                if slot.t0 < lo:
                    continue
                for bname, w in slot.worst.items():
                    cur = worst.get(bname)
                    if cur is None or w[0] > cur[0]:
                        worst[bname] = w
            sketches: dict[str, dict] = {}
            for (dim, cls), sk in self._sketches.items():
                sketches.setdefault(dim, {})[cls] = sk.top(n)
        for row in buckets:
            w = worst.get(row["name"])
            if w is not None:
                row["worst"] = {"durationMs": round(w[0], 3),
                                "traceId": w[1]}
        return {"topK": n,
                "windows": {"fastS": self.fast_s, "slowS": self.slow_s},
                "buckets": buckets,
                "tenants": ranked("tenants"),
                "keys": sketches.get("key", {}),
                "clients": sketches.get("client", {})}

    def reset(self) -> None:
        with self._mu:
            self._slots.clear()
            self._sketches = {}
            self._totals = [0, 0, 0, 0, 0]
            self.folded_total = 0


# -- cluster merge ----------------------------------------------------------


def merge_usage(named_snaps: list[tuple[str, dict]]) -> dict:
    """Merge per-node usage snapshots into one cluster view: accounts
    sum per name, sketches merge (merge_topk), totals add — with an
    HONEST ``nodes`` count (only nodes that answered; the endpoint
    reports unreachable peers separately, so a lost node never reads
    as idle)."""
    snaps = [s for _, s in named_snaps
             if isinstance(s, dict) and "totals" in s]
    out: dict = {"nodes": len(snaps),
                 "topK": max([s.get("topK", 0) for s in snaps] or [0]),
                 "windows": (snaps[0].get("windows", {}) if snaps
                             else {}),
                 "totals": {"requests": 0, "rxBytes": 0, "txBytes": 0,
                            "errors": 0, "shed": 0},
                 "folded": 0,
                 "buckets": {"fast": {}, "slow": {}},
                 "tenants": {"fast": {}, "slow": {}},
                 "sketches": {}}
    for snap in snaps:
        for k, v in (snap.get("totals") or {}).items():
            out["totals"][k] = out["totals"].get(k, 0) + v
        out["folded"] += snap.get("folded", 0)
        for kind in ("buckets", "tenants"):
            for win in ("fast", "slow"):
                dst = out[kind][win]
                for name, acc in ((snap.get(kind) or {}).get(win)
                                  or {}).items():
                    cur = dst.setdefault(name, {})
                    for f, v in acc.items():
                        cur[f] = cur.get(f, 0) + v
    by_dim_cls: dict[str, dict[str, list]] = {}
    for snap in snaps:
        for dim, classes in (snap.get("sketches") or {}).items():
            for cls, sk in classes.items():
                by_dim_cls.setdefault(dim, {}).setdefault(
                    cls, []).append(sk)
    for dim, classes in by_dim_cls.items():
        out["sketches"][dim] = {
            cls: merge_topk(sks) for cls, sks in classes.items()}
    return out


# -- redaction for unauthenticated surfaces ---------------------------------


def _redact_name(name: str) -> str:
    """Short stable identity for access keys / client addresses on the
    UNAUTHENTICATED usage endpoints (same policy as drivemon's
    redacted_endpoint): enough to tell tenants apart and correlate
    with the root-only admin /top, without disclosing credentials or
    client topology to anonymous probes."""
    if name in (OTHER, "-", ""):
        return name
    digest = hashlib.sha256(name.encode("utf-8", "replace"))
    return f"{name[:2]}…#{digest.hexdigest()[:8]}"


def redact_usage(doc: dict) -> dict:
    """Copy of a usage snapshot (or cluster merge) with tenant names,
    client-sketch keys, and object-key tails redacted.  Bucket names
    stay: they already ride unauthenticated metric labels, like the
    reference's per-bucket Prometheus series."""
    out = dict(doc)
    tenants = doc.get("tenants")
    if isinstance(tenants, dict):
        out["tenants"] = {
            win: {_redact_name(name): acc for name, acc in accs.items()}
            for win, accs in tenants.items()}
    classes = doc.get("classes")
    if isinstance(classes, dict):
        red_classes = {}
        for cls, cdoc in classes.items():
            cdoc = dict(cdoc)
            for key in ("topTenant", "topShedTenant"):
                if isinstance(cdoc.get(key), dict):
                    cdoc[key] = dict(cdoc[key],
                                     name=_redact_name(
                                         cdoc[key].get("name", "")))
            red_classes[cls] = cdoc
        out["classes"] = red_classes
    sketches = doc.get("sketches")
    if isinstance(sketches, dict):
        red_sketches = dict(sketches)
        if "client" in sketches:
            red = {}
            for cls, sk in sketches["client"].items():
                sk = dict(sk)
                sk["counters"] = [
                    dict(c, key=_redact_name(c.get("key", "")))
                    for c in sk.get("counters", [])]
                sk.pop("cm", None)  # rows leak nothing; save bytes
                red[cls] = sk
            red_sketches["client"] = red
        if "key" in sketches:
            # Object-key names can embed user ids/filenames and never
            # ride metric labels — keep the bucket prefix (hot-bucket
            # shape stays readable), redact the key tail; admin /top
            # serves keys whole.
            red = {}
            for cls, sk in sketches["key"].items():
                sk = dict(sk)

                def _red_key(full: str) -> str:
                    bkt, sep, key = full.partition("/")
                    return bkt + sep + _redact_name(key) if sep \
                        else _redact_name(full)

                sk["counters"] = [
                    dict(c, key=_red_key(c.get("key", "")))
                    for c in sk.get("counters", [])]
                sk.pop("cm", None)
                red[cls] = sk
            red_sketches["key"] = red
        out["sketches"] = red_sketches
    return out


# The process-wide attribution plane the S3 front end records into.
USAGE = UsageAccountant()
